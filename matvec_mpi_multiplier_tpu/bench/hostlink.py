"""Host↔device link measurement and derived reference-mode timing.

The reference times its data distribution INSIDE the benchmark loop (quirk
Q5: ``README.md:42-44`` requires each repetition to start with data resident
only on the main process; the scatter at ``src/multiplier_rowwise.c:139`` is
inside the ``MPI_Wtime`` fences at ``:136-144``). On TPU that corresponds to
a host→HBM ``device_put`` every repetition — which on a *tunneled* backend is
exactly the operation whose interruption has been observed to wedge the
transport permanently (killed mid-transfer ``device_put`` → every later
``jax.devices()`` blocks forever).

This module provides the wedge-safe substitute: measure the host→device link
once with a bounded, monotonically-growing ladder of transfer sizes (no
kills, no deletes racing a transfer — each step fully completes before the
next starts), fit the classic latency/bandwidth line ``t(bytes) = α + β·b``,
and *derive* reference-mode rows from amortized measurements:

    t_reference(size) ≈ t_link(bytes(A) + bytes(x)) + t_amortized(size)

The derived rows carry ``mode="reference_derived"`` (own per-strategy CSV
file) and ``measure="derived"`` in the extended CSV, so they can never be
mistaken for — or averaged together with — literal per-rep measurements. On
backends
where the literal protocol is safe (CPU mesh, local chips) the existing
``mode="reference"`` path in timing.py remains the primary source; the two
agree to within the link model's fit error (asserted in tests on CPU).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Sequence

import jax
import numpy as np

from .timing import TimingResult, _fence

# Transfer ladder: 1 MB → 256 MB, ×4 per step. Bounded (max step well under
# HBM and host RAM), increasing (a failure mid-ladder loses the big steps,
# not the measurement), and spanning ~2.5 decades for a stable line fit.
DEFAULT_LADDER_BYTES = tuple(2**20 * 4**i for i in range(5))


@dataclasses.dataclass(frozen=True)
class LinkModel:
    """Fitted host→device transfer-time model ``t(bytes) = alpha_s + bytes/bps``."""

    alpha_s: float  # fixed per-transfer latency (dispatch + round-trip)
    bps: float      # asymptotic bandwidth, bytes/second
    samples: tuple[tuple[int, float], ...]  # (bytes, seconds) raw points

    def transfer_time_s(self, n_bytes: int) -> float:
        return self.alpha_s + n_bytes / self.bps

    @property
    def gbps(self) -> float:
        return self.bps / 1e9


def measure_link(
    ladder: Sequence[int] = DEFAULT_LADDER_BYTES,
    *,
    sharding=None,
    reps: int = 3,
    device=None,
) -> LinkModel:
    """Measure host→device placement time over a size ladder; fit (α, β).

    Every transfer runs to completion (fenced by a scalar fetch) before the
    next begins — the wedge-trigger pattern (killing a transfer mid-flight)
    cannot occur here by construction. ``reps`` per size, minimum kept (the
    transfer floor; interference only adds time).
    """
    from ..utils.errors import ConfigError

    ladder = [int(b) for b in ladder]
    if not ladder or any(b < 4 for b in ladder):
        raise ConfigError(
            f"measurement ladder must hold sizes >= 4 bytes, got {ladder}"
        )
    if reps < 1:
        raise ConfigError(f"reps must be >= 1, got {reps}")
    points: list[tuple[int, float]] = []
    for n_bytes in ladder:
        host = np.empty(n_bytes // 4, np.float32)
        host.fill(1.0)
        best = np.inf
        for _ in range(reps):
            start = time.perf_counter()
            if sharding is not None:
                arr = jax.device_put(host, sharding)
            elif device is not None:
                arr = jax.device_put(host, device)
            else:
                arr = jax.device_put(host)
            _fence(arr[:1])
            best = min(best, time.perf_counter() - start)
            # Drop the reference only after the transfer is provably complete
            # (fenced above): no delete ever races an in-flight transfer.
            del arr
        points.append((n_bytes, float(best)))

    xs = np.array([p[0] for p in points], np.float64)
    ys = np.array([p[1] for p in points], np.float64)
    if len(points) < 2:
        # One size cannot separate latency from bandwidth: attribute the
        # whole time to bandwidth (a conservative per-transfer estimate).
        slope, alpha = float(ys[0] / xs[0]), 0.0
    else:
        # Least-squares line, weighted by 1/bytes so the small-transfer
        # points pin alpha while the big ones pin the bandwidth slope.
        w = 1.0 / xs
        coeffs = np.polyfit(xs, ys, 1, w=np.sqrt(w))
        slope, alpha = float(coeffs[0]), float(coeffs[1])
    slope = max(slope, 1e-15)  # degenerate fit guard (instant transfers)
    return LinkModel(
        alpha_s=max(alpha, 0.0), bps=1.0 / slope, samples=tuple(points)
    )


def operand_bytes(result: TimingResult) -> int:
    """Bytes re-distributed per repetition in reference mode: A plus the
    right-hand side (x, or B for GEMM) — matching the reference's in-loop
    scatter+bcast payload (``src/multiplier_rowwise.c:16-47``)."""
    itemsize = 2 if result.dtype == "bfloat16" else np.dtype(result.dtype).itemsize
    return itemsize * (
        result.n_rows * result.n_cols + result.n_cols * result.n_rhs
    )


def derive_reference_result(
    amortized: TimingResult, link: LinkModel
) -> TimingResult:
    """Synthesize a reference-mode row from an amortized one + the link model.

    ``mode="reference_derived"`` with ``measure="derived"``: the per-rep time
    is the modeled host→device distribution of A and x plus the measured
    amortized compute time — the Q5-faithful quantity, computed without
    per-rep transfers on the live link. The distinct mode routes these rows
    to their own ``<strategy>_reference_derived.csv`` (bench/metrics.csv_path
    keys the filename on the mode), so modeled rows can never mix with
    literal ``mode="reference"`` measurements in one file — analysis
    averaging over a per-strategy CSV stays single-provenance.
    """
    if amortized.mode != "amortized":
        raise ValueError(
            f"derive_reference_result needs an amortized input, got "
            f"mode={amortized.mode!r}"
        )
    t = link.transfer_time_s(operand_bytes(amortized)) + amortized.mean_time_s
    return dataclasses.replace(
        amortized,
        mode="reference_derived",
        measure="derived",
        mean_time_s=t,
        times_s=(t,),
    )

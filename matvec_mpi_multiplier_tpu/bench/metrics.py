"""CSV metric emission.

Reference analog: component C8, the inline CSV bootstrap+append in each
``main`` (``src/multiplier_rowwise.c:77-88,159-170`` and colwise/blockwise
equivalents): per-strategy file ``./data/out/<strategy>.csv``, header row
``"n_rows, n_cols, n_processes, time"`` written once if the file is absent
(``:86``), then one appended row per run (``:168``) — append-only so re-runs
extend the sweep incrementally (the reference's only "resume" mechanism,
SURVEY.md §5.4).

Preserved exactly: the schema, the spaced header, the per-strategy filename,
append-only semantics. Fixed: the reference's fd leak in the existence probe
(quirk Q7 — ``fopen(..., "r")`` never closed, ``src/multiplier_rowwise.c:80``).
Added: an extended CSV with strategy/dtype/mode/throughput columns for the
TPU build's richer analysis.
"""

from __future__ import annotations

import os
from pathlib import Path

from ..parallel.distributed import is_main_process
from ..utils.constants import CSV_HEADER, CSV_HEADER_EXTENDED, OUT_SUBDIR
from ..utils.io import data_dir
from .timing import TimingResult


def out_dir(root: str | os.PathLike | None = None) -> Path:
    return data_dir(root) / OUT_SUBDIR


def csv_path(
    strategy: str, root: str | os.PathLike | None = None, mode: str = "amortized"
) -> Path:
    """Per-strategy CSV, the reference's ``./data/out/<strategy>.csv``.

    Reference-mode timings (host transfer in the timed region) land in a
    separate ``<strategy>_reference.csv``: the two modes differ by orders of
    magnitude and the reference schema has no column to tell them apart, so
    sharing a file would corrupt the SpeedUp/Efficiency averaging in
    analysis/stats.py. (The schema also cannot carry dtype — use the extended
    CSV for dtype-aware analysis.)
    """
    suffix = "" if mode == "amortized" else f"_{mode}"
    return out_dir(root) / f"{strategy}{suffix}.csv"


def extended_csv_path(root: str | os.PathLike | None = None) -> Path:
    return out_dir(root) / "results_extended.csv"


def _append_row(path: Path, header: str, row: str) -> None:
    """Append-only write with header-schema validation.

    A pre-existing file written under an older schema (e.g. the extended CSV
    before the ``measure`` column) must not silently receive rows misaligned
    with its header — it is rotated to ``<name>.bak`` (``.bak2`` … if taken)
    and a fresh file started under the current header.
    """
    path.parent.mkdir(parents=True, exist_ok=True)
    is_new = True
    if path.exists():
        with open(path) as f:
            existing = f.readline().rstrip("\n")
        if existing == header:
            is_new = False
        elif existing:  # non-empty stale header: rotate; empty file: reuse
            bak = path.with_suffix(path.suffix + ".bak")
            n = 2
            while bak.exists():
                bak = path.with_suffix(f"{path.suffix}.bak{n}")
                n += 1
            path.rename(bak)
    with open(path, "a") as f:
        if is_new:
            f.write(header + "\n")
        f.write(row + "\n")


def append_result(result: TimingResult, root: str | os.PathLike | None = None) -> Path:
    """Append one result in the reference schema (+ the extended CSV).

    Row format mirrors ``fprintf(..., "%ld, %ld, %d, %f\\n", ...)`` at
    ``src/multiplier_rowwise.c:168``: comma+space separated, time with 6
    decimal places. Multi-host: only the coordinator process writes — the
    reference's ``rank == MAIN_PROCESS`` guard around its CSV block
    (``src/multiplier_rowwise.c:159-170``); without it every process of a
    multi-host run would append a duplicate row.
    """
    path = csv_path(result.strategy, root, mode=result.mode)
    if not is_main_process():
        return path
    row = (
        f"{result.n_rows}, {result.n_cols}, {result.n_devices}, "
        f"{result.mean_time_s:.6f}"
    )
    _append_row(path, CSV_HEADER, row)

    ext_row = (
        f"{result.n_rows}, {result.n_cols}, {result.n_devices}, "
        f"{result.mean_time_s:.6f}, {result.strategy}, {result.dtype}, "
        f"{result.mode}, {result.measure}, {result.gflops:.4f}, "
        f"{result.gbps:.4f}, {result.n_rhs}"
    )
    _append_row(extended_csv_path(root), CSV_HEADER_EXTENDED, ext_row)
    return path


def read_csv(path: str | os.PathLike) -> list[dict]:
    """Parse a reference-schema or extended CSV into row dicts (numbers
    converted). Tolerates both the spaced header the reference's CODE
    writes (src/multiplier_rowwise.c:86 — the convention this module
    emits) and the no-space header of every CSV the reference actually
    COMMITTED (not just the asymmetric ones, as SURVEY quirk Q10 implies:
    its square files predate the committed source's fprintf too)."""
    path = Path(path)
    lines = [ln.strip() for ln in path.read_text().splitlines() if ln.strip()]
    if not lines:
        return []
    keys = [k.strip() for k in lines[0].split(",")]
    rows = []
    for ln in lines[1:]:
        vals = [v.strip() for v in ln.split(",")]
        row: dict = {}
        for k, v in zip(keys, vals):
            try:
                row[k] = int(v)
            except ValueError:
                try:
                    row[k] = float(v)
                except ValueError:
                    row[k] = v
        rows.append(row)
    return rows

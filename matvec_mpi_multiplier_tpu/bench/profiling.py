"""Profiling: JAX/XLA trace capture around benchmark regions.

Reference analog: §5.1 — the reference has no tracer; its only profiling is
the manual barrier/Wtime protocol (C9). The timing module reproduces that
protocol; this module adds the capability the reference lacked: on-device
traces (TensorBoard/Perfetto format) of the benchmark region, showing the
XLA fusion boundaries, collective schedule, and HBM traffic that the
wall-clock numbers summarize.
"""

from __future__ import annotations

import contextlib
import os
from pathlib import Path

import jax


@contextlib.contextmanager
def trace(log_dir: str | os.PathLike, *, enabled: bool = True):
    """Capture a device trace of the enclosed region into ``log_dir``.

    View with TensorBoard (profile plugin) or Perfetto. ``enabled=False``
    turns this into a no-op so call sites can thread a --profile flag
    through unconditionally.
    """
    if not enabled:
        yield None
        return
    log_dir = Path(log_dir)
    log_dir.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(log_dir)):
        yield log_dir


def annotate(name: str):
    """Named sub-region inside a trace (shows as a span in the viewer)."""
    return jax.profiler.TraceAnnotation(name)

"""Profiling: JAX/XLA trace capture around benchmark regions.

Reference analog: §5.1 — the reference has no tracer; its only profiling is
the manual barrier/Wtime protocol (C9). The timing module reproduces that
protocol; this module adds the capability the reference lacked: on-device
traces (TensorBoard/Perfetto format) of the benchmark region, showing the
XLA fusion boundaries, collective schedule, and HBM traffic that the
wall-clock numbers summarize.

Two annotation layers compose inside a :func:`trace` capture:

* :func:`annotate` — a host-side ``TraceAnnotation`` around a benchmark
  region (the sweep wraps each config in one);
* :func:`named_span` (re-exported from ``obs/annotations`` — the
  implementation lives there so ``parallel``/``models`` can use it without
  importing ``bench``) — trace-time spans INSIDE jitted programs: each
  strategy's local GEMV, each combine schedule, and each overlap stage
  (``stage{i}/compute`` / ``stage{i}/combine``). Off by default; enable
  with ``--annotate`` on the serve/sweep CLIs, ``MATVEC_ANNOTATE=1``, or
  :func:`set_annotations`. Capture recipe: ``docs/OBSERVABILITY.md``.
"""

from __future__ import annotations

import contextlib
import os
from pathlib import Path

import jax

from ..obs.annotations import (  # noqa: F401  (public re-exports)
    annotations,
    annotations_enabled,
    named_span,
    set_annotations,
)


@contextlib.contextmanager
def trace(log_dir: str | os.PathLike, *, enabled: bool = True):
    """Capture a device trace of the enclosed region into ``log_dir``.

    View with TensorBoard (profile plugin) or Perfetto. ``enabled=False``
    turns this into a no-op so call sites can thread a --profile flag
    through unconditionally.
    """
    if not enabled:
        yield None
        return
    log_dir = Path(log_dir)
    log_dir.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(log_dir)):
        yield log_dir


def annotate(name: str):
    """Named sub-region inside a trace (shows as a span in the viewer)."""
    return jax.profiler.TraceAnnotation(name)

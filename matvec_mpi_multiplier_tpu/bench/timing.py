"""Benchmark timing protocol.

Reference analog: component C9, the inline loop in each ``main``
(``src/multiplier_rowwise.c:135-151``, ``src/multiplier_colwise.c:218-233``,
``src/multiplier_blockwise.c:361-378``) and the protocol definition at
``README.md:41-52``:

* 100 repetitions (``:135``);
* per-rep fences: ``MPI_Barrier`` → ``MPI_Wtime`` → work → ``MPI_Barrier`` →
  ``MPI_Wtime`` (``:136-144``);
* per-run time = **max across ranks** (``MPI_Reduce(MPI_MAX)``, ``:147``);
* reported time = mean over repetitions (``:168``).

TPU-native mapping: the barrier+Wtime pair becomes ``block_until_ready`` +
``time.perf_counter``; max-across-ranks becomes a max over JAX processes (on a
single host there is one process, and within it XLA already synchronizes all
devices at ``block_until_ready``).

Two timing modes (SURVEY.md §7 hard part (i)):

* ``amortized`` — operands resident in HBM with their strategy sharding before
  the loop; measures the distributed matvec itself. The honest TPU number.
* ``reference`` — host→device placement of A and x is INSIDE the timed region
  every repetition, reproducing the reference's in-loop ``distribute_data``
  (quirk Q5: ``README.md:42-44`` requires timing to start with data preloaded
  on the main process only). On TPU this measures PCIe, and is reported so
  curves are comparable with the reference's.

Compilation is warmed up before the loop in both modes — the C reference has
no JIT, so including XLA compile time in rep 0 would measure nothing the
reference measures.

Three measurement methods:

* ``loop`` (amortized default) — the rep loop runs ON DEVICE: a
  ``lax.fori_loop`` of N dependent executions inside one jitted computation,
  timed between a single dispatch and a single fetch, for two different N;
  per-matvec time is the slope. One tunnel crossing per sample, so the
  ~0.4-0.5 ms per-enqueue transport cost of the tunneled backend — which
  swamped sub-millisecond kernels and made the round-1/2 small-size CSV rows
  non-monotonic — never touches the measurement (see :func:`_build_looped`
  for how dead-code elimination is prevented).
* ``chain`` — enqueue N executions back-to-back and time
  the whole chain between two device fetches, for two different N; the
  per-matvec time is the slope ``(T(N2) - T(N1)) / (N2 - N1)``. Device
  execution is stream-ordered, so one small fetch at the end fences the whole
  chain, and dispatch/transport latency cancels in the difference. This is
  robust on remote-tunneled backends where ``block_until_ready`` returns
  before execution completes and a fetch costs a large fixed round-trip
  (measured here: ~30-70 ms), and on local hardware it simply converges to
  the sync number.
* ``sync`` (reference-mode default) — the literal per-rep protocol: fence,
  start clock, run once, fence, stop clock. Matches the reference
  rep-by-rep; on tunneled backends each rep pays the round-trip, which is
  reported as-is (for mode="reference" that round-trip IS the host↔device
  distribution cost being measured).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.constants import DEFAULT_N_REPS
from ..utils.errors import ConfigError, TimingError

TIMING_MODES = ("amortized", "reference")
MEASURE_METHODS = ("auto", "loop", "chain", "sync")

# Independent chain-slope estimates per config; the reported time is their
# MEDIAN. 5 (not 3): on tunneled backends single slopes occasionally stall
# by orders of magnitude, and a median-of-5 still rejects two outliers.
DEFAULT_CHAIN_SAMPLES = 5


@dataclasses.dataclass(frozen=True)
class TimingResult:
    """One benchmark measurement (one CSV row)."""

    n_rows: int
    n_cols: int
    n_devices: int
    strategy: str
    dtype: str
    mode: str
    measure: str
    mean_time_s: float
    # 'sync': per-rep max-across-processes times (n_reps entries);
    # 'chain': independent slope estimates of the per-matvec time.
    times_s: tuple[float, ...]
    n_reps: int = DEFAULT_N_REPS
    # Columns of the right-hand side: 1 = matvec (y = A·x, the reference's
    # whole scope); >1 = GEMM (C = A @ B with B (n_cols, n_rhs)).
    n_rhs: int = 1

    @property
    def gflops(self) -> float:
        """Aggregate GFLOP/s: 2·m·k·n_rhs FLOPs (BASELINE.md formula at
        n_rhs=1)."""
        return (
            2.0 * self.n_rows * self.n_cols * self.n_rhs / self.mean_time_s / 1e9
        )

    @property
    def gbps(self) -> float:
        """Effective GB/s: one read of A and B(/x), one write of C(/y)."""
        itemsize = np.dtype(self.dtype).itemsize if self.dtype != "bfloat16" else 2
        elems = self.n_rows * self.n_cols + (self.n_rows + self.n_cols) * self.n_rhs
        return itemsize * elems / self.mean_time_s / 1e9

    @property
    def min_time_s(self) -> float:
        return min(self.times_s)


def _max_across_processes(value: float) -> float:
    """The MPI_Reduce(MPI_MAX) analog (src/multiplier_rowwise.c:147).

    With jax.distributed initialized (multi-host), take the max over
    processes; single-process runs return the local value unchanged.
    """
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    arr = multihost_utils.process_allgather(np.asarray(value))
    return float(np.max(arr))


def _fence(y) -> None:
    """Force completion of everything enqueued before ``y`` was produced.

    ``block_until_ready`` alone is not trusted (remote-tunneled PJRT backends
    have been observed returning early); fetching a scalar reduction of the
    result is an unambiguous completion fence because device programs execute
    in submission order.
    """
    np.asarray(jnp.sum(y))


def _build_looped(fn: Callable) -> Callable:
    """Wrap ``fn`` in a device-side rep loop: ONE dispatch runs ``k`` reps.

    The round-1/round-2 small-size CSV rows were non-monotonic because the
    host-driven chain dispatches each rep across the tunneled backend
    (~0.4-0.5 ms per enqueue), so for sub-millisecond kernels the chain slope
    measures dispatch, not compute. Here the rep loop is a ``lax.fori_loop``
    inside a single jitted computation: the tunnel is crossed once per
    timing sample and the device executes ``k`` back-to-back ops.

    The carry threads the right-hand side through every iteration with a
    runtime-zero bump, ``carry + eps * sum(out * out)``: ``eps`` is a traced
    runtime scalar (not a compile-time constant), so XLA cannot fold the
    bump away or dead-code-eliminate the op — while at runtime ``eps = 0``
    leaves the operand bit-identical every rep.

    The bump must be NONLINEAR in ``out``. A linear reduction like
    ``sum(out)`` is algebraically transparent: ``sum(A @ x)`` equals
    ``dot(colsum(A), x)``, and ``colsum(A)`` is loop-invariant, so XLA's
    simplifier + loop-invariant code motion turn every iteration into an
    O(n) vector dot — the loop then "measures" a matvec without ever
    re-reading the matrix (this produced fp32 rows at 2x the HBM peak).
    ``sum(out**2)`` (= x'A'Ax) admits no such factoring short of forming
    A'A, which XLA will not do, so every iteration must materialize ``out``
    and therefore stream the full matrix. The square is computed in at least
    float32 (never demoting fp64) to keep the (runtime-dead) bump value
    finite in low-precision dtypes.
    """

    def chained(a, rhs, k, eps):
        def body(_, carry):
            out = fn(a, carry)
            acc = jnp.promote_types(out.dtype, jnp.float32)
            bump = eps * jnp.sum(jnp.square(out.astype(acc)))
            return carry + bump.astype(carry.dtype)

        return jax.lax.fori_loop(0, k, body, rhs)

    return jax.jit(chained)


# Bounds for the adaptive rep-spread growth in _loop_slope. The tunneled
# backend's per-dispatch overhead is tens of milliseconds with multi-
# millisecond jitter; a slope over a spread whose device time is smaller than
# that jitter measures noise, not the kernel (the round-1/2 physically
# impossible CSV rows — e.g. fp32 matvec "bandwidths" 2x the HBM peak — were
# exactly this). The spread therefore grows until the endpoint-time delta
# dominates the measured dispatch overhead.
_LOOP_REP_CAP = 1_000_000
_LOOP_MAX_RUN_S = 2.0
_LOOP_TARGET_FLOOR_S = 0.005
_LOOP_JITTER_FACTOR = 3.0


def _min2(run: Callable[[int], float], k: int) -> float:
    """Min of two runs at ``k`` — min filters dispatch-latency spikes, the
    dominant noise over a tunneled backend.

    Both runs are always taken, even when the first already exceeds the
    growth cap: a single dispatch spike masquerading as a heavy run would
    otherwise halt ``_grow_spread`` at a jitter-dominated spread (the
    garbage-CSV failure mode). The repeat is bounded — for a genuinely heavy
    kernel it doubles only the one probe at which growth stops anyway."""
    return min(run(k), run(k))


def _grow_spread(
    run: Callable[[int], float], n1: int, delta: int, *,
    target_delta_s: float, rep_cap: int = _LOOP_REP_CAP,
    max_run_s: float = _LOOP_MAX_RUN_S,
) -> tuple[int, float, float]:
    """Widen the rep spread until the timing signal beats dispatch jitter.

    Returns ``(delta, t1, t2)`` — the chosen spread plus the min-of-2 endpoint
    times measured at it (reusable as the first slope sample). Growth is
    driven by *measured* run times, never by an extrapolated per-rep estimate,
    so a misestimate can never request an unboundedly long run: expansion
    stops as soon as the endpoint delta reaches ``target_delta_s``, a single
    run reaches ``max_run_s``, or the spread reaches ``rep_cap``.

    Each endpoint is unconditionally the min of two runs (``_min2``): a lone
    dispatch spike must never be able to satisfy the ``max_run_s`` stop
    condition and halt growth at a jitter-dominated spread.
    """
    if delta <= 0:
        raise ConfigError(f"rep spread must be positive, got {delta}")
    t1 = _min2(run, n1)
    while True:
        t2 = _min2(run, n1 + delta)
        if t2 - t1 >= target_delta_s or t2 >= max_run_s or delta >= rep_cap:
            return delta, t1, t2
        delta = min(delta * 4, rep_cap)


def _dispatch_overhead(run: Callable[[int], float]) -> tuple[float, float]:
    """Dispatch+fence overhead estimate from k=1 and k=2 runs.

    Returns ``(pure, t_k1)``. A k=1 run contains one full kernel
    execution, so ``pure`` subtracts the (k=2 − k=1) one-rep estimate —
    otherwise a kernel whose single rep rivals the dispatch overhead
    inflates the jitter target (and with it every run in the spread
    search) by its own runtime for no signal gain. The subtraction can
    UNDER-estimate when a latency burst spans both k=2 runs (min-of-2 only
    filters independent spikes), which is why ``t_k1`` — the conservative
    estimate that can only overestimate — is returned alongside: callers
    floor their jitter target at it, so a burst can cost wall-time but
    can never collapse the anti-jitter guard.
    """
    t_k1 = _min2(run, 1)
    t_k2 = _min2(run, 2)
    return max(0.0, t_k1 - max(0.0, t_k2 - t_k1)), t_k1


def _loop_slope(
    fn: Callable, a_dev, rhs_dev, n1: int, n2: int, samples: int,
    warmup: int = 0,
) -> list[float]:
    """Per-execution time as the slope between device-looped runs of n1 and
    n2 reps (one dispatch each); the single dispatch+fence overhead cancels
    in the difference just as in :func:`_chain_slope`.

    The requested spread ``n2 - n1`` is a lower bound: it is adaptively
    widened (``_grow_spread``) until the endpoint-time difference is at least
    ``_LOOP_JITTER_FACTOR`` x the measured post-compile dispatch overhead,
    floored at the one-rep run time and ``_LOOP_TARGET_FLOOR_S``, and each
    endpoint is the min of two runs —
    otherwise, over a high-latency tunnel, the slope measures dispatch jitter
    rather than the kernel. The overhead is *measured* (a post-compile k=1
    run), so the same code self-calibrates on fast local backends (sub-ms
    dispatch → small spreads) and the tunneled TPU (~70 ms dispatch → spreads
    sized to drown it).

    ``warmup``: extra fenced n1-length runs after the compile — a cold
    process under-reports bandwidth on its first runs (clock ramp / cold
    caches), so headline callers warm for a few."""
    if samples < 1:
        raise ConfigError(f"chain_samples must be >= 1, got {samples}")
    chained = _build_looped(fn)
    eps = jnp.asarray(0.0, jnp.float32)

    def run(k: int) -> float:
        start = time.perf_counter()
        y = chained(a_dev, rhs_dev, jnp.asarray(k, jnp.int32), eps)
        _fence(y)
        # Max-reduce at the SOURCE, not just on the final estimates: every
        # control-flow decision below (growth stops, the TimingError raise)
        # must be identical on every process, or a multi-host run would
        # issue divergent dispatch counts of the same sharded program and
        # deadlock. Max across processes is also the reference's per-run
        # semantics (MPI_Reduce(MPI_MAX), src/multiplier_rowwise.c:147).
        # Single-process (the common case) returns the local value untouched.
        return _max_across_processes(time.perf_counter() - start)

    run(1)  # compile (k is traced: one compile covers every k)
    t_dispatch, t_k1 = _dispatch_overhead(run)
    for _ in range(max(0, warmup)):
        run(n1)
    # Jitter margin on the PURE dispatch estimate, floored at t_k1
    # (dispatch + one rep, un-multiplied). The two terms cover different
    # failure modes: 3x t_dispatch drowns dispatch jitter without tripling
    # wall-time for rep-dominated kernels (whose t_k1 >> t_dispatch — the
    # round-3 wall-time finding, pinned by
    # test_dispatch_overhead_subtracts_one_rep); the t_k1 floor preserves
    # the dispatch+one-rep SCALE (not the old 3x-of-it target) when a
    # correlated burst fools the one-rep subtraction and t_dispatch
    # collapses toward zero — a weaker margin than 3x in that regime, paid
    # for by the min-of-2 endpoints and the non-positive-median TimingError
    # downstream.
    target = max(
        _LOOP_TARGET_FLOOR_S, _LOOP_JITTER_FACTOR * t_dispatch, t_k1
    )
    delta, t1, t2 = _grow_spread(run, n1, n2 - n1, target_delta_s=target)
    n2 = n1 + delta
    estimates = [(t2 - t1) / delta]
    while len(estimates) < samples:
        t1 = _min2(run, n1)
        t2 = _min2(run, n2)
        estimates.append((t2 - t1) / delta)
    # No clamping: a non-positive slope means jitter beat the signal — a
    # clamped value would reach the CSV as an absurd-but-finite row (the
    # round-1/2 failure mode). Individual negative samples are tolerated as
    # visible noise, but a non-positive MEDIAN is a failed measurement.
    if float(np.median(estimates)) <= 0.0:
        raise TimingError(
            f"device-looped slope not measurable: median of {samples} "
            f"samples at spread {delta} reps is <= 0 against a "
            f"{t_dispatch * 1e3:.1f} ms dispatch overhead — the backend is "
            "too noisy at this spread (growth stops at "
            f"{_LOOP_MAX_RUN_S:.0f} s/run or {_LOOP_REP_CAP} reps); retry "
            "when the backend is quieter"
        )
    return estimates


def time_fn_looped(
    fn: Callable, args: tuple, *, n_reps: int = DEFAULT_N_REPS,
    samples: int = DEFAULT_CHAIN_SAMPLES, warmup: int = 1,
) -> list[float]:
    """Device-looped slope timing of an arbitrary device function on
    device-resident args (the ``measure='loop'`` face of
    :func:`time_fn_chained`): one dispatch per sample instead of one per
    rep, so per-dispatch transport cost on tunneled backends never touches
    the estimate. Used by bench.py with device-side operand generation."""
    a_dev, rhs_dev = args
    n1 = max(1, n_reps // 10)
    # Estimates are already max-reduced across processes at the source
    # (inside _loop_slope's run), so no re-reduction here.
    return _loop_slope(
        fn, a_dev, rhs_dev, n1, n1 + n_reps, samples, warmup=warmup
    )


def _chain_slope(run_once: Callable[[], object], n1: int, n2: int, samples: int) -> list[float]:
    """Per-execution time as the slope between chains of n1 and n2 runs."""
    if samples < 1:
        raise ConfigError(f"chain_samples must be >= 1, got {samples}")

    def chain(n: int) -> float:
        start = time.perf_counter()
        y = None
        for _ in range(n):
            y = run_once()
        _fence(y)
        # Max-reduced at the source so the TimingError decision below is
        # identical on every process (see the matching comment in
        # _loop_slope; a divergent raise would strand the other processes
        # in their next collective).
        return _max_across_processes(time.perf_counter() - start)

    estimates = []
    for _ in range(samples):
        t1 = chain(n1)
        t2 = chain(n2)
        estimates.append((t2 - t1) / (n2 - n1))
    # Same doctrine as _loop_slope: host-timer noise can drive individual
    # slopes negative (tolerated, visible), but a non-positive MEDIAN means
    # the chain spread carries no signal — raise rather than clamp to a
    # value that would reach the CSV as an absurd-but-finite row.
    if float(np.median(estimates)) <= 0.0:
        raise TimingError(
            f"chain slope not measurable: median of {samples} samples over "
            f"a {n2 - n1}-rep spread is <= 0 — the kernel is too fast for "
            "host-driven chaining here; use measure='loop'"
        )
    return estimates


def time_fn_chained(
    fn: Callable, args: tuple, *, n_reps: int = DEFAULT_N_REPS,
    samples: int = DEFAULT_CHAIN_SAMPLES, warmup: int = 1,
) -> list[float]:
    """Chain-slope timing of an arbitrary device function on device-resident
    args (no host placement). Used by bench.py with device-side operand
    generation so multi-GB operands never cross the host link.

    ``warmup`` extra fenced executions run after the compile: a cold process
    measurably under-reports bandwidth on its first chains (clock ramp /
    cold caches), so headline callers should warm for a few runs.
    """
    y = fn(*args)
    for _ in range(max(0, warmup)):
        y = fn(*args)
    _fence(y)
    n1 = max(1, n_reps // 10)
    # Estimates are already max-reduced across processes at the source
    # (inside _chain_slope's chain), so no re-reduction here.
    return _chain_slope(lambda: fn(*args), n1, n1 + n_reps, samples)


def resolve_measure(mode: str, measure: str) -> str:
    """Validate (mode, measure) and resolve 'auto' to a concrete method."""
    if mode not in TIMING_MODES:
        raise ConfigError(f"mode must be one of {TIMING_MODES}, got {mode!r}")
    if measure not in MEASURE_METHODS:
        raise ConfigError(
            f"measure must be one of {MEASURE_METHODS}, got {measure!r}"
        )
    if measure == "auto":
        # Device-looped reps for amortized (immune to per-dispatch tunnel
        # overhead — the round-1/2 non-monotonic-CSV failure mode); literal
        # per-rep protocol for reference mode, whose point is the transfer.
        measure = "loop" if mode == "amortized" else "sync"
    if mode == "reference" and measure in ("chain", "loop"):
        raise ConfigError(
            f"measure={measure!r} cannot time mode='reference': the per-rep "
            "host->device transfer is the thing being measured and cannot "
            "ride a device-side execution chain; use measure='sync'"
        )
    return measure


def time_matvec(
    fn: Callable,
    a,
    x,
    *,
    shardings=None,
    n_reps: int = DEFAULT_N_REPS,
    mode: str = "amortized",
    measure: str = "auto",
    chain_samples: int = DEFAULT_CHAIN_SAMPLES,
) -> list[float]:
    """Run the reference timing protocol around ``fn(a, x)``.

    ``a``/``x`` are host (numpy) arrays; ``shardings`` is the (A, x) pair of
    NamedShardings from ``strategy.shardings(mesh)`` (None → default
    placement). Returns per-measurement max-across-processes times in seconds
    (see module docstring for the two measurement methods).
    """
    measure = resolve_measure(mode, measure)
    if n_reps < 1:
        raise ConfigError(f"n_reps must be >= 1, got {n_reps}")
    sh_a, sh_x = shardings if shardings is not None else (None, None)

    def place(arr, sh):
        return jax.device_put(arr, sh)

    # Warm-up: compile + one run, outside the timed region (the C reference
    # pays no compile cost; see module docstring). measure='loop' compiles
    # and warms its own wrapped program inside _loop_slope — compiling the
    # bare fn here too would double per-config compile cost for nothing.
    a_dev, x_dev = place(a, sh_a), place(x, sh_x)
    if measure != "loop":
        _fence(fn(a_dev, x_dev))

    if mode == "amortized" and measure in ("chain", "loop"):
        n1 = max(1, n_reps // 10)
        n2 = n1 + n_reps
        # Slope estimates are max-reduced across processes at the source
        # (inside _loop_slope/_chain_slope), so no re-reduction here.
        if measure == "loop":
            return _loop_slope(fn, a_dev, x_dev, n1, n2, chain_samples)
        return _chain_slope(lambda: fn(a_dev, x_dev), n1, n2, chain_samples)

    times: list[float] = []
    for _ in range(n_reps):
        if mode == "reference":
            # Host→device distribution inside the timed region (quirk Q5).
            # Delete device copies first so device_put really transfers.
            # (Leaf-wise: a quantized-storage A is a pytree of buffers.)
            for leaf in jax.tree_util.tree_leaves(a_dev):
                leaf.delete()
            x_dev.delete()
            start = time.perf_counter()
            a_dev = place(a, sh_a)
            x_dev = place(x, sh_x)
            _fence(fn(a_dev, x_dev))
            elapsed = time.perf_counter() - start
        else:
            start = time.perf_counter()
            _fence(fn(a_dev, x_dev))
            elapsed = time.perf_counter() - start
        times.append(_max_across_processes(elapsed))
    return times


def _run_benchmark(
    *,
    fn: Callable,
    a: np.ndarray,
    rhs: np.ndarray,
    shardings,
    mesh,
    strategy_name: str,
    n_rhs: int,
    n_reps: int,
    mode: str,
    measure: str,
    chain_samples: int = DEFAULT_CHAIN_SAMPLES,
) -> TimingResult:
    """The shared protocol body behind :func:`benchmark_strategy` and
    :func:`benchmark_gemm`: time the built fn and assemble the result —
    one place, so matvec and GEMM rows in the shared extended CSV are always
    measured under the identical protocol.

    Reported time: **mean** over the per-rep times for ``sync`` (the
    reference's own protocol, ``src/multiplier_rowwise.c:168``) but
    **median** over slope estimates for ``chain``/``loop`` — each sample is
    an independent estimate of the same per-matvec time, and on tunneled
    backends a single stalled sample can be off by orders of magnitude (the
    round-1 small-size CSVs were non-monotonic for exactly this reason); the
    median rejects it where the mean absorbs it.
    """
    times = time_matvec(
        fn, a, rhs, shardings=shardings, n_reps=n_reps, mode=mode,
        measure=measure, chain_samples=chain_samples,
    )
    reported = (
        np.median(times) if measure in ("chain", "loop") else np.mean(times)
    )
    return TimingResult(
        n_rows=a.shape[0],
        n_cols=a.shape[1],
        n_devices=int(mesh.devices.size),
        strategy=strategy_name,
        dtype=str(a.dtype),
        mode=mode,
        measure=measure,
        mean_time_s=float(reported),
        times_s=tuple(times),
        n_reps=n_reps,
        n_rhs=n_rhs,
    )


def _prepare_operands(
    a: np.ndarray, rhs: np.ndarray, dtype: str | None
) -> tuple[np.ndarray, np.ndarray]:
    if dtype is not None:
        a = a.astype(dtype)
        rhs = rhs.astype(dtype)
    if a.dtype == np.float64 and not jax.config.jax_enable_x64:
        # Without x64, JAX silently downcasts fp64 operands to fp32 while
        # TimingResult would still record 'float64' — mislabeled results.
        jax.config.update("jax_enable_x64", True)
    return a, rhs


def benchmark_strategy(
    strategy,
    mesh,
    a: np.ndarray,
    x: np.ndarray,
    *,
    dtype: str | None = None,
    n_reps: int = DEFAULT_N_REPS,
    mode: str = "amortized",
    measure: str = "auto",
    kernel: str | Callable = "xla",
    gather_output: bool = True,
    chain_samples: int = DEFAULT_CHAIN_SAMPLES,
    combine: str | None = None,
    stages: int | str | None = None,
    dtype_storage: str | None = None,
) -> TimingResult:
    """Benchmark one (strategy, mesh, size) configuration — the body of the
    reference's per-config run (``src/multiplier_rowwise.c:54-176``) minus the
    CSV write (see bench.metrics).

    ``combine`` selects the combine schedule by name (``"auto"`` consults
    the tuning cache) and ``stages`` pins the staged ``overlap`` schedules'
    stage count — see ``MatvecStrategy.build``. ``dtype_storage`` measures
    the quantized-residency path: A is quantized host-side (outside the
    timed region, like any operand prep) and the strategy runs against the
    payload pytree."""
    measure = resolve_measure(mode, measure)
    a, x = _prepare_operands(a, x, dtype)
    strategy.validate(a.shape[0], a.shape[1], mesh)
    fn = strategy.build(
        mesh, kernel=kernel, gather_output=gather_output, combine=combine,
        stages=stages, dtype_storage=dtype_storage,
    )
    a = _maybe_quantize(a, dtype_storage, strategy, mesh)
    return _run_benchmark(
        fn=fn, a=a, rhs=x, shardings=strategy.shardings(mesh), mesh=mesh,
        strategy_name=strategy.name, n_rhs=1, n_reps=n_reps, mode=mode,
        measure=measure, chain_samples=chain_samples,
    )


def _maybe_quantize(a, dtype_storage, strategy, mesh):
    """Quantize the benchmark operand when a storage format is requested
    (ops/quantize.py; the once-at-residency step, here once-per-config)."""
    from ..ops.quantize import NATIVE, normalize_storage, quantize_matrix

    if normalize_storage(dtype_storage) == NATIVE:
        return a
    return quantize_matrix(
        a, dtype_storage,
        contraction_shards=strategy.contraction_shards(mesh),
    )


def benchmark_gemm(
    name: str,
    mesh,
    a: np.ndarray,
    b: np.ndarray,
    *,
    dtype: str | None = None,
    n_reps: int = DEFAULT_N_REPS,
    mode: str = "amortized",
    measure: str = "auto",
    kernel: str | Callable = "xla",
    gather_output: bool = True,
    chain_samples: int = DEFAULT_CHAIN_SAMPLES,
    combine: str | None = None,
    stages: int | str | None = None,
    dtype_storage: str | None = None,
) -> TimingResult:
    """Benchmark one GEMM (strategy, mesh, size) configuration.

    Same protocol as :func:`benchmark_strategy` with a rank-2 right-hand
    side; the result's strategy is recorded as ``gemm_<name>`` so GEMM rows
    land in their own per-strategy CSVs (the reference schema has no op
    column to tell matvec and GEMM apart).

    ``combine`` selects the combine schedule by name (``"auto"`` consults
    the tuning cache under ``op="gemm"``), ``stages`` the staged
    ``overlap`` stage count, and ``dtype_storage`` the quantized-residency
    path — see ``build_gemm`` / :func:`benchmark_strategy`.
    """
    from ..models import get_strategy
    from ..models.gemm import build_gemm, gemm_shardings, validate_gemm

    measure = resolve_measure(mode, measure)
    a, b = _prepare_operands(a, b, dtype)
    validate_gemm(name, a.shape[0], a.shape[1], b.shape[1], mesh)
    fn = build_gemm(
        name, mesh, kernel=kernel, gather_output=gather_output,
        combine=combine, stages=stages, dtype_storage=dtype_storage,
    )
    a = _maybe_quantize(a, dtype_storage, get_strategy(name), mesh)
    return _run_benchmark(
        fn=fn, a=a, rhs=b, shardings=gemm_shardings(name, mesh), mesh=mesh,
        strategy_name=f"gemm_{name}", n_rhs=b.shape[1], n_reps=n_reps,
        mode=mode, measure=measure, chain_samples=chain_samples,
    )

"""Benchmark timing protocol.

Reference analog: component C9, the inline loop in each ``main``
(``src/multiplier_rowwise.c:135-151``, ``src/multiplier_colwise.c:218-233``,
``src/multiplier_blockwise.c:361-378``) and the protocol definition at
``README.md:41-52``:

* 100 repetitions (``:135``);
* per-rep fences: ``MPI_Barrier`` → ``MPI_Wtime`` → work → ``MPI_Barrier`` →
  ``MPI_Wtime`` (``:136-144``);
* per-run time = **max across ranks** (``MPI_Reduce(MPI_MAX)``, ``:147``);
* reported time = mean over repetitions (``:168``).

TPU-native mapping: the barrier+Wtime pair becomes ``block_until_ready`` +
``time.perf_counter``; max-across-ranks becomes a max over JAX processes (on a
single host there is one process, and within it XLA already synchronizes all
devices at ``block_until_ready``).

Two timing modes (SURVEY.md §7 hard part (i)):

* ``amortized`` — operands resident in HBM with their strategy sharding before
  the loop; measures the distributed matvec itself. The honest TPU number.
* ``reference`` — host→device placement of A and x is INSIDE the timed region
  every repetition, reproducing the reference's in-loop ``distribute_data``
  (quirk Q5: ``README.md:42-44`` requires timing to start with data preloaded
  on the main process only). On TPU this measures PCIe, and is reported so
  curves are comparable with the reference's.

Compilation is warmed up before the loop in both modes — the C reference has
no JIT, so including XLA compile time in rep 0 would measure nothing the
reference measures.

Three measurement methods:

* ``loop`` (amortized default) — the rep loop runs ON DEVICE: a
  ``lax.fori_loop`` of N dependent executions inside one jitted computation,
  timed between a single dispatch and a single fetch, for two different N;
  per-matvec time is the slope. One tunnel crossing per sample, so the
  ~0.4-0.5 ms per-enqueue transport cost of the tunneled backend — which
  swamped sub-millisecond kernels and made the round-1/2 small-size CSV rows
  non-monotonic — never touches the measurement (see :func:`_build_looped`
  for how dead-code elimination is prevented).
* ``chain`` — enqueue N executions back-to-back and time
  the whole chain between two device fetches, for two different N; the
  per-matvec time is the slope ``(T(N2) - T(N1)) / (N2 - N1)``. Device
  execution is stream-ordered, so one small fetch at the end fences the whole
  chain, and dispatch/transport latency cancels in the difference. This is
  robust on remote-tunneled backends where ``block_until_ready`` returns
  before execution completes and a fetch costs a large fixed round-trip
  (measured here: ~30-70 ms), and on local hardware it simply converges to
  the sync number.
* ``sync`` (reference-mode default) — the literal per-rep protocol: fence,
  start clock, run once, fence, stop clock. Matches the reference
  rep-by-rep; on tunneled backends each rep pays the round-trip, which is
  reported as-is (for mode="reference" that round-trip IS the host↔device
  distribution cost being measured).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.constants import DEFAULT_N_REPS
from ..utils.errors import ConfigError

TIMING_MODES = ("amortized", "reference")
MEASURE_METHODS = ("auto", "loop", "chain", "sync")

# Independent chain-slope estimates per config; the reported time is their
# MEDIAN. 5 (not 3): on tunneled backends single slopes occasionally stall
# by orders of magnitude, and a median-of-5 still rejects two outliers.
DEFAULT_CHAIN_SAMPLES = 5


@dataclasses.dataclass(frozen=True)
class TimingResult:
    """One benchmark measurement (one CSV row)."""

    n_rows: int
    n_cols: int
    n_devices: int
    strategy: str
    dtype: str
    mode: str
    measure: str
    mean_time_s: float
    # 'sync': per-rep max-across-processes times (n_reps entries);
    # 'chain': independent slope estimates of the per-matvec time.
    times_s: tuple[float, ...]
    n_reps: int = DEFAULT_N_REPS
    # Columns of the right-hand side: 1 = matvec (y = A·x, the reference's
    # whole scope); >1 = GEMM (C = A @ B with B (n_cols, n_rhs)).
    n_rhs: int = 1

    @property
    def gflops(self) -> float:
        """Aggregate GFLOP/s: 2·m·k·n_rhs FLOPs (BASELINE.md formula at
        n_rhs=1)."""
        return (
            2.0 * self.n_rows * self.n_cols * self.n_rhs / self.mean_time_s / 1e9
        )

    @property
    def gbps(self) -> float:
        """Effective GB/s: one read of A and B(/x), one write of C(/y)."""
        itemsize = np.dtype(self.dtype).itemsize if self.dtype != "bfloat16" else 2
        elems = self.n_rows * self.n_cols + (self.n_rows + self.n_cols) * self.n_rhs
        return itemsize * elems / self.mean_time_s / 1e9

    @property
    def min_time_s(self) -> float:
        return min(self.times_s)


def _max_across_processes(value: float) -> float:
    """The MPI_Reduce(MPI_MAX) analog (src/multiplier_rowwise.c:147).

    With jax.distributed initialized (multi-host), take the max over
    processes; single-process runs return the local value unchanged.
    """
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    arr = multihost_utils.process_allgather(np.asarray(value))
    return float(np.max(arr))


def _fence(y) -> None:
    """Force completion of everything enqueued before ``y`` was produced.

    ``block_until_ready`` alone is not trusted (remote-tunneled PJRT backends
    have been observed returning early); fetching a scalar reduction of the
    result is an unambiguous completion fence because device programs execute
    in submission order.
    """
    np.asarray(jnp.sum(y))


def _build_looped(fn: Callable) -> Callable:
    """Wrap ``fn`` in a device-side rep loop: ONE dispatch runs ``k`` reps.

    The round-1/round-2 small-size CSV rows were non-monotonic because the
    host-driven chain dispatches each rep across the tunneled backend
    (~0.4-0.5 ms per enqueue), so for sub-millisecond kernels the chain slope
    measures dispatch, not compute. Here the rep loop is a ``lax.fori_loop``
    inside a single jitted computation: the tunnel is crossed once per
    timing sample and the device executes ``k`` back-to-back ops.

    The carry threads the right-hand side through every iteration with a
    runtime-zero bump, ``carry + eps * sum(out)``: ``eps`` is a traced
    runtime scalar (not a compile-time constant), so XLA cannot fold the
    bump away, dead-code-eliminate the op, or hoist it out of the loop —
    while at runtime ``eps = 0`` leaves the operand bit-identical every rep.
    """

    def chained(a, rhs, k, eps):
        def body(_, carry):
            out = fn(a, carry)
            return carry + (eps * jnp.sum(out)).astype(carry.dtype)

        return jax.lax.fori_loop(0, k, body, rhs)

    return jax.jit(chained)


def _loop_slope(
    fn: Callable, a_dev, rhs_dev, n1: int, n2: int, samples: int,
    warmup: int = 0,
) -> list[float]:
    """Per-execution time as the slope between device-looped runs of n1 and
    n2 reps (one dispatch each); the single dispatch+fence overhead cancels
    in the difference just as in :func:`_chain_slope`.

    ``warmup``: extra fenced n1-length runs after the compile — a cold
    process under-reports bandwidth on its first runs (clock ramp / cold
    caches), so headline callers warm for a few."""
    if samples < 1:
        raise ConfigError(f"chain_samples must be >= 1, got {samples}")
    chained = _build_looped(fn)
    eps = jnp.asarray(0.0, jnp.float32)

    def run(k: int) -> float:
        start = time.perf_counter()
        y = chained(a_dev, rhs_dev, jnp.asarray(k, jnp.int32), eps)
        _fence(y)
        return time.perf_counter() - start

    run(1)  # compile (k is traced: one compile covers every k)
    for _ in range(max(0, warmup)):
        run(n1)
    estimates = []
    for _ in range(samples):
        t1 = run(n1)
        t2 = run(n2)
        estimates.append(max((t2 - t1) / (n2 - n1), 1e-9))
    return estimates


def time_fn_looped(
    fn: Callable, args: tuple, *, n_reps: int = DEFAULT_N_REPS,
    samples: int = DEFAULT_CHAIN_SAMPLES, warmup: int = 1,
) -> list[float]:
    """Device-looped slope timing of an arbitrary device function on
    device-resident args (the ``measure='loop'`` face of
    :func:`time_fn_chained`): one dispatch per sample instead of one per
    rep, so per-dispatch transport cost on tunneled backends never touches
    the estimate. Used by bench.py with device-side operand generation."""
    a_dev, rhs_dev = args
    n1 = max(1, n_reps // 10)
    per = _loop_slope(
        fn, a_dev, rhs_dev, n1, n1 + n_reps, samples, warmup=warmup
    )
    return [_max_across_processes(t) for t in per]


def _chain_slope(run_once: Callable[[], object], n1: int, n2: int, samples: int) -> list[float]:
    """Per-execution time as the slope between chains of n1 and n2 runs."""
    if samples < 1:
        raise ConfigError(f"chain_samples must be >= 1, got {samples}")

    def chain(n: int) -> float:
        start = time.perf_counter()
        y = None
        for _ in range(n):
            y = run_once()
        _fence(y)
        return time.perf_counter() - start

    estimates = []
    for _ in range(samples):
        t1 = chain(n1)
        t2 = chain(n2)
        # Clamp: host-timer noise can make t2 < t1 for sub-microsecond
        # kernels; keep estimates positive so derived GB/s stays finite.
        estimates.append(max((t2 - t1) / (n2 - n1), 1e-9))
    return estimates


def time_fn_chained(
    fn: Callable, args: tuple, *, n_reps: int = DEFAULT_N_REPS,
    samples: int = DEFAULT_CHAIN_SAMPLES, warmup: int = 1,
) -> list[float]:
    """Chain-slope timing of an arbitrary device function on device-resident
    args (no host placement). Used by bench.py with device-side operand
    generation so multi-GB operands never cross the host link.

    ``warmup`` extra fenced executions run after the compile: a cold process
    measurably under-reports bandwidth on its first chains (clock ramp /
    cold caches), so headline callers should warm for a few runs.
    """
    y = fn(*args)
    for _ in range(max(0, warmup)):
        y = fn(*args)
    _fence(y)
    n1 = max(1, n_reps // 10)
    return [
        _max_across_processes(t)
        for t in _chain_slope(lambda: fn(*args), n1, n1 + n_reps, samples)
    ]


def resolve_measure(mode: str, measure: str) -> str:
    """Validate (mode, measure) and resolve 'auto' to a concrete method."""
    if mode not in TIMING_MODES:
        raise ConfigError(f"mode must be one of {TIMING_MODES}, got {mode!r}")
    if measure not in MEASURE_METHODS:
        raise ConfigError(
            f"measure must be one of {MEASURE_METHODS}, got {measure!r}"
        )
    if measure == "auto":
        # Device-looped reps for amortized (immune to per-dispatch tunnel
        # overhead — the round-1/2 non-monotonic-CSV failure mode); literal
        # per-rep protocol for reference mode, whose point is the transfer.
        measure = "loop" if mode == "amortized" else "sync"
    if mode == "reference" and measure in ("chain", "loop"):
        raise ConfigError(
            f"measure={measure!r} cannot time mode='reference': the per-rep "
            "host->device transfer is the thing being measured and cannot "
            "ride a device-side execution chain; use measure='sync'"
        )
    return measure


def time_matvec(
    fn: Callable,
    a,
    x,
    *,
    shardings=None,
    n_reps: int = DEFAULT_N_REPS,
    mode: str = "amortized",
    measure: str = "auto",
    chain_samples: int = DEFAULT_CHAIN_SAMPLES,
) -> list[float]:
    """Run the reference timing protocol around ``fn(a, x)``.

    ``a``/``x`` are host (numpy) arrays; ``shardings`` is the (A, x) pair of
    NamedShardings from ``strategy.shardings(mesh)`` (None → default
    placement). Returns per-measurement max-across-processes times in seconds
    (see module docstring for the two measurement methods).
    """
    measure = resolve_measure(mode, measure)
    sh_a, sh_x = shardings if shardings is not None else (None, None)

    def place(arr, sh):
        return jax.device_put(arr, sh)

    # Warm-up: compile + one run, outside the timed region (the C reference
    # pays no compile cost; see module docstring). measure='loop' compiles
    # and warms its own wrapped program inside _loop_slope — compiling the
    # bare fn here too would double per-config compile cost for nothing.
    a_dev, x_dev = place(a, sh_a), place(x, sh_x)
    if measure != "loop":
        _fence(fn(a_dev, x_dev))

    if mode == "amortized" and measure in ("chain", "loop"):
        n1 = max(1, n_reps // 10)
        n2 = n1 + n_reps
        if measure == "loop":
            per = _loop_slope(fn, a_dev, x_dev, n1, n2, chain_samples)
        else:
            per = _chain_slope(lambda: fn(a_dev, x_dev), n1, n2, chain_samples)
        return [_max_across_processes(t) for t in per]

    times: list[float] = []
    for _ in range(n_reps):
        if mode == "reference":
            # Host→device distribution inside the timed region (quirk Q5).
            # Delete device copies first so device_put really transfers.
            a_dev.delete()
            x_dev.delete()
            start = time.perf_counter()
            a_dev = place(a, sh_a)
            x_dev = place(x, sh_x)
            _fence(fn(a_dev, x_dev))
            elapsed = time.perf_counter() - start
        else:
            start = time.perf_counter()
            _fence(fn(a_dev, x_dev))
            elapsed = time.perf_counter() - start
        times.append(_max_across_processes(elapsed))
    return times


def _run_benchmark(
    *,
    fn: Callable,
    a: np.ndarray,
    rhs: np.ndarray,
    shardings,
    mesh,
    strategy_name: str,
    n_rhs: int,
    n_reps: int,
    mode: str,
    measure: str,
    chain_samples: int = DEFAULT_CHAIN_SAMPLES,
) -> TimingResult:
    """The shared protocol body behind :func:`benchmark_strategy` and
    :func:`benchmark_gemm`: time the built fn and assemble the result —
    one place, so matvec and GEMM rows in the shared extended CSV are always
    measured under the identical protocol.

    Reported time: **mean** over the per-rep times for ``sync`` (the
    reference's own protocol, ``src/multiplier_rowwise.c:168``) but
    **median** over slope estimates for ``chain``/``loop`` — each sample is
    an independent estimate of the same per-matvec time, and on tunneled
    backends a single stalled sample can be off by orders of magnitude (the
    round-1 small-size CSVs were non-monotonic for exactly this reason); the
    median rejects it where the mean absorbs it.
    """
    times = time_matvec(
        fn, a, rhs, shardings=shardings, n_reps=n_reps, mode=mode,
        measure=measure, chain_samples=chain_samples,
    )
    reported = (
        np.median(times) if measure in ("chain", "loop") else np.mean(times)
    )
    return TimingResult(
        n_rows=a.shape[0],
        n_cols=a.shape[1],
        n_devices=int(mesh.devices.size),
        strategy=strategy_name,
        dtype=str(a.dtype),
        mode=mode,
        measure=measure,
        mean_time_s=float(reported),
        times_s=tuple(times),
        n_reps=n_reps,
        n_rhs=n_rhs,
    )


def _prepare_operands(
    a: np.ndarray, rhs: np.ndarray, dtype: str | None
) -> tuple[np.ndarray, np.ndarray]:
    if dtype is not None:
        a = a.astype(dtype)
        rhs = rhs.astype(dtype)
    if a.dtype == np.float64 and not jax.config.jax_enable_x64:
        # Without x64, JAX silently downcasts fp64 operands to fp32 while
        # TimingResult would still record 'float64' — mislabeled results.
        jax.config.update("jax_enable_x64", True)
    return a, rhs


def benchmark_strategy(
    strategy,
    mesh,
    a: np.ndarray,
    x: np.ndarray,
    *,
    dtype: str | None = None,
    n_reps: int = DEFAULT_N_REPS,
    mode: str = "amortized",
    measure: str = "auto",
    kernel: str | Callable = "xla",
    gather_output: bool = True,
    chain_samples: int = DEFAULT_CHAIN_SAMPLES,
) -> TimingResult:
    """Benchmark one (strategy, mesh, size) configuration — the body of the
    reference's per-config run (``src/multiplier_rowwise.c:54-176``) minus the
    CSV write (see bench.metrics)."""
    measure = resolve_measure(mode, measure)
    a, x = _prepare_operands(a, x, dtype)
    strategy.validate(a.shape[0], a.shape[1], mesh)
    fn = strategy.build(mesh, kernel=kernel, gather_output=gather_output)
    return _run_benchmark(
        fn=fn, a=a, rhs=x, shardings=strategy.shardings(mesh), mesh=mesh,
        strategy_name=strategy.name, n_rhs=1, n_reps=n_reps, mode=mode,
        measure=measure, chain_samples=chain_samples,
    )


def benchmark_gemm(
    name: str,
    mesh,
    a: np.ndarray,
    b: np.ndarray,
    *,
    dtype: str | None = None,
    n_reps: int = DEFAULT_N_REPS,
    mode: str = "amortized",
    measure: str = "auto",
    kernel: str | Callable = "xla",
    gather_output: bool = True,
    chain_samples: int = DEFAULT_CHAIN_SAMPLES,
) -> TimingResult:
    """Benchmark one GEMM (strategy, mesh, size) configuration.

    Same protocol as :func:`benchmark_strategy` with a rank-2 right-hand
    side; the result's strategy is recorded as ``gemm_<name>`` so GEMM rows
    land in their own per-strategy CSVs (the reference schema has no op
    column to tell matvec and GEMM apart).
    """
    from ..models.gemm import build_gemm, gemm_shardings, validate_gemm

    measure = resolve_measure(mode, measure)
    a, b = _prepare_operands(a, b, dtype)
    validate_gemm(name, a.shape[0], a.shape[1], b.shape[1], mesh)
    fn = build_gemm(name, mesh, kernel=kernel, gather_output=gather_output)
    return _run_benchmark(
        fn=fn, a=a, rhs=b, shardings=gemm_shardings(name, mesh), mesh=mesh,
        strategy_name=f"gemm_{name}", n_rhs=b.shape[1], n_reps=n_reps,
        mode=mode, measure=measure, chain_samples=chain_samples,
    )

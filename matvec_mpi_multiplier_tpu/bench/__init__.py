"""Benchmark harness: timing protocol (C9), CSV metrics (C8), sweep CLI (C10)."""

from .metrics import append_result, csv_path, extended_csv_path, read_csv
from .timing import TIMING_MODES, TimingResult, benchmark_strategy, time_matvec

__all__ = [
    "TimingResult",
    "TIMING_MODES",
    "benchmark_strategy",
    "time_matvec",
    "append_result",
    "csv_path",
    "extended_csv_path",
    "read_csv",
]

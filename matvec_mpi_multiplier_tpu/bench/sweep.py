"""Benchmark sweep driver CLI.

Reference analog: component C10, ``test.sh`` — for each strategy it runs the
matrix of ``n_proc ∈ {1,2,6,12,24}`` × ``n ∈ {600,1800,...,10200}`` square
sizes (``test.sh:5,8``), invoking ``mpiexec -n $n_proc out/multiplier
$n_rows $n_rows`` (``:11``), appending to the per-strategy CSV. The
asymmetric CSVs (120–1200 × 60000, quirk Q10) came from a modified driver the
reference never committed; here both sweeps are first-class.

TPU-native mapping: the process count axis becomes subset device meshes
(1,2,4,8,... of the available devices); strategy selection is a runtime flag,
not a compile-time binary choice (``test.sh:3,10``).

Usage (replaces ``./test.sh <type>``)::

    python -m matvec_mpi_multiplier_tpu.bench.sweep --strategy rowwise
    python -m matvec_mpi_multiplier_tpu.bench.sweep \
        --strategy all --devices 1 2 4 8 --sweep square --dtype float32
    python -m matvec_mpi_multiplier_tpu.bench.sweep --sweep asymmetric

By default operand data is generated in memory (seeded, identical
distribution to the file generator): the reference's whitespace-text format at
its own 10200² top size is an ~800 MB file, and at the TPU-scale sizes in
BASELINE.json it would be tens of GB. ``--use-files`` restores the
reference-faithful path through ``./data/matrix_*.txt``.
"""

from __future__ import annotations

import argparse
import os
import re
import sys

import jax
import numpy as np

from ..models import available_strategies, get_strategy
from ..models.gemm import available_gemm_strategies, validate_gemm
from ..parallel.mesh import make_mesh
from ..utils import io
from ..utils.errors import MatvecError, TimingError
from .metrics import append_result, csv_path
from .profiling import annotate, trace
from .timing import (
    MEASURE_METHODS,
    TIMING_MODES,
    benchmark_gemm,
    benchmark_strategy,
)

# The reference's sweeps (test.sh:5,8 and the asymmetric CSVs' sizes).
SQUARE_SIZES = list(range(600, 10201, 1200))
ASYMMETRIC_SIZES = [(r, 60000) for r in range(120, 1201, 120)]


def device_counts_available(max_devices: int | None = None) -> list[int]:
    """Power-of-two subset mesh sizes up to the device count — the analog of
    test.sh's {1,2,6,12,24} process list on a fixed machine."""
    n = len(jax.devices())
    if max_devices is not None:
        n = min(n, max_devices)
    counts = []
    c = 1
    while c <= n:
        counts.append(c)
        c *= 2
    if counts[-1] != n and n not in counts:
        counts.append(n)
    return counts


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="matvec-sweep",
        description="Benchmark sweep over strategies x device counts x sizes "
        "(TPU-native replacement for the reference's test.sh).",
    )
    p.add_argument(
        "--strategy",
        nargs="+",
        default=["all"],
        help=f"strategies to run: {available_strategies()} or 'all' "
        f"(with --op gemm: {available_gemm_strategies()})",
    )
    p.add_argument(
        "--op",
        choices=["matvec", "gemm", "serve"],
        default="matvec",
        help="operation to sweep: matvec (y = A·x, the reference's scope), "
        "gemm (C = A @ B, the MXU-bound extension; rows land in "
        "gemm_<strategy>.csv), or serve (mixed-width request stream "
        "through the serving engine — requests/sec, p50/p99 dispatch "
        "latency, compile counts; rows land in serve_<strategy>.csv — "
        "bench/serve.py)",
    )
    p.add_argument(
        "--n-requests",
        type=int,
        default=200,
        help="with --op serve: steady-phase request count",
    )
    p.add_argument(
        "--max-bucket",
        type=int,
        default=32,
        help="with --op serve: widest batch bucket (power-of-two ladder)",
    )
    p.add_argument(
        "--promote",
        default="auto",
        help="with --op serve: GEMV->GEMM crossover b* — 'auto' (tuned), "
        "an int, or 'never'",
    )
    p.add_argument(
        "--n-rhs",
        type=int,
        default=None,
        help="with --op gemm: columns of B (default: square, n_rhs = n_cols)",
    )
    p.add_argument(
        "--devices",
        nargs="+",
        type=int,
        default=None,
        help="device counts to sweep (default: powers of two up to available)",
    )
    p.add_argument(
        "--sweep",
        choices=["square", "asymmetric", "both"],
        default="square",
        help="size sweep: square 600..10200 step 1200 (test.sh:8) or "
        "asymmetric 120..1200 x 60000 (the reference's long-contraction regime)",
    )
    p.add_argument(
        "--sizes",
        nargs="+",
        type=int,
        default=None,
        help="explicit square sizes, overriding --sweep",
    )
    p.add_argument("--dtype", default="float32", help="operand dtype")
    p.add_argument(
        "--n-reps",
        type=int,
        default=100,
        help="repetitions per config (reference: 100, src/multiplier_rowwise.c:135)",
    )
    p.add_argument(
        "--mode",
        choices=list(TIMING_MODES) + ["both"],
        default="amortized",
        help="'amortized': operands HBM-resident (honest TPU number); "
        "'reference': host->device transfer timed every rep (quirk Q5 parity)",
    )
    p.add_argument(
        "--kernel",
        default="xla",
        help="local GEMV kernel name; 'auto' consults the tuning cache "
        "(tuning/ — populate with --tune or the tuning CLI) and falls back "
        "to the static default on a miss",
    )
    p.add_argument(
        "--combine",
        default=None,
        choices=[
            "auto", "psum", "psum_scatter", "ring", "ring_overlap", "a2a",
            "gather", "overlap", "overlap_ring", "pallas_ring",
        ],
        help="combine-schedule override: a concrete schedule name, or "
        "'auto' for the tuning-cache winner per config (static default on "
        "a miss) — see MatvecStrategy.build. 'overlap' is the staged "
        "compute/communication pipeline (stage count from --stages or the "
        "tuned fifth axis); 'pallas_ring' the fused collective kernel "
        "(1-D meshes, matvec only)",
    )
    p.add_argument(
        "--stages",
        type=int,
        default=None,
        help="with --combine overlap (or auto resolving to it): pin the "
        "software-pipeline stage count S instead of consulting the tuned "
        "stage ladder; clamped down to the largest valid divisor of the "
        "per-device chunk",
    )
    p.add_argument(
        "--dtype-storage", dest="dtype_storage", default=None,
        choices=["native", "int8", "int8c", "fp8", "auto"],
        help="resident-A storage format (ops/quantize.py): quantize A "
        "per config and measure the strategy against the low-bit "
        "payload (un-staged combine family only). Rows are labeled "
        "<strategy>_<format> so native and quantized measurements of "
        "the same config coexist in the CSVs. --op serve forwards it "
        "to the engine; 'auto' is serve-only (the tuned sixth axis)",
    )
    p.add_argument(
        "--tune",
        action="store_true",
        help="pre-pass: measure kernel/tile/combine candidates for every "
        "config in this sweep (under this sweep's --measure/--kernel) and "
        "persist winners to the tuning cache before sweeping (the inline "
        "form of `python -m matvec_mpi_multiplier_tpu.tuning`)",
    )
    p.add_argument(
        "--min-gain",
        type=float,
        default=None,
        help="with --tune: hysteresis margin — a non-default candidate must "
        "beat the static default by this relative fraction to be recorded "
        "(default 0.05; raise on noisy shared hosts)",
    )
    p.add_argument(
        "--measure",
        choices=list(MEASURE_METHODS),
        default="auto",
        help="'loop': device-side fori_loop rep chain, one dispatch per "
        "sample (immune to per-dispatch tunnel overhead; amortized default); "
        "'chain': slope between host-driven fenced execution chains; "
        "'sync': literal per-rep fence protocol — use on "
        "oversubscribed virtual-device CPU meshes, where long queued chains "
        "can starve a device thread past XLA's collective-rendezvous timeout",
    )
    p.add_argument(
        "--chain-samples",
        type=int,
        default=None,
        help="independent chain-slope estimates per config (median reported; "
        "default 5 — single slopes stall on tunneled backends)",
    )
    p.add_argument(
        "--use-files",
        action="store_true",
        help="load operands via the ./data/matrix_*.txt convention "
        "(reference-faithful; slow/huge for large sizes)",
    )
    p.add_argument("--data-root", default=None, help="data directory override")
    p.add_argument(
        "--platform",
        default=None,
        help="force a jax platform (e.g. 'cpu'); set at jax.config level "
        "because accelerator plugins may pin jax_platforms at startup, where "
        "the JAX_PLATFORMS env var alone is outranked",
    )
    p.add_argument(
        "--host-devices",
        type=int,
        default=None,
        help="with --platform cpu: number of virtual host devices "
        "(--xla_force_host_platform_device_count), the reference's "
        "'mpiexec -n p on one machine' analog",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--no-csv", action="store_true", help="print results without writing CSVs"
    )
    p.add_argument(
        "--label-suffix",
        default=None,
        metavar="SUFFIX",
        help="append _SUFFIX to the strategy name in CSV rows (e.g. "
        "--kernel native --label-suffix native lands rows as "
        "rowwise_native.csv) — the reference schema has no kernel column, "
        "and unlabeled kernel-variant rows would contaminate per-strategy "
        "SpeedUp/Efficiency averaging",
    )
    p.add_argument(
        "--skip-measured",
        action="store_true",
        help="skip any config whose row already exists in the extended CSV "
        "(same strategy label, shape, device count, dtype, mode, measure "
        "and n_rhs) — lets a capture that died mid-sweep (tunnel wedge) "
        "resume at the next healthy window instead of redoing every "
        "config; requires an explicit --measure (an 'auto' sweep cannot "
        "know which method an existing row used)",
    )
    p.add_argument(
        "--keep-going",
        action="store_true",
        help="on a runtime/backend error in one config (e.g. a transient "
        "tunnel failure), record it and continue with the next config "
        "instead of aborting the whole sweep; exit code 5 = some config "
        "failed but the sweep COMPLETED (backend fault — retry-worthy, "
        "and with --skip-measured a retry redoes only the failures), "
        "3 = completed with only unmeasurable (TimingError) skips — a "
        "re-run would re-hit the same noise floor, so callers should "
        "treat 3 as a soft success. Distinct codes on purpose: crashes "
        "exit 1 and argparse usage errors exit 2, and neither of those "
        "deterministic classes may ever read as retry-worthy",
    )
    p.add_argument(
        "--profile-dir",
        default=None,
        metavar="DIR",
        help="capture a JAX device trace of the whole sweep into DIR "
        "(TensorBoard/Perfetto format; bench/profiling.py — the capability "
        "the reference lacked, SURVEY.md §5.1)",
    )
    p.add_argument(
        "--annotate",
        action="store_true",
        help="enable named device-trace spans (strategy local-GEMV/combine "
        "bodies, overlap stage{i}/compute|combine) in every program this "
        "sweep builds — pair with --profile-dir so the capture reads by "
        "phase (docs/OBSERVABILITY.md)",
    )
    p.add_argument(
        "--metrics-out",
        default=None,
        metavar="FILE",
        help="write an obs metrics snapshot after the run: with --op serve "
        "the engine's counters + latency histograms per config; otherwise "
        "the process registry (e.g. the --tune pre-pass's per-candidate "
        "measurement events). Render with "
        "`python -m matvec_mpi_multiplier_tpu.obs metrics FILE`",
    )
    p.add_argument(
        "--trace-jsonl",
        default=None,
        metavar="FILE",
        help="with --op serve: stream one request-lifecycle span tree per "
        "request to FILE (obs sink thread); summarize with "
        "`python -m matvec_mpi_multiplier_tpu.obs trace FILE`",
    )
    return p


def resolve_strategies(names: list[str], op: str = "matvec") -> list[str]:
    available = (
        available_gemm_strategies() if op == "gemm" else available_strategies()
    )
    if "all" in names:
        return available
    for n in names:
        if n not in available:
            raise SystemExit(
                f"unknown {op} strategy {n!r}; available: {available}"
            )
    return names


def operands(n_rows: int, n_cols: int, args) -> tuple[np.ndarray, np.ndarray]:
    if args.use_files:
        return io.ensure_data(n_rows, n_cols, args.data_root, seed=args.seed)
    return (
        io.generate_matrix(n_rows, n_cols, seed=args.seed),
        io.generate_vector(n_cols, seed=args.seed + 1),
    )


def configure_platform(platform: str | None, host_devices: int | None) -> None:
    """Apply platform/virtual-device overrides before any backend exists."""
    if host_devices is not None:
        flag = f"--xla_force_host_platform_device_count={host_devices}"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in flags:
            # Replace the inherited value — silently keeping it would hand the
            # user a different device count than the one they asked for.
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags
            )
        else:
            flags = f"{flags} {flag}".strip()
        os.environ["XLA_FLAGS"] = flags
    if platform is not None:
        os.environ["JAX_PLATFORMS"] = platform
        jax.config.update("jax_platforms", platform)


def run_sweep(args: argparse.Namespace) -> int:
    if args.op == "serve":
        # The serve protocol has its own driver (warmup/steady phases,
        # futures, promotion check) — bench/serve.py.
        from .serve import run_serve_sweep

        if args.promote == "never":
            args.promote = None
        return run_serve_sweep(args)
    if args.annotate:
        # Scope the named-span override to this run: an in-process caller
        # must not find the process-global flag flipped afterwards.
        from .profiling import annotations

        with annotations(True):
            return _run_sweep(args)
    return _run_sweep(args)


def _run_sweep(args: argparse.Namespace) -> int:
    if args.measure in ("chain", "loop") and args.mode in ("reference", "both"):
        # Reject up front: time_matvec raises the same ConfigError, but only
        # deep inside the loop, after earlier configs already burned minutes.
        raise SystemExit(
            f"--measure {args.measure} cannot time --mode reference (the "
            "per-rep host->device transfer cannot ride a device-side "
            "execution chain); use --measure sync or auto"
        )
    if args.op == "gemm" and args.use_files:
        raise SystemExit(
            "--use-files is matvec-only (the reference's vector-file "
            "convention has no rank-2 right-hand side); gemm operands are "
            "generated in memory"
        )
    if args.skip_measured and args.measure == "auto":
        raise SystemExit(
            "--skip-measured needs an explicit --measure: existing rows are "
            "matched by their measure column, and 'auto' resolves per "
            "config AFTER the skip decision would have to be made"
        )
    if args.skip_measured and args.no_csv:
        raise SystemExit(
            "--skip-measured with --no-csv would re-skip forever (new "
            "results are never written back) — drop one of the two"
        )
    if args.trace_jsonl is not None:
        raise SystemExit(
            "--trace-jsonl is request-lifecycle tracing — serve-mode only "
            "(--op serve); matvec/gemm sweeps have no request stream to "
            "trace (use --profile-dir for a device trace)"
        )
    if getattr(args, "dtype_storage", None) == "auto":
        raise SystemExit(
            "--dtype-storage auto is serve-only (the engine consults the "
            "tuned sixth axis at construction); a matvec/gemm sweep "
            "measures ONE format per run — name it (int8/int8c/fp8), or "
            "run --tune to record the measured decision"
        )
    # Fail fast on an unknown kernel: get_*_kernel raises the same KeyError,
    # but only deep inside the loop, after earlier configs already ran.
    from ..ops import available_gemm_kernels, available_kernels

    if args.kernel == "native":
        # The native FFI tiers register only when the .so exists; build it
        # on demand so `--kernel native` works in a default checkout.
        from ..ops import native_gemm, native_gemv

        native_gemv.register_if_available(build=True)
        native_gemm.register_if_available(build=True)

    kernels = (
        available_gemm_kernels() if args.op == "gemm" else available_kernels()
    )
    if args.kernel not in kernels:
        raise SystemExit(
            f"unknown {args.op} kernel {args.kernel!r}; available: {kernels}"
        )
    configure_platform(args.platform, args.host_devices)
    strategies = resolve_strategies(args.strategy, args.op)
    counts = args.devices or device_counts_available()
    if args.sizes:
        sizes = [(s, s) for s in args.sizes]
    elif args.sweep == "square":
        sizes = [(s, s) for s in SQUARE_SIZES]
    elif args.sweep == "asymmetric":
        sizes = list(ASYMMETRIC_SIZES)
    else:
        sizes = [(s, s) for s in SQUARE_SIZES] + list(ASYMMETRIC_SIZES)
    modes = list(TIMING_MODES) if args.mode == "both" else [args.mode]
    if args.op == "gemm" and args.combine == "gather":
        # The reduction family transfers to gemm; the gather schedules are
        # matvec-only (the batched output gather is XLA's to schedule).
        raise SystemExit(
            "--combine gather is matvec-only; gemm accepts "
            "auto/psum/psum_scatter/ring/ring_overlap/a2a (see build_gemm)"
        )

    meshes = {n_dev: make_mesh(n_dev) for n_dev in counts}
    if args.tune:
        from ..tuning import TuningCache, reset_cache
        from ..tuning.search import TUNE_MIN_GAIN, tune_sweep

        cache = TuningCache.load()
        print(f"tuning pre-pass -> {cache.path}")
        tune_sweep(
            strategies, sizes, [meshes[n] for n in counts], args.dtype,
            cache, op=args.op, n_rhs=args.n_rhs, seed=args.seed,
            # Tune under the sweep's own conditions — a combine crossover
            # measured under a different kernel/protocol need not hold in
            # the sweep it feeds. kernel='auto' would consult the very
            # cache being built, so the pre-pass measures its candidates
            # under the static default instead.
            kernel="xla" if args.kernel == "auto" else args.kernel,
            measure=args.measure,
            min_gain=(
                args.min_gain if args.min_gain is not None else TUNE_MIN_GAIN
            ),
        )
        cache.save()
        # The sweep's auto lookups must see the fresh decisions, not a
        # singleton loaded before the pre-pass ran.
        reset_cache()
    # [timed, skipped, unmeasurable, failed] — the last two only fill under
    # --keep-going. Unmeasurable (TimingError) is separated from hard
    # failures because the two demand opposite reactions from a capture
    # watcher: re-running a hard-failed sweep may succeed (transient tunnel
    # fault), re-running an unmeasurable config just re-hits the same noise
    # floor — a watcher that retried the whole capture over it would burn
    # the healthy window the --keep-going skip was meant to protect.
    counters = [0, 0, 0, 0]
    # The trace must stop (and flush its file) on ANY exit — an exception
    # mid-sweep or Ctrl+C hours in must not lose the whole capture.
    with trace(args.profile_dir or "", enabled=args.profile_dir is not None):
        _sweep_loop(args, strategies, counts, sizes, modes, meshes, counters)
    n_ok, n_skip, n_unmeasurable, n_failed = counters
    if not args.no_csv:
        for name in strategies:
            csv_name = csv_label(
                name, args.op, args.label_suffix,
                storage=getattr(args, "dtype_storage", None),
            )
            for mode in modes:
                print(f"CSV: {csv_path(csv_name, args.data_root, mode=mode)}")
    if args.profile_dir is not None:
        print(f"trace: {args.profile_dir}")
    if args.metrics_out is not None:
        # The process registry: subsystem-level events this run emitted —
        # chiefly the --tune pre-pass's per-candidate measurements
        # (tuning/search.py). Serve-mode snapshots (engine counters) are
        # written by the serve driver itself.
        import json as _json
        from pathlib import Path

        from ..obs.registry import get_registry

        out = Path(args.metrics_out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(
            _json.dumps(get_registry().snapshot(), indent=2) + "\n"
        )
        print(f"metrics: {out}")
    print(
        f"{n_ok} configs timed, {n_skip} skipped, "
        f"{n_unmeasurable} unmeasurable, {n_failed} failed"
    )
    if n_failed:
        # 5, not 1: a COMPLETED sweep with recorded config failures is the
        # transient-backend class (worth retrying; --skip-measured makes
        # the retry redo only the failures), while a crash — config bug,
        # re-raised MatvecError — exits 1 via the interpreter. A capture
        # orchestrator keys retry-vs-stop off exactly this distinction.
        return 5
    # 3, not 2: argparse's usage-error convention is exit 2, and a capture
    # orchestrator must never read a broken command line as a soft skip.
    return 3 if n_unmeasurable else 0


def csv_label(
    name: str, op: str, label_suffix: str | None,
    storage: str | None = None,
) -> str:
    """The strategy label exactly as CSV rows record it: gemm rows land as
    ``gemm_<name>`` (timing.py::benchmark_gemm sets ``strategy_name``) and
    ``--label-suffix`` appends after that. Single source for the CSV-path
    printout AND the ``--skip-measured`` row matching — if these drifted
    apart, resumed sweeps would silently re-run (and duplicate) every
    config."""
    label = f"gemm_{name}" if op == "gemm" else name
    if storage not in (None, "native"):
        # Quantized-storage rows append the format first, then any
        # user suffix — the same order the sweep loop writes rows in.
        label = f"{label}_{storage}"
    return f"{label}_{label_suffix}" if label_suffix else label


def _measured_keys(args) -> set[tuple]:
    """Identity keys of rows already in the extended CSV, for
    ``--skip-measured``: strategy label as written (``--label-suffix``
    included), shape, device count, dtype, mode, measure, n_rhs.

    Rows missing any key column are dropped, not fatal: the extended CSV
    can hold old-schema rows (pre-``measure`` files rotate on first
    append, ``metrics._append_row``) or a final line truncated by the
    wedge-timeout kill — the very crash this resume path recovers from.
    An unmatchable row simply re-measures."""
    from .metrics import extended_csv_path, read_csv

    path = extended_csv_path(args.data_root)
    if not path.exists():
        return set()
    keys = set()
    for row in read_csv(path):
        try:
            keys.add((
                str(row["strategy"]), int(row["n_rows"]),
                int(row["n_cols"]), int(row["n_devices"]),
                str(row["dtype"]), str(row["mode"]), str(row["measure"]),
                int(row.get("n_rhs", 1)),
            ))
        except (KeyError, TypeError, ValueError):
            continue
    return keys


def _sweep_loop(args, strategies, counts, sizes, modes, meshes, counters):
    # Sizes on the outer loop: operands depend only on the size (and seed),
    # so each (n_rows, n_cols) pair is generated/loaded exactly once and
    # shared across every strategy x device-count combination — and only
    # when at least one of its configs actually runs (a fully
    # skip-measured size never generates operands at all).
    gemm = args.op == "gemm"
    measured = _measured_keys(args) if args.skip_measured else set()
    for n_rows, n_cols in sizes:
        n_rhs = (args.n_rhs or n_cols) if gemm else 1
        a = x = None
        for name in strategies:
            strat = None if gemm else get_strategy(name)
            supports = True
            if args.combine is not None:
                if gemm:
                    supports = get_strategy(name).supports_combine_batched(
                        args.combine
                    )
                else:
                    supports = strat.supports_combine(args.combine)
            if not supports:
                # e.g. --combine psum_scatter under --strategy all: rowwise
                # has no such schedule. A skip, not a crash — the flag is
                # meaningful for the strategies that do support it.
                print(
                    f"skip {name} {n_rows}x{n_cols}: no combine schedule "
                    f"{args.combine!r} for this strategy"
                )
                counters[1] += 1
                continue
            label_name = csv_label(
                name, args.op, args.label_suffix,
                storage=getattr(args, "dtype_storage", None),
            )
            for n_dev in counts:
                mesh = meshes[n_dev]
                try:
                    if gemm:
                        validate_gemm(name, n_rows, n_cols, n_rhs, mesh)
                    else:
                        strat.validate(n_rows, n_cols, mesh)
                except MatvecError as e:
                    print(f"skip {name} {n_rows}x{n_cols} p={n_dev}: {e}")
                    counters[1] += 1
                    continue
                for mode in modes:
                    if (label_name, n_rows, n_cols, n_dev, args.dtype,
                            mode, args.measure, n_rhs) in measured:
                        print(
                            f"skip {label_name} {n_rows}x{n_cols} p={n_dev} "
                            f"[{mode}]: already measured (--skip-measured)"
                        )
                        counters[1] += 1
                        continue
                    if a is None:
                        if gemm:
                            a = io.generate_matrix(
                                n_rows, n_cols, seed=args.seed
                            )
                            x = io.generate_matrix(
                                n_cols, n_rhs, seed=args.seed + 1
                            )
                        else:
                            a, x = operands(n_rows, n_cols, args)
                    label = f"{args.op}_{name}_{n_rows}x{n_cols}_p{n_dev}_{mode}"
                    bench_kwargs = dict(
                        dtype=args.dtype,
                        n_reps=args.n_reps,
                        mode=mode,
                        measure=args.measure,
                        kernel=args.kernel,
                    )
                    if args.combine is not None:
                        bench_kwargs["combine"] = args.combine
                    if args.stages is not None:
                        bench_kwargs["stages"] = args.stages
                    if args.dtype_storage not in (None, "native"):
                        bench_kwargs["dtype_storage"] = args.dtype_storage
                    if args.chain_samples is not None:
                        bench_kwargs["chain_samples"] = args.chain_samples
                    try:
                        with annotate(label):
                            if gemm:
                                result = benchmark_gemm(
                                    name, mesh, a, x, **bench_kwargs
                                )
                            else:
                                result = benchmark_strategy(
                                    strat, mesh, a, x, **bench_kwargs
                                )
                    except TimingError as e:
                        # Measurement failure (jitter beat the signal), not a
                        # config bug: skippable like any transient backend
                        # fault so a long capture survives a noisy window.
                        if not args.keep_going:
                            raise
                        print(
                            f"UNMEASURABLE {label}: {e}", file=sys.stderr
                        )
                        counters[2] += 1
                        continue
                    except MatvecError:
                        raise  # config bugs must fail loudly, flag or not
                    except Exception as e:
                        if not args.keep_going:
                            raise
                        # Transient backend failure (tunneled TPU: compile
                        # endpoint drop, claim loss): later configs may well
                        # succeed — a flushed partial sweep beats an empty one.
                        print(
                            f"FAILED {label}: {type(e).__name__}: {e}",
                            file=sys.stderr,
                        )
                        counters[3] += 1
                        continue
                    suffixes = [
                        s for s in (
                            bench_kwargs.get("dtype_storage"),
                            args.label_suffix,
                        ) if s
                    ]
                    if suffixes:
                        # Quantized rows land as <strategy>_<format> so
                        # native and quantized measurements of the same
                        # config coexist in the per-strategy CSVs (the
                        # --label-suffix convention).
                        import dataclasses

                        result = dataclasses.replace(
                            result,
                            strategy="_".join([result.strategy] + suffixes),
                        )
                    if not args.no_csv:
                        append_result(result, args.data_root)
                    print(
                        f"{result.strategy} {n_rows}x{n_cols} p={n_dev} [{mode}] "
                        f"mean={result.mean_time_s:.6f}s "
                        f"{result.gflops:.2f} GFLOP/s {result.gbps:.2f} GB/s"
                    )
                    counters[0] += 1


def main(argv: list[str] | None = None) -> int:
    return run_sweep(build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())

"""Serve-throughput benchmark: the request-stream face of the suite.

Where ``bench.sweep`` measures one (strategy, shape) matvec in isolation —
the paper's protocol — this mode drives the serving engine (``engine/``)
with a mixed-width stream of right-hand-side blocks against a resident
sharded ``A`` and reports the numbers a serving system is judged on:

* **requests/sec** and **columns/sec** over the steady phase;
* **p50/p99 dispatch latency** — time from ``submit()`` entry to return,
  i.e. the host cost of one request *excluding* device execution (dispatch
  never host-syncs; the stream drains once at the end). Percentiles come
  from the shared obs histogram (``obs/registry.py`` — the one percentile
  implementation in the repo; exact over the steady window);
* **compile counts** per phase — the zero-recompilation criterion: after
  the warmup phase covers the bucket ladder, ``compiles_steady`` must be 0
  across any mixed-shape replay;
* the **GEMV→GEMM promotion check** — one engine-dispatched block of
  ``b*`` columns vs ``b*`` sequential single-RHS dispatches, both through
  the same engine under the same wall-clock protocol (the tuned crossover
  must actually pay off in the serving loop, not just in the tuner).

**Load mode** (:func:`run_serve_load`) drives the *continuous-batching*
face instead: realistic traffic — a closed-loop ``--concurrency`` axis
(N clients, each submit→materialize→repeat) or an open-loop arrival
process (``--arrival poisson|burst --rate``) — optionally through the
arrival-window scheduler (``engine/scheduler.py``, ``--coalesce``), so
coalescing is exercised by concurrency instead of back-to-back submits.
Load rows report requests/sec under offered load, **end-to-end** p50/p99
latency (submit entry to materialized result — the latency columns'
meaning in load rows, where dispatch-only time would hide the window),
and the batching-efficiency columns: mean batch width and coalesce ratio
(NaN in uncoalesced rows). ``--coalesce both`` measures each config
uncoalesced then coalesced — the committed ``data/batching_demo/``
capture's protocol, and the ≥2× acceptance comparison.

**Chaos mode** (``--fault-spec``; docs/RESILIENCE.md) arms a seeded
:class:`~..resilience.FaultPlan` on the engine's compile/dispatch sites
and (by default) the retry + circuit-breaker + degradation-ladder
recovery policy, so availability is *measured*, not assumed: the load
loops tolerate per-request failures, and every row carries
``success_rate`` / ``failed_requests`` (fault failures — deadline
failures stay in the ``*_deadline_failures`` counters, so the two are
distinguishable) plus the ``retries`` / ``downgrades`` recovery tallies.
``--poison-rate`` marks a seeded fraction of requests with a payload
signature a poison fault spec matches — the deterministic "bad request"
whose blast radius the scheduler's batch bisection must contain.

**Multi-tenant trace mode** (``--tenants``; docs/MULTITENANT.md) drives
the matrix registry (``engine/registry.py``) instead of a single
engine: N seeded tenant matrices against an ``--hbm-budget``, a
Zipf-popularity request trace (``--zipf-a``), optional warm-pinning
(``--pin-hot``) and per-tenant admission quotas (``--tenant-quota``).
Rows land in ``serve_tenants_<strategy>.csv`` — one per tenant with
availability/hit-rate/eviction columns plus an ``ALL`` summary — and
``lru_floor`` replays the same trace through plain LRU so the eviction
policy is measured against its expectation. The chaos overlay composes:
``--fault-spec 'dispatch:device_error:key=tenant-0/*'`` targets exactly
one tenant (labels are tenant-prefixed), and the isolation acceptance
asserts every OTHER tenant's availability column stays at 1.0.

**Solver mode** (``--op cg|gmres|power|lanczos|chebyshev``;
docs/SOLVERS.md) serves ANSWERS instead of multiplies: each request is
one compiled-loop solve (``engine.submit(op=..., rhs=b, rtol=...,
maxiter=...)``) against a seeded diagonally-dominant SPD operand, so
every op converges by construction and a divergence is a signal, not
noise. Rows land in ``serve_solver_<strategy>.csv`` with the
answer-quality columns — ``iterations`` / ``final_residual`` /
``time_per_iter_ms`` — next to the serving ones (solve p50/p99,
compiles per phase; ``compiles_steady`` must stay 0 across repeated
solves: rtol/maxiter are dynamic operands of ONE executable).
``chebyshev``'s required spectral interval comes from Gershgorin
bounds on the generated operand — cheap, deterministic, and honest
about being bounds (a wider interval slows Chebyshev; it never breaks
it). The committed capture is ``data/solver_demo/``.

**Global-scheduler A/B** (``--global-sched on|off|both`` with
``--tenants``; docs/SCHEDULING.md) routes submits through the
cost-model-driven :class:`~..engine.GlobalScheduler` — predicted-time
admission, cross-tenant interleaving/coalescing, demand-aware eviction
(``--demand-weight``) — against the greedy baseline on the SAME seeded
trace. ``--deadline-ms`` adds the SLO overlay: arrivals paced at
``--rate`` req/s with deadlines anchored at scheduled arrivals, rows
gaining the ``deadline_expires``/``rejected`` split (rejected ≠ failed),
on-time goodput and end-to-end p50/p99; ``--decision-jsonl`` mirrors
every scheduling decision. The committed capture is
``data/gsched_demo/`` (``scripts/gsched_study.py``).

Rows land in ``data/out/serve_<strategy>.csv`` (``--data-root`` to
redirect; the committed demos live under ``data/engine_demo/``,
``data/batching_demo/`` and ``data/resilience_demo/``).

Usage::

    python -m matvec_mpi_multiplier_tpu.bench.serve \
        --strategy rowwise colwise --sizes 1024 --platform cpu \
        --host-devices 8 --tune

    # or through the sweep driver:
    python -m matvec_mpi_multiplier_tpu.bench.sweep --op serve ...

Observability: ``--metrics-out`` writes the engine's metrics snapshot
(requests/dispatches/compiles/hits/drains + latency histograms — the same
counters ``EngineStats`` reports, one source of truth) as JSON after each
config; ``--trace-jsonl`` streams one span tree per request through the
obs sink thread; ``--annotate`` enables the named device-trace spans
(strategy bodies, overlap stages) for a ``--profile``-style capture.
Render either with ``python -m matvec_mpi_multiplier_tpu.obs``.

This is timing/driver code: host syncs are deliberate protocol fences here
(the engine's own dispatch path stays lint-enforced sync-free), and the
metrics-snapshot write happens after the timed phases.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import queue
import sys
import threading
import time
from pathlib import Path
from typing import Sequence

import numpy as np

from ..engine import (
    ArrivalWindowScheduler,
    DEFAULT_MAX_WINDOW_MS,
    MatrixRegistry,
    MatvecEngine,
    TenantQuota,
    bucket_for,
    split_widths,
)
from ..models import available_strategies
from ..obs import (
    DEFAULT_TARGETS,
    FlightRecorder,
    JsonlSink,
    SloMonitor,
    reset_hub,
)
from ..obs.registry import MetricsRegistry
from ..resilience import (
    FaultPlan,
    FaultSpec,
    ResiliencePolicy,
    RetryPolicy,
    parse_fault_spec,
)
from ..solvers import SOLVER_OPS
from ..utils.errors import (
    AdmissionRejectedError,
    ConfigError,
    DeadlineExceededError,
    MatvecError,
    SolverDivergedError,
)

# The payload signature --poison-rate plants in row 0 of a poisoned
# request (and the matching FaultSpec(poison=...) keys on): far outside
# the uniform(0, 10) request distribution, exactly representable in every
# served float dtype.
POISON_SIGNATURE = 1e30

# Default request-width mix: single vectors through full buckets, with
# off-bucket widths (3, 6, 12, 24) so the pad/unpad path is always
# exercised. Clipped to --max-bucket.
DEFAULT_WIDTH_MIX = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32)

# Load-mode width mix: heavy single-RHS traffic — the workload coalescing
# exists for (ISSUE/ROADMAP: every lone dispatch re-reads all of A for one
# output column).
LOAD_WIDTH_MIX = (1,)

SERVE_CSV_HEADER = (
    "n_rows, n_cols, n_devices, strategy, dtype, kernel, combine, "
    "b_star, max_bucket, n_requests, total_cols, wall_s, rps, cols_per_s, "
    "p50_dispatch_ms, p99_dispatch_ms, compiles_warmup, compiles_steady, "
    "hits_steady, promo_b, promo_gemm_s, promo_seq_s, promo_speedup, "
    "arrival, rate_req_s, concurrency, coalesce, mean_batch_width, "
    "coalesce_ratio, success_rate, failed_requests, retries, downgrades, "
    "dtype_storage, resident_bytes, speculated, escalation_rate, "
    "spec_bandwidth_ratio"
)


@dataclasses.dataclass(frozen=True)
class ServeResult:
    """One serve-bench measurement (one CSV row)."""

    n_rows: int
    n_cols: int
    n_devices: int
    strategy: str
    dtype: str
    kernel: str
    combine: str
    b_star: int | None
    max_bucket: int
    n_requests: int
    total_cols: int
    wall_s: float
    p50_dispatch_ms: float
    p99_dispatch_ms: float
    compiles_warmup: int
    compiles_steady: int
    hits_steady: int
    # Promotion check: one b-wide GEMM dispatch vs b sequential single-RHS
    # dispatches, per-request wall seconds (NaN when promotion is off).
    promo_b: int
    promo_gemm_s: float
    promo_seq_s: float
    # Load-mode columns (run_serve_load): the traffic shape offered and
    # the batching efficiency achieved. The sequential protocol's rows
    # carry the defaults (closed-loop, one client, uncoalesced). In load
    # rows the latency columns above are END-TO-END (submit entry to
    # materialized result), not dispatch-only.
    arrival: str = "closed"
    rate_req_s: float = float("nan")
    concurrency: int = 1
    coalesce: int = 0
    mean_batch_width: float = float("nan")
    coalesce_ratio: float = float("nan")
    # Availability columns (chaos mode / ISSUE 7): failed_requests counts
    # FAULT failures — requests whose result() raised something other
    # than a deadline (those stay in the *_deadline_failures counters, so
    # the two failure classes are distinguishable); retries/downgrades
    # are the recovery policy's tallies (0 without --fault-spec).
    failed_requests: int = 0
    retries: int = 0
    downgrades: int = 0
    # Quantized-storage columns (ops/quantize.py): the resident-A format
    # the engine actually served from (``auto`` rows record the resolved
    # winner, not the request) and its HBM payload bytes.
    dtype_storage: str = "native"
    resident_bytes: int = 0
    # Speculative-serving columns (ops/speculative.py; docs/QUANTIZATION.md):
    # speculated counts requests served through the int8c speculative tier,
    # escalation_rate is the engine's gauge (escalations over speculative
    # dispatches — the cost model's ε feed), and spec_bandwidth_ratio is
    # the amortized resident-stream bytes per request relative to native:
    # (spec_bytes + rate·native_bytes) / native_bytes. NaN when the run
    # never armed speculation.
    speculated: int = 0
    escalation_rate: float = float("nan")
    spec_bandwidth_ratio: float = float("nan")

    @property
    def success_rate(self) -> float:
        """Fraction of offered requests that returned a result (fault
        failures excluded; 1.0 for a fault-free run)."""
        if self.n_requests == 0:
            return float("nan")
        return (self.n_requests - self.failed_requests) / self.n_requests

    @property
    def rps(self) -> float:
        return self.n_requests / self.wall_s

    @property
    def cols_per_s(self) -> float:
        return self.total_cols / self.wall_s

    @property
    def promo_speedup(self) -> float:
        """How many times faster the promoted block GEMM serves its batch
        than sequential dispatch would (>1 = promotion pays)."""
        if not (self.promo_gemm_s > 0):
            return float("nan")
        return self.promo_seq_s / self.promo_gemm_s


def serve_csv_path(strategy: str, root=None):
    from .metrics import out_dir

    return out_dir(root) / f"serve_{strategy}.csv"


def append_serve_result(result: ServeResult, root=None):
    from ..parallel.distributed import is_main_process
    from .metrics import _append_row

    path = serve_csv_path(result.strategy, root)
    if not is_main_process():
        return path
    row = (
        f"{result.n_rows}, {result.n_cols}, {result.n_devices}, "
        f"{result.strategy}, {result.dtype}, {result.kernel}, "
        f"{result.combine}, "
        f"{result.b_star if result.b_star is not None else -1}, "
        f"{result.max_bucket}, {result.n_requests}, {result.total_cols}, "
        f"{result.wall_s:.6f}, {result.rps:.2f}, {result.cols_per_s:.2f}, "
        f"{result.p50_dispatch_ms:.4f}, {result.p99_dispatch_ms:.4f}, "
        f"{result.compiles_warmup}, {result.compiles_steady}, "
        f"{result.hits_steady}, {result.promo_b}, "
        f"{result.promo_gemm_s:.6f}, {result.promo_seq_s:.6f}, "
        f"{result.promo_speedup:.3f}, {result.arrival}, "
        f"{result.rate_req_s:.2f}, {result.concurrency}, "
        f"{result.coalesce}, {result.mean_batch_width:.3f}, "
        f"{result.coalesce_ratio:.3f}, {result.success_rate:.4f}, "
        f"{result.failed_requests}, {result.retries}, {result.downgrades}, "
        f"{result.dtype_storage}, {result.resident_bytes}, "
        f"{result.speculated}, {result.escalation_rate:.4f}, "
        f"{result.spec_bandwidth_ratio:.4f}"
    )
    _append_row(path, SERVE_CSV_HEADER, row)
    return path


def _request_pool(
    k: int, widths: Sequence[int], dtype, seed: int
) -> dict[int, np.ndarray]:
    """One seeded host block per distinct width — generated once so the
    timed loop measures dispatch, not numpy RNG."""
    rng = np.random.default_rng(seed)
    return {
        w: rng.uniform(0, 10, (k, w)).astype(dtype) for w in set(widths)
    }


def _drain(futures) -> None:
    """Protocol fence: materialize every outstanding result (timing code —
    the one place the serve protocol host-syncs)."""
    for fut in futures:
        fut.result()


def measure_promotion(
    engine: MatvecEngine, pool: dict[int, np.ndarray], *, n_reps: int = 20
) -> tuple[int, float, float]:
    """One promoted block dispatch vs the same columns served one by one.

    Both sides run through the SAME warm engine and the same wall-clock
    protocol (submit everything, drain once), so the comparison isolates
    exactly the promotion decision: one bucket-padded GEMM executable
    versus ``b`` single-RHS executables. Returns per-request seconds
    ``(b, t_gemm, t_seq)`` — or ``(0, nan, nan)`` when the engine has
    promotion disabled: its block submits would take the per-column path
    too, and recording that as a "promotion" row would pollute any
    crossover analysis of the promo columns.
    """
    if engine.b_star is None:
        return 0, float("nan"), float("nan")
    b = max(2, min(engine.b_star, engine.max_bucket))
    block = pool.get(b)
    if block is None:
        block = _request_pool(engine.k, [b], engine.dtype, seed=7)[b]
    cols = [np.ascontiguousarray(block[:, j]) for j in range(b)]

    # Warm both paths (compile + first-run costs out of the timed region).
    _drain([engine.submit(block)])
    _drain([engine.submit(c) for c in cols])

    start = time.perf_counter()
    futures = [engine.submit(block) for _ in range(n_reps)]
    _drain(futures)
    t_gemm = (time.perf_counter() - start) / n_reps

    start = time.perf_counter()
    futures = []
    for _ in range(n_reps):
        futures.extend(engine.submit(c) for c in cols)
    _drain(futures)
    t_seq = (time.perf_counter() - start) / n_reps
    return b, t_gemm, t_seq


def _arrival_gaps(
    arrival: str, n: int, rate: float, burst: int, rng
) -> list[float]:
    """Inter-arrival gaps (seconds) for the open-loop processes: Poisson
    (exponential gaps at ``rate`` req/s) or bursty (groups of ``burst``
    simultaneous arrivals, one group per ``burst/rate`` seconds — same
    offered rate, maximally coalescable)."""
    if rate <= 0:
        raise MatvecError(f"open-loop arrival needs rate > 0, got {rate}")
    if arrival == "poisson":
        return list(rng.exponential(1.0 / rate, size=n))
    if arrival == "burst":
        if burst < 1:
            raise MatvecError(f"burst size must be >= 1, got {burst}")
        return [
            (burst / rate) if i % burst == 0 else 0.0 for i in range(n)
        ]
    raise MatvecError(f"unknown arrival process {arrival!r}")


def _closed_loop(
    submit, blocks: Sequence[np.ndarray], concurrency: int, hist,
    fail_counter=None,
) -> float:
    """Closed-loop load: ``concurrency`` client threads, each
    submit→materialize→repeat over its slice of the request trace (the
    classic offered-concurrency protocol). Returns steady-phase wall
    seconds; per-request END-TO-END latency lands in ``hist``.

    With ``fail_counter`` (chaos mode) a request failing with a
    framework fault — injected device error, integrity-gate refusal —
    is counted and the client moves on (availability is the measured
    quantity); deadline failures are already counted by the admission
    gates, and anything non-framework still aborts the run (a bench bug
    must not read as downtime)."""
    barrier = threading.Barrier(concurrency + 1)
    errors: list[BaseException] = []

    def client(tid: int) -> None:
        try:
            barrier.wait()
            for i in range(tid, len(blocks), concurrency):
                t0 = time.perf_counter()
                try:
                    # An uncoalesced poisoned dispatch raises from
                    # submit() itself; a coalesced one from result().
                    submit(blocks[i]).result()
                except DeadlineExceededError:
                    continue  # tallied by the gate's deadline counters
                except MatvecError:
                    if fail_counter is None:
                        raise
                    fail_counter.inc()
                    continue
                hist.observe((time.perf_counter() - t0) * 1e3)
        except BaseException as e:  # surface on the driver thread
            errors.append(e)

    threads = [
        threading.Thread(target=client, args=(t,), daemon=True)
        for t in range(concurrency)
    ]
    for t in threads:
        t.start()
    barrier.wait()
    start = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - start
    if errors:
        raise errors[0]
    return wall


def _open_loop(
    submit, blocks: Sequence[np.ndarray], gaps: Sequence[float], hist,
    flush=None, fail_counter=None,
) -> float:
    """Open-loop load: requests arrive on the precomputed gap schedule
    regardless of completion (one submitter thread paces arrivals; one
    drainer thread materializes in order and records arrival→result
    latency). Returns wall seconds from first arrival to last result.
    ``fail_counter`` as in :func:`_closed_loop` — chaos-mode fault
    failures are counted, tolerated, and excluded from the latency
    histogram."""
    results: queue.Queue = queue.Queue()
    errors: list[BaseException] = []

    def drainer() -> None:
        while True:
            item = results.get()
            if item is None:
                return
            t_arrival, fut = item
            try:
                fut.result()
            except DeadlineExceededError:
                continue  # tallied by the gate's deadline counters
            except MatvecError as e:
                if fail_counter is None:
                    errors.append(e)
                else:
                    fail_counter.inc()
                continue
            except BaseException as e:
                errors.append(e)
                continue
            hist.observe((time.perf_counter() - t_arrival) * 1e3)

    drain_thread = threading.Thread(target=drainer, daemon=True)
    drain_thread.start()
    start = time.perf_counter()
    next_at = start
    for x, gap in zip(blocks, gaps):
        next_at += gap
        while True:
            now = time.perf_counter()
            if now >= next_at:
                break
            time.sleep(min(next_at - now, 5e-4))
        try:
            results.put((time.perf_counter(), submit(x)))
        except MatvecError as e:
            # An uncoalesced poisoned dispatch raises at submit() on the
            # pacing thread; chaos mode counts it and keeps the arrival
            # schedule, anything else still aborts the run. (Deadline
            # expiry never raises from submit — it returns a failed
            # future, handled by the drainer.)
            if fail_counter is None:
                errors.append(e)
            else:
                fail_counter.inc()
    if flush is not None:
        flush()  # fence the open window so the drain is prompt
    results.put(None)
    drain_thread.join()
    wall = time.perf_counter() - start
    if errors:
        raise errors[0]
    return wall


def run_serve_load(
    strategy_name: str,
    mesh,
    m: int,
    k: int,
    *,
    dtype: str = "float32",
    kernel: str = "xla",
    combine: str | None = None,
    stages: int | None = None,
    dtype_storage: str | None = None,
    n_requests: int = 200,
    max_bucket: int = 32,
    widths: Sequence[int] | None = None,
    promote: str | int | None = "auto",
    donate: bool = True,
    concurrency: int = 8,
    coalesce: bool = True,
    arrival: str = "closed",
    rate: float = 500.0,
    burst: int = 8,
    window_ms: str | float = "auto",
    max_window_ms: float = DEFAULT_MAX_WINDOW_MS,
    flush_width: str | int = "auto",
    seed: int = 0,
    metrics_out: str | None = None,
    trace_jsonl: str | None = None,
    events_jsonl: str | None = None,
    slo_out: str | None = None,
    flight_dir: str | None = None,
    fault_spec: str | None = None,
    fault_seed: int = 0,
    poison_rate: float = 0.0,
    integrity_gate: bool = False,
    resilience: bool | None = None,
    breaker_reset_s: float = 30.0,
) -> ServeResult:
    """Run the load protocol for one (strategy, shape, mesh, traffic)
    config: realistic concurrent/open-loop traffic, optionally coalesced
    through the arrival-window scheduler. The request trace (widths +
    payloads, seeded) is identical for coalesced and uncoalesced runs of
    the same config — the acceptance comparison is same-trace by
    construction.

    Chaos mode (module docstring): ``fault_spec`` arms a seeded
    FaultPlan; ``poison_rate`` marks a seeded fraction of requests with
    :data:`POISON_SIGNATURE` and appends a persistent poison fault spec;
    ``resilience`` (default: on whenever faults are armed) enables the
    engine's retry/breaker/ladder policy with ``breaker_reset_s``
    cooldowns; ``integrity_gate`` arms the NaN/Inf materialize gate.

    Observability control plane (docs/OBSERVABILITY.md):
    ``events_jsonl`` streams the correlated event timeline to a JSONL
    file (render one request with ``obs timeline``); ``slo_out`` arms a
    burn-rate monitor over the run's registry (sampled around each
    phase) and writes its evaluation JSON (render with ``obs slo``);
    ``flight_dir`` arms a flight recorder that auto-dumps post-mortem
    bundles there on typed failures (render with ``obs dump``)."""
    from ..utils.io import generate_matrix

    if widths is None:
        widths = [w for w in LOAD_WIDTH_MIX if w <= max_bucket]
    a = generate_matrix(m, k, seed=seed).astype(dtype)
    registry = MetricsRegistry()

    # Arm the observability control plane BEFORE engine construction so
    # warmup traffic and scheduler decisions land on the same hub. The
    # hub is process-global (that is what lets the engine, schedulers
    # and registry correlate without plumbing), so a sink requested here
    # replaces any previous one.
    hub = (
        reset_hub(sink=JsonlSink(events_jsonl))
        if events_jsonl is not None
        else None
    )
    slo_monitor = (
        SloMonitor(registry, DEFAULT_TARGETS) if slo_out is not None else None
    )
    recorder = None
    if flight_dir is not None:
        from ..obs import get_hub

        recorder = FlightRecorder(
            hub if hub is not None else get_hub(),
            registry, slo=slo_monitor, dump_dir=flight_dir,
        )

    if not (0.0 <= poison_rate <= 1.0):
        raise ConfigError(
            f"poison_rate must be in [0, 1], got {poison_rate}"
        )
    chaos = fault_spec is not None or poison_rate > 0
    plan = None
    if chaos:
        specs = (
            parse_fault_spec(fault_spec, seed=fault_seed).specs
            if fault_spec is not None else ()
        )
        if poison_rate > 0:
            specs = specs + (FaultSpec(
                site="dispatch", kind="device_error",
                poison=POISON_SIGNATURE,
            ),)
        plan = FaultPlan(specs, seed=fault_seed)
    if resilience is None:
        resilience = chaos
    policy = (
        ResiliencePolicy(
            retry=RetryPolicy(seed=fault_seed),
            breaker_reset_s=breaker_reset_s,
        )
        if resilience else None
    )

    engine = MatvecEngine(
        a, mesh, strategy=strategy_name, kernel=kernel, combine=combine,
        stages=stages, dtype_storage=dtype_storage, dtype=dtype,
        max_bucket=max_bucket, promote=promote,
        donate=donate, metrics=registry, trace_jsonl=trace_jsonl,
        fault_plan=plan, resilience=policy, integrity_gate=integrity_gate,
    )
    latency_hist = registry.histogram(
        "serve_e2e_latency_ms",
        "steady-phase submit-entry to materialized-result host time",
        window=max(n_requests, 1),
    )
    fail_counter = (
        registry.counter(
            "serve_failed_requests_total",
            "steady-phase requests whose result() raised a fault "
            "(deadline failures counted separately)",
        )
        if chaos else None
    )
    # The availability denominator: STEADY-PHASE offered requests. The
    # obs `resilience` panel divides failures by this — engine_requests_
    # total would also count warmup submits and overstate availability on
    # uncoalesced runs.
    req_counter = (
        registry.counter(
            "serve_requests_total",
            "steady-phase offered requests (the availability denominator)",
        )
        if chaos else None
    )
    pool = _request_pool(k, widths, engine.dtype, seed=seed + 1)
    rng = np.random.default_rng(seed + 2)
    sequence = [int(w) for w in rng.choice(list(pool), size=n_requests)]
    blocks = [
        pool[w] if pool[w].shape[1] > 1 else pool[w][:, 0]
        for w in sequence
    ]
    if poison_rate > 0:
        # Seeded poison set: copies (the pool blocks are shared across
        # requests) with the signature planted where the poison fault
        # spec looks for it — row 0.
        poison_rng = np.random.default_rng(seed + 4)
        n_poisoned = max(1, int(round(poison_rate * n_requests)))
        for i in poison_rng.choice(n_requests, size=n_poisoned, replace=False):
            block = np.array(blocks[i])
            block[0] = engine.dtype.type(POISON_SIGNATURE)
            blocks[i] = block

    scheduler = (
        ArrivalWindowScheduler(
            engine, window_ms=window_ms, max_window_ms=max_window_ms,
            flush_width=flush_width,
        )
        if coalesce else None
    )
    submit = scheduler.submit if scheduler is not None else engine.submit
    try:
        # ---- warmup: the whole ladder — coalesced widths are emergent,
        # so every bucket a flush could land on must be compiled AND run
        # once (first execution of an AOT program carries one-time costs
        # a p99 must not absorb). Chaos spares warmup: the plan is
        # disarmed here and armed at the steady phase, so fault event
        # ordinals start at zero at a deterministic point ----
        from ..engine import bucket_ladder

        if plan is not None:
            plan.disarm()
        engine.warmup()
        _drain([engine.submit(pool[w]) for w in sorted(set(sequence))])
        if engine.b_star is not None:
            warm_rng = np.random.default_rng(seed + 9)
            _drain([
                engine.submit(
                    warm_rng.uniform(0, 10, (k, b)).astype(engine.dtype)
                )
                for b in bucket_ladder(max_bucket) if b >= engine.b_star
            ])
        warm_stats = engine.stats
        compiles_warmup = warm_stats.compiles
        if plan is not None:
            plan.arm()
        if slo_monitor is not None:
            # Phase boundary: the window baseline. Sampled BEFORE the
            # offered-request counter bumps so the steady window sees
            # the full offered/failed deltas.
            slo_monitor.sample()
        if recorder is not None:
            recorder.snapshot_metrics()
        if req_counter is not None:
            req_counter.inc(n_requests)

        # ---- steady phase under load ----
        if arrival == "closed":
            wall = _closed_loop(
                submit, blocks, concurrency, latency_hist,
                fail_counter=fail_counter,
            )
        else:
            gaps = _arrival_gaps(
                arrival, n_requests, rate, burst,
                np.random.default_rng(seed + 3),
            )
            wall = _open_loop(
                submit, blocks, gaps, latency_hist,
                flush=scheduler.flush if scheduler is not None else None,
                fail_counter=fail_counter,
            )
        steady_stats = engine.stats
        if scheduler is not None:
            sched_stats = scheduler.stats
            mean_batch_width = sched_stats.mean_batch_width
            coalesce_ratio = sched_stats.coalesce_ratio
        else:
            mean_batch_width = coalesce_ratio = float("nan")
    finally:
        if scheduler is not None:
            scheduler.close()
    if plan is not None:
        for spec in plan.summary()["specs"]:
            if spec["site"] == "compile" and spec["matched"] == 0:
                # Warmup pre-compiles every preferred ExecKey while the
                # plan is disarmed, so a compile spec aimed at a
                # preferred config never sees an event — the run would
                # silently measure nothing at that site.
                print(
                    "WARNING: compile fault spec "
                    f"(key={spec['key']!r}) matched 0 events — warmup "
                    "pre-compiles preferred configs; compile faults "
                    "only fire for executables first compiled in the "
                    "steady phase (fallback tiers, shrunken buckets)",
                    file=sys.stderr,
                )
    if trace_jsonl is not None:
        if not engine.flush_traces():
            print(
                f"WARNING: trace sink could not confirm {trace_jsonl} — "
                "the file is missing or incomplete", file=sys.stderr,
            )
        engine.close()
    if slo_monitor is not None:
        slo_monitor.sample()  # the post-steady observation
    if recorder is not None:
        recorder.snapshot_metrics()
        recorder.close()
    if slo_out is not None:
        path = Path(slo_out)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(
            json.dumps(slo_monitor.evaluate(), indent=2) + "\n"
        )
    if hub is not None:
        if not hub.flush():
            print(
                f"WARNING: event sink could not confirm {events_jsonl} — "
                "the file is missing or incomplete", file=sys.stderr,
            )
        hub.close()
    snap_counters = registry.snapshot()["counters"]
    if metrics_out is not None:
        _ = engine.stats  # refresh the in_flight gauge before exporting
        if chaos or resilience:
            engine.health()  # refresh the breaker gauge the same way
        path = Path(metrics_out)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(registry.snapshot(), indent=2) + "\n")
    return ServeResult(
        n_rows=m,
        n_cols=k,
        n_devices=int(mesh.devices.size),
        strategy=strategy_name,
        dtype=str(engine.dtype),
        kernel=kernel if isinstance(kernel, str) else "custom",
        combine=combine or "default",
        b_star=engine.b_star,
        max_bucket=max_bucket,
        n_requests=n_requests,
        total_cols=int(sum(sequence)),
        wall_s=wall,
        p50_dispatch_ms=latency_hist.percentile(50),
        p99_dispatch_ms=latency_hist.percentile(99),
        compiles_warmup=compiles_warmup,
        compiles_steady=steady_stats.compiles - compiles_warmup,
        hits_steady=steady_stats.hits - warm_stats.hits,
        promo_b=0,
        promo_gemm_s=float("nan"),
        promo_seq_s=float("nan"),
        arrival=arrival,
        rate_req_s=rate if arrival != "closed" else float("nan"),
        concurrency=concurrency,
        coalesce=int(coalesce),
        mean_batch_width=mean_batch_width,
        coalesce_ratio=coalesce_ratio,
        failed_requests=snap_counters.get("serve_failed_requests_total", 0),
        retries=snap_counters.get("resil_retries_total", 0),
        downgrades=snap_counters.get("resil_downgrades_total", 0),
        dtype_storage=engine.storage,
        resident_bytes=engine.resident_bytes,
    )


# ---------------------------------------------------------- multi-tenant
#
# The trace mode for the matrix registry (engine/registry.py;
# docs/MULTITENANT.md): N tenants' matrices served against one HBM
# budget under Zipf-distributed tenant popularity — the skew real
# multi-tenant traffic has, so eviction policy is measured under the
# distribution it must win on, not assumed. One CSV row per tenant (plus
# an ALL summary row) carries the per-tenant availability, hit-rate and
# eviction columns; `lru_floor` is the same trace replayed through a
# plain-LRU simulation (pin-aware), the floor the registry's cost-aware
# policy must meet — for homogeneous tenants the two are exactly equal.

MULTITENANT_CSV_HEADER = (
    "n_rows, n_cols, n_devices, strategy, dtype, n_tenants, zipf_a, "
    "hbm_budget, budget_tenants, n_requests, wall_s, rps, hit_rate, "
    "lru_floor, global_sched, deadline_ms, deadline_expires, on_time, "
    "p50_e2e_ms, p99_e2e_ms, tenant, requests, hits, tenant_hit_rate, "
    "evictions, evictions_caused, quota_rejections, failed_requests, "
    "rejected, availability, resident_bytes, pinned"
)


@dataclasses.dataclass(frozen=True)
class TenantRow:
    """Per-tenant outcome of one multi-tenant trace (one CSV row)."""

    tenant: str
    requests: int
    hits: int
    evictions: int
    evictions_caused: int
    quota_rejections: int
    failed_requests: int
    resident_bytes: int
    pinned: int
    # Requests the global scheduler's predicted-time admission refused
    # (typed AdmissionRejectedError, pre-dispatch). Rejected ≠ failed:
    # a rejection consumed no device time and is retryable by design,
    # so it has its own column and does NOT count against availability
    # (resilience.is_rejection; docs/SCHEDULING.md).
    rejected: int = 0

    @property
    def hit_rate(self) -> float:
        return self.hits / self.requests if self.requests else float("nan")

    @property
    def availability(self) -> float:
        """Fraction of this tenant's offered requests that neither
        faulted nor expired (quota rejections, deadline expires and
        fault failures all count against it — the tenant-visible
        downtime). Admission REJECTIONS do not: they are typed,
        pre-dispatch, zero-cost scheduling outcomes (``rejected``
        column), not downtime."""
        if self.requests == 0:
            return float("nan")
        return (self.requests - self.failed_requests) / self.requests

    @property
    def served_rate(self) -> float:
        """Fraction of offered requests that actually returned a result
        (failures AND rejections both subtracted) — the honesty check
        next to ``availability``: a scheduler cannot buy availability by
        rejecting everything without this column collapsing."""
        if self.requests == 0:
            return float("nan")
        return (
            self.requests - self.failed_requests - self.rejected
        ) / self.requests


@dataclasses.dataclass(frozen=True)
class MultiTenantResult:
    """One multi-tenant trace: run-level fields plus the per-tenant rows
    (``rows`` ends with the aggregate ``ALL`` row)."""

    n_rows: int
    n_cols: int
    n_devices: int
    strategy: str
    dtype: str
    n_tenants: int
    zipf_a: float
    hbm_budget: int           # 0 = unlimited
    budget_tenants: int       # payloads that fit (meaningful when
                              # hbm_budget > 0; a sub-payload budget is 0)
    n_requests: int
    wall_s: float
    hit_rate: float           # registry-wide: hits / submits
    lru_floor: float          # plain-LRU replay of the same trace
    rows: tuple[TenantRow, ...]
    # Global-scheduler A/B columns (--global-sched; docs/SCHEDULING.md).
    # deadline_expires counts requests that expired in an ENGINE gate
    # (pre-dispatch deadline failures) — the failure mode predicted-time
    # admission converts into typed rejects; the acceptance gate pins it
    # at 0 with scheduling on. p50/p99 are end-to-end (scheduled arrival
    # to materialized result) over SERVED requests; NaN without a
    # deadline overlay.
    global_sched: bool = False
    deadline_ms: float = float("nan")
    deadline_expires: int = 0
    p50_e2e_ms: float = float("nan")
    p99_e2e_ms: float = float("nan")
    # SLO goodput: served requests whose end-to-end latency (scheduled
    # arrival -> materialized result) landed INSIDE the deadline. The
    # honest A/B numerator — a late serve burned device time for an
    # answer nobody was waiting for, and a scheduler cannot win this
    # column by rejecting everything.
    on_time: int = 0

    @property
    def rps(self) -> float:
        return self.n_requests / self.wall_s if self.wall_s > 0 else float("nan")


def multitenant_csv_path(strategy: str, root=None):
    from .metrics import out_dir

    return out_dir(root) / f"serve_tenants_{strategy}.csv"


def append_multitenant_result(result: MultiTenantResult, root=None):
    from ..parallel.distributed import is_main_process
    from .metrics import _append_row

    path = multitenant_csv_path(result.strategy, root)
    if not is_main_process():
        return path
    prefix = (
        f"{result.n_rows}, {result.n_cols}, {result.n_devices}, "
        f"{result.strategy}, {result.dtype}, {result.n_tenants}, "
        f"{result.zipf_a:.3f}, {result.hbm_budget}, "
        f"{result.budget_tenants}, {result.n_requests}, "
        f"{result.wall_s:.6f}, {result.rps:.2f}, {result.hit_rate:.4f}, "
        f"{result.lru_floor:.4f}, {int(result.global_sched)}, "
        f"{result.deadline_ms:.3f}, {result.deadline_expires}, "
        f"{result.on_time}, "
        f"{result.p50_e2e_ms:.4f}, {result.p99_e2e_ms:.4f}"
    )
    for row in result.rows:
        _append_row(
            path, MULTITENANT_CSV_HEADER,
            f"{prefix}, {row.tenant}, {row.requests}, {row.hits}, "
            f"{row.hit_rate:.4f}, {row.evictions}, {row.evictions_caused}, "
            f"{row.quota_rejections}, {row.failed_requests}, "
            f"{row.rejected}, {row.availability:.4f}, "
            f"{row.resident_bytes}, {row.pinned}",
        )
    return path


def parse_hbm_budget(text: str | None, payload_bytes: int) -> int | None:
    """``--hbm-budget`` grammar: plain bytes (``2097152``), or a payload
    multiple (``2.5x`` = room for 2.5 tenants of this run's shape — the
    shape-independent spelling the tier-1 smoke and demo use). None/0 =
    unlimited."""
    if text is None:
        return None
    text = str(text).strip()
    if text.endswith(("x", "X")):
        mult = float(text[:-1])
        budget = int(mult * payload_bytes)
    else:
        budget = int(float(text))
    if budget < 0:
        raise ConfigError(f"hbm budget must be >= 0, got {text!r}")
    return budget or None


def parse_tenant_quota(text: str | None) -> dict[str, int] | int | None:
    """``--tenant-quota`` grammar: a bare int (every tenant's
    ``max_in_flight``) or ``tenant-0=4,tenant-3=8`` (named tenants only —
    the chaos overlay's quota-pressure-on-one-tenant spelling)."""
    if text is None:
        return None
    text = text.strip()
    if "=" not in text:
        return int(text)
    quotas: dict[str, int] = {}
    for item in text.split(","):
        if "=" not in item:
            raise ConfigError(
                f"tenant quota item {item!r} must be tenant=max_in_flight"
            )
        tid, value = (s.strip() for s in item.split("=", 1))
        quotas[tid] = int(value)
    return quotas


def _zipf_probs(n_tenants: int, zipf_a: float) -> np.ndarray:
    """Bounded Zipf over tenant ranks: ``p(i) ∝ (i+1)^-a`` — rank 0 is
    the hottest tenant."""
    ranks = np.arange(1, n_tenants + 1, dtype=np.float64)
    probs = ranks ** -float(zipf_a)
    return probs / probs.sum()


def lru_hit_floor(
    tenant_seq: Sequence[int], capacity: int | None,
    pinned: Sequence[int] = (),
) -> float:
    """Replay the tenant sequence through plain LRU with ``capacity``
    resident slots (None = unlimited; 0 = a real budget too small for
    one payload — every unpinned access misses) and a pre-admitted pinned set
    (pins consume slots and always hit) — the hit-rate floor the
    registry's cost-aware policy must meet on the same trace. For
    homogeneous tenants the registry's score reduces to exactly LRU, so
    measured == floor there; a cost-aware win on heterogeneous fleets
    shows up as measured > floor."""
    if not len(tenant_seq):
        return float("nan")
    pinned_set = set(pinned)
    slots = (
        None if capacity is None else max(0, capacity - len(pinned_set))
    )
    resident: list[int] = []  # LRU order: least-recent first
    hits = 0
    for t in tenant_seq:
        if t in pinned_set:
            hits += 1
            continue
        if t in resident:
            hits += 1
            resident.remove(t)
        elif slots is not None and slots == 0:
            continue  # every slot pinned: perpetual (counted) overshoot
        elif slots is not None and len(resident) >= slots:
            resident.pop(0)
        resident.append(t)
    return hits / len(tenant_seq)


def run_serve_multitenant(
    strategy_name: str,
    mesh,
    m: int,
    k: int,
    *,
    dtype: str = "float32",
    kernel: str = "xla",
    combine: str | None = None,
    stages: int | None = None,
    dtype_storage: str | None = None,
    n_tenants: int = 8,
    zipf_a: float = 1.1,
    hbm_budget: str | int | None = None,
    pin_hot: int = 0,
    tenant_quota: str | int | dict | None = None,
    n_requests: int = 200,
    max_bucket: int = 32,
    promote: str | int | None = None,
    donate: bool = True,
    seed: int = 0,
    metrics_out: str | None = None,
    fault_spec: str | None = None,
    fault_seed: int = 0,
    poison_rate: float = 0.0,
    poison_tenant: str | None = None,
    integrity_gate: bool = False,
    resilience: bool | None = None,
    breaker_reset_s: float = 30.0,
    global_sched: bool = False,
    deadline_ms: float | None = None,
    rate: float | None = None,
    max_in_flight: int | None = None,
    demand_weight: float = 0.0,
    deadline_margin: float = 1.0,
    decision_jsonl: str | None = None,
    reshard: str = "off",
    reshard_cooldown_s: float = 30.0,
    reshard_horizon_s: float = 30.0,
) -> MultiTenantResult:
    """Run the multi-tenant trace protocol for one (strategy, shape,
    mesh) config: ``n_tenants`` seeded matrices registered against
    ``hbm_budget``, driven by a Zipf(``zipf_a``) tenant-popularity trace
    of ``n_requests`` vector requests. Submits are issued in trace
    order and materialized at the end — outstanding futures are what the
    ``max_in_flight`` quotas meter, and eviction under in-flight work is
    exactly the hazard the refcounted-residency doctrine covers.

    Chaos overlay: ``fault_spec`` patterns may target one tenant
    (``key=tenant-0/*``), ``tenant_quota`` may throttle one tenant, and
    ``poison_rate``/``poison_tenant`` plant the persistent poison
    payload signature on a seeded fraction of one tenant's requests
    (every tenant's when ``poison_tenant`` is None) — the isolation
    acceptance asserts the OTHER tenants' availability columns stay at
    1.0.

    Global-scheduler A/B (``global_sched``; docs/SCHEDULING.md): route
    every submit through a :class:`~..engine.GlobalScheduler` over the
    same registry — predicted-time admission, cross-tenant interleaving
    and coalescing, demand-aware eviction (``demand_weight``) — against
    the greedy baseline on the SAME seeded trace. Per-tenant
    ``requests``/``availability`` columns stay offered-trace-based in
    both arms; the registry-side ``hits`` column counts DISPATCHES, so
    in the (deadline-free) classic protocol a coalesced flush of b
    same-group requests contributes one hit, not b — compare hit-rate
    across arms only on the deadline overlay (which flushes per
    request) or with coalescing accounted for. With ``deadline_ms``
    the trace becomes an SLO overlay: arrivals are paced at ``rate``
    req/s (a burst when None), each request's deadline is anchored at
    its SCHEDULED arrival (loop lag consumes deadline budget — the
    overload signal), results are drained concurrently, and the result
    carries end-to-end p50/p99 over served requests plus the
    ``deadline_expires``/``rejected`` split. ``max_in_flight`` arms the
    engines' backpressure gate so overload queues instead of enqueueing
    unboundedly — the greedy failure mode admission control deletes.
    ``reshard="auto"`` additionally arms the scheduler's online-
    resharding crossover trigger (docs/RESHARDING.md); the dedicated
    drifting-shape A/B protocol lives in :func:`run_reshard_drift`."""
    from ..utils.io import generate_matrix

    if n_tenants < 1:
        raise ConfigError(f"n_tenants must be >= 1, got {n_tenants}")
    if not (0 <= pin_hot <= n_tenants):
        raise ConfigError(
            f"pin_hot must be in [0, {n_tenants}], got {pin_hot}"
        )
    if not (0.0 <= poison_rate <= 1.0):
        raise ConfigError(
            f"poison_rate must be in [0, 1], got {poison_rate}"
        )
    registry_metrics = MetricsRegistry()
    chaos = fault_spec is not None or poison_rate > 0
    specs = (
        parse_fault_spec(fault_spec, seed=fault_seed).specs
        if fault_spec is not None else ()
    )
    if poison_rate > 0:
        # Poison faults stay payload-scoped (never open breakers); the
        # key narrows the blast radius to the targeted tenant's labels.
        specs = specs + (FaultSpec(
            site="dispatch", kind="device_error",
            poison=POISON_SIGNATURE,
            key=f"{poison_tenant}/*" if poison_tenant else "*",
        ),)
    plan = FaultPlan(specs, seed=fault_seed) if specs else None
    if resilience is None:
        resilience = chaos
    policy = (
        ResiliencePolicy(
            retry=RetryPolicy(seed=fault_seed),
            breaker_reset_s=breaker_reset_s,
        )
        if resilience else None
    )
    payload_probe = generate_matrix(m, k, seed=seed).astype(dtype)
    budget = parse_hbm_budget(
        hbm_budget,
        # Budget multiples are in NATIVE payloads; quantized tenants'
        # real payload bytes land in the accountant either way.
        int(payload_probe.nbytes),
    )
    quotas = parse_tenant_quota(tenant_quota) if isinstance(
        tenant_quota, str
    ) else tenant_quota

    registry = MatrixRegistry(
        mesh,
        hbm_budget=budget,
        demand_weight=demand_weight,
        metrics=registry_metrics,
        fault_plan=plan,
        resilience=policy,
        integrity_gate=integrity_gate,
        strategy=strategy_name, kernel=kernel, combine=combine,
        stages=stages, dtype_storage=dtype_storage, dtype=dtype,
        max_bucket=max_bucket, promote=promote, donate=donate,
        max_in_flight=max_in_flight,
    )
    tenant_ids = [f"tenant-{i}" for i in range(n_tenants)]
    payload_bytes = 0
    try:
        for i, tid in enumerate(tenant_ids):
            if isinstance(quotas, dict):
                q = quotas.get(tid)
            else:
                q = quotas
            registry.register(
                tid,
                generate_matrix(m, k, seed=seed + i).astype(dtype),
                quota=TenantQuota(max_in_flight=q) if q else None,
            )
            if i == 0:
                payload_bytes = registry.health()["tenants"][tid][
                    "payload_bytes"
                ]

        # ---- warmup: compile the shared executable set once (no
        # residency needed), spare it from the chaos plan ----
        if plan is not None:
            plan.disarm()
        registry.warmup(widths=[1])
        if plan is not None:
            plan.arm()
        for i in range(pin_hot):
            registry.pin(tenant_ids[i])

        # ---- the Zipf trace ----
        rng = np.random.default_rng(seed + 2)
        tenant_seq = rng.choice(
            n_tenants, size=n_requests, p=_zipf_probs(n_tenants, zipf_a)
        )
        xpool = [
            rng.standard_normal(k).astype(dtype) for _ in range(4)
        ]
        poison_idx: set[int] = set()
        if poison_rate > 0:
            if poison_tenant is not None and poison_tenant not in tenant_ids:
                raise ConfigError(
                    f"poison_tenant {poison_tenant!r} is not one of the "
                    f"{n_tenants} registered tenants"
                )
            target = [
                j for j, t in enumerate(tenant_seq)
                if poison_tenant is None or tenant_ids[t] == poison_tenant
            ]
            if target:
                prng = np.random.default_rng(seed + 4)
                n_poison = min(
                    len(target), max(1, round(poison_rate * len(target)))
                )
                poison_idx = set(
                    int(j) for j in
                    prng.choice(target, size=n_poison, replace=False)
                )
        gs = None
        if global_sched:
            from ..engine import GlobalScheduler

            gs = GlobalScheduler(
                registry, cost_model="auto",
                deadline_margin=deadline_margin,
                decision_jsonl=decision_jsonl,
                reshard=reshard,
                reshard_cooldown_s=reshard_cooldown_s,
                reshard_horizon_s=reshard_horizon_s,
            )
        submit = (
            gs.submit if gs is not None
            else lambda tid, x, **kw: registry.submit(tid, x, **kw)
        )
        failed = [0] * n_tenants
        rejected = [0] * n_tenants
        e2e_hist = registry_metrics.histogram(
            "serve_e2e_latency_ms",
            "scheduled-arrival to materialized-result host time over "
            "served requests (deadline overlay)",
            window=max(n_requests, 1),
        )

        on_time = [0]

        def _consume(t: int, fut, arrival: float | None) -> None:
            try:
                fut.result()
            except AdmissionRejectedError:
                rejected[t] += 1  # typed, pre-dispatch: rejected != failed
            except MatvecError:
                failed[t] += 1
            else:
                if arrival is not None:
                    lat_ms = (time.perf_counter() - arrival) * 1e3
                    e2e_hist.observe(lat_ms)
                    if deadline_ms is not None and lat_ms <= deadline_ms:
                        on_time[0] += 1  # SLO goodput, not just served

        start = time.perf_counter()
        if deadline_ms is None:
            # Classic protocol: submit in trace order, materialize once.
            futures: list[tuple[int, object]] = []
            for j, t in enumerate(tenant_seq):
                x = xpool[j % len(xpool)]
                if j in poison_idx:
                    x = np.array(x)
                    x[0] = x.dtype.type(POISON_SIGNATURE)
                try:
                    futures.append((int(t), submit(tenant_ids[t], x)))
                except MatvecError:
                    # Uncoalesced dispatch faults surface at submit; the
                    # trace keeps going — availability is the measurement.
                    failed[t] += 1
            if gs is not None:
                gs.flush()  # close the open coalescing batch pre-drain
            for t, fut in futures:
                _consume(t, fut, None)
        else:
            # SLO overlay: paced arrivals, deadlines anchored at the
            # SCHEDULED arrival (loop lag consumes deadline budget — the
            # overload signal), results drained concurrently so e2e
            # latency is per-request, not drain-order.
            gap_s = (1.0 / rate) if rate else 0.0
            results: queue.Queue = queue.Queue()

            def drainer() -> None:
                while True:
                    item = results.get()
                    if item is None:
                        return
                    _consume(*item)

            drain_thread = threading.Thread(target=drainer, daemon=True)
            drain_thread.start()
            for j, t in enumerate(tenant_seq):
                x = xpool[j % len(xpool)]
                if j in poison_idx:
                    x = np.array(x)
                    x[0] = x.dtype.type(POISON_SIGNATURE)
                arrival = start + j * gap_s
                while True:
                    now = time.perf_counter()
                    if now >= arrival:
                        break
                    time.sleep(min(arrival - now, 5e-4))
                remaining = (
                    arrival + deadline_ms / 1e3 - time.perf_counter()
                ) * 1e3
                try:
                    fut = submit(
                        tenant_ids[t], x, deadline_ms=remaining
                    )
                except MatvecError:
                    failed[t] += 1
                    continue
                results.put((int(t), fut, arrival))
            results.put(None)
            drain_thread.join()
        wall = time.perf_counter() - start
        if gs is not None:
            gs.close()

        health = registry.health()
        if metrics_out is not None:
            path = Path(metrics_out)
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(
                json.dumps(registry_metrics.snapshot(), indent=2) + "\n"
            )
    finally:
        registry.close()

    # capacity 0 with a budget set is a REAL (sub-payload) budget, not
    # unlimited — the floor sim and the summary line keep the two apart.
    capacity = (budget // payload_bytes) if budget else 0
    floor = lru_hit_floor(
        tenant_seq, capacity if budget else None, pinned=range(pin_hot)
    )
    offered = np.bincount(tenant_seq, minlength=n_tenants)
    rows = []
    for i, tid in enumerate(tenant_ids):
        stat = health["tenants"][tid]
        rows.append(TenantRow(
            tenant=tid,
            requests=int(offered[i]),
            hits=stat["hits"],
            evictions=stat["evictions"],
            evictions_caused=stat["evictions_caused"],
            quota_rejections=stat["quota_rejections"],
            failed_requests=failed[i],
            rejected=rejected[i],
            resident_bytes=stat["resident_bytes"],
            pinned=int(stat["pinned"]),
        ))
    rows.append(TenantRow(
        tenant="ALL",
        requests=n_requests,
        hits=sum(r.hits for r in rows),
        evictions=sum(r.evictions for r in rows),
        evictions_caused=sum(r.evictions_caused for r in rows),
        quota_rejections=sum(r.quota_rejections for r in rows),
        failed_requests=sum(r.failed_requests for r in rows),
        rejected=sum(r.rejected for r in rows),
        resident_bytes=health["hbm"]["charged_bytes"],
        pinned=pin_hot,
    ))
    all_row = rows[-1]
    counters = registry_metrics.snapshot()["counters"]
    return MultiTenantResult(
        n_rows=m, n_cols=k, n_devices=int(mesh.devices.size),
        strategy=strategy_name, dtype=dtype,
        n_tenants=n_tenants, zipf_a=float(zipf_a),
        hbm_budget=budget or 0, budget_tenants=capacity,
        n_requests=n_requests, wall_s=wall,
        hit_rate=(
            all_row.hits / n_requests if n_requests else float("nan")
        ),
        lru_floor=floor,
        rows=tuple(rows),
        global_sched=global_sched,
        deadline_ms=(
            float(deadline_ms) if deadline_ms is not None else float("nan")
        ),
        # Engine-gate deadline failures: the expire-after-queueing
        # failure mode. Warmup submits carry no deadlines, so the total
        # is the steady phase's.
        deadline_expires=counters.get("engine_deadline_failures_total", 0),
        on_time=on_time[0],
        p50_e2e_ms=e2e_hist.percentile(50),
        p99_e2e_ms=e2e_hist.percentile(99),
    )


# ---- the drifting-shape online-resharding A/B (docs/RESHARDING.md) ----

RESHARD_AB_CSV_HEADER = (
    "m, k, p, strategy, dtype, reshard, n_tenants, zipf_a, n_requests, "
    "rollover, steady_skip, width_steady, wall_s, p50_pre_ms, "
    "p99_pre_ms, p50_steady_ms, p99_steady_ms, reshards, reshard_bytes, "
    "compiles_total, compiles_steady, last_reshard_at, final_strategies"
)


def reshard_csv_path(root=None):
    from .metrics import out_dir

    return out_dir(root) / "reshard_ab.csv"


def append_reshard_result(result: dict, root=None):
    from ..parallel.distributed import is_main_process
    from .metrics import _append_row

    path = reshard_csv_path(root)
    if not is_main_process():
        return path
    r = result
    finals = "|".join(
        f"{tid}:{s}" for tid, s in sorted(r["final_strategies"].items())
    )
    _append_row(
        path, RESHARD_AB_CSV_HEADER,
        f"{r['m']}, {r['k']}, {r['p']}, {r['strategy']}, {r['dtype']}, "
        f"{r['reshard']}, {r['n_tenants']}, {r['zipf_a']:.3f}, "
        f"{r['n_requests']}, {r['rollover']}, {r['steady_skip']}, "
        f"{r['width_steady']}, {r['wall_s']:.6f}, "
        f"{r['p50_pre_ms']:.4f}, {r['p99_pre_ms']:.4f}, "
        f"{r['p50_steady_ms']:.4f}, {r['p99_steady_ms']:.4f}, "
        f"{r['reshards']}, {r['reshard_bytes']}, {r['compiles_total']}, "
        f"{r['compiles_steady']}, {r['last_reshard_at']}, {finals}",
    )
    return path


def run_reshard_drift(
    strategy_name: str,
    mesh,
    m: int,
    k: int,
    *,
    dtype: str = "float32",
    kernel: str = "xla",
    n_tenants: int = 3,
    zipf_a: float = 1.1,
    n_requests: int = 200,
    rollover: int = 24,
    width_steady: int = 8,
    pre_rate: float = 6.0,
    steady_skip: int = 48,
    seed: int = 0,
    reshard: str = "off",
    reshard_cooldown_s: float = 30.0,
    reshard_horizon_s: float = 0.5,
    rate_tau_s: float = 0.1,
    metrics_out: str | None = None,
    decision_jsonl: str | None = None,
) -> dict:
    """The ``--reshard auto|off`` A/B protocol (docs/RESHARDING.md): a
    Zipf fleet registered in ``strategy_name`` serves a trace whose
    SHAPE drifts at the ``rollover`` index — width-1 vector requests
    trickling at ``pre_rate`` req/s before it, closed-loop
    ``width_steady``-column blocks after it. Registering in a layout
    the cost model scores poorly for the steady shape (the study script
    picks the predicted-worst) puts the fleet on the wrong side of the
    crossover surface the moment the shape drifts; with
    ``reshard="auto"`` the :class:`~..engine.GlobalScheduler` trigger
    migrates each tenant on-device once its EWMA demand amortizes the
    collectives, with ``"off"`` the fleet stays frozen in the
    registered layout — same seeded trace, so the steady-state
    percentile columns are directly comparable.

    Measurement discipline: every request is closed-loop (submit then
    materialize), so per-request e2e latency is service time, not
    drain-order artifact. The steady window opens ``steady_skip``
    requests after the rollover — wide enough that the one-time
    migration (and its ``warm_widths`` new-layout compile) lands inside
    the skip, which the ``compiles_steady == 0`` gate then enforces:
    post-migration steady state must replay warm executables only.
    ``last_reshard_at`` (request index of the last migration, -1 when
    none) lets the caller assert the migrations really did land before
    the window. The pre-phase trickle is the drift's OTHER half: at
    ``pre_rate`` below ``1 / reshard_horizon_s`` the amortization
    damper holds the trigger off, so the migration is attributable to
    the demand+shape drift, not to registration-time misprediction."""
    from ..utils.io import generate_matrix

    if reshard not in ("auto", "off"):
        raise ConfigError(
            f"reshard must be 'auto' or 'off', got {reshard!r}"
        )
    if not (0 < rollover < n_requests):
        raise ConfigError(
            f"rollover must be in (0, {n_requests}), got {rollover}"
        )
    if rollover + steady_skip >= n_requests:
        raise ConfigError(
            f"steady window is empty: rollover={rollover} + "
            f"steady_skip={steady_skip} >= n_requests={n_requests}"
        )
    registry_metrics = MetricsRegistry()
    registry = MatrixRegistry(
        mesh,
        metrics=registry_metrics,
        rate_tau_s=rate_tau_s,
        strategy=strategy_name, kernel=kernel, dtype=dtype,
        max_bucket=max(width_steady, 1),
    )
    tenant_ids = [f"tenant-{i}" for i in range(n_tenants)]
    gs = None
    try:
        for i, tid in enumerate(tenant_ids):
            registry.register(
                tid, generate_matrix(m, k, seed=seed + i).astype(dtype)
            )
        # Warmup covers BOTH trace widths in the REGISTERED layout, so
        # the frozen arm's wide compile lands here, not in its steady
        # window — the compiles_steady gate must be symmetric.
        registry.warmup(widths=[1, width_steady])

        from ..engine import GlobalScheduler

        gs = GlobalScheduler(
            registry, cost_model="auto",
            decision_jsonl=decision_jsonl,
            reshard=reshard,
            reshard_cooldown_s=reshard_cooldown_s,
            reshard_horizon_s=reshard_horizon_s,
        )
        rng = np.random.default_rng(seed + 2)
        tenant_seq = rng.choice(
            n_tenants, size=n_requests, p=_zipf_probs(n_tenants, zipf_a)
        )
        xpool = [rng.standard_normal(k).astype(dtype) for _ in range(4)]
        xbpool = [
            rng.standard_normal((k, width_steady)).astype(dtype)
            for _ in range(4)
        ]
        counters0 = registry_metrics.snapshot()["counters"]
        compiles_warm = counters0.get("engine_compiles_total", 0)
        compiles_at_window = None
        lat_ms = np.zeros(n_requests)
        reshards_seen = 0
        last_reshard_at = -1
        gap_s = (1.0 / pre_rate) if pre_rate else 0.0
        start = time.perf_counter()
        for j, t in enumerate(tenant_seq):
            if j < rollover:
                # Pre-drift trickle: paced arrivals hold the EWMA
                # below the amortization threshold.
                arrival = start + j * gap_s
                while True:
                    now = time.perf_counter()
                    if now >= arrival:
                        break
                    time.sleep(min(arrival - now, 5e-4))
                x = xpool[j % len(xpool)]
            else:
                x = xbpool[j % len(xbpool)]
            if j == rollover + steady_skip:
                compiles_at_window = registry_metrics.snapshot()[
                    "counters"
                ].get("engine_compiles_total", 0)
            t0 = time.perf_counter()
            y = gs.submit(tenant_ids[t], x)
            np.asarray(y.result())  # closed loop: e2e IS service time
            lat_ms[j] = (time.perf_counter() - t0) * 1e3
            n_resh = registry_metrics.snapshot()["counters"].get(
                "registry_reshards_total", 0
            )
            if n_resh > reshards_seen:
                reshards_seen = n_resh
                last_reshard_at = j
        wall = time.perf_counter() - start
        counters = registry_metrics.snapshot()["counters"]
        compiles_total = counters.get(
            "engine_compiles_total", 0
        ) - compiles_warm
        if compiles_at_window is None:  # degenerate: window at trace end
            compiles_at_window = counters.get("engine_compiles_total", 0)
        health = registry.health()
        finals = {
            tid: health["tenants"][tid]["strategy"] for tid in tenant_ids
        }
        if metrics_out is not None:
            path = Path(metrics_out)
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(
                json.dumps(registry_metrics.snapshot(), indent=2) + "\n"
            )
    finally:
        if gs is not None:
            gs.close()
        registry.close()

    pre = lat_ms[:rollover]
    steady = lat_ms[rollover + steady_skip:]
    return {
        "m": m, "k": k, "p": int(mesh.devices.size),
        "strategy": strategy_name, "dtype": dtype, "reshard": reshard,
        "n_tenants": n_tenants, "zipf_a": float(zipf_a),
        "n_requests": n_requests, "rollover": rollover,
        "steady_skip": steady_skip, "width_steady": width_steady,
        "wall_s": wall,
        "p50_pre_ms": float(np.percentile(pre, 50)),
        "p99_pre_ms": float(np.percentile(pre, 99)),
        "p50_steady_ms": float(np.percentile(steady, 50)),
        "p99_steady_ms": float(np.percentile(steady, 99)),
        "reshards": counters.get("registry_reshards_total", 0),
        "reshard_bytes": counters.get("reshard_bytes_total", 0),
        "compiles_total": compiles_total,
        "compiles_steady": counters.get("engine_compiles_total", 0)
        - compiles_at_window,
        "last_reshard_at": last_reshard_at,
        "final_strategies": finals,
    }


def run_serve(
    strategy_name: str,
    mesh,
    m: int,
    k: int,
    *,
    dtype: str = "float32",
    kernel: str = "xla",
    combine: str | None = None,
    stages: int | None = None,
    dtype_storage: str | None = None,
    n_requests: int = 200,
    max_bucket: int = 32,
    widths: Sequence[int] | None = None,
    promote: str | int | None = "auto",
    donate: bool = True,
    seed: int = 0,
    promo_reps: int = 20,
    metrics_out: str | None = None,
    trace_jsonl: str | None = None,
    rtol: float | None = None,
) -> ServeResult:
    """Run the serve protocol for one (strategy, shape, mesh) config.

    ``metrics_out``: write the run's metrics snapshot (engine counters +
    the steady-phase dispatch-latency histogram, one registry) as JSON.
    ``trace_jsonl``: stream every request's span tree to a JSONL file
    (flushed before return, so the file is complete when this returns).
    ``rtol``: per-request tolerance forwarded to every steady-phase
    ``submit()`` — with ``dtype_storage="speculate"`` armed this routes
    the stream through the int8c speculative tier (escalating only on a
    failed on-device check); ``None`` keeps every request exact/native.
    """
    from ..utils.io import generate_matrix

    if widths is None:
        widths = [w for w in DEFAULT_WIDTH_MIX if w <= max_bucket]
    a = generate_matrix(m, k, seed=seed).astype(dtype)
    # One registry for the whole config: the engine's counters and the
    # serve protocol's own latency histogram land in the same snapshot.
    registry = MetricsRegistry()
    engine = MatvecEngine(
        a, mesh, strategy=strategy_name, kernel=kernel, combine=combine,
        stages=stages, dtype_storage=dtype_storage, dtype=dtype,
        max_bucket=max_bucket, promote=promote,
        donate=donate, metrics=registry, trace_jsonl=trace_jsonl,
    )
    latency_hist = registry.histogram(
        "serve_dispatch_latency_ms",
        "steady-phase submit() entry-to-return host time",
        # Window sized to the run so percentiles are exact over the WHOLE
        # steady phase — the default window would silently degrade a
        # longer stream's p50/p99 to its most recent tail.
        window=max(n_requests, 1),
    )
    pool = _request_pool(k, widths, engine.dtype, seed=seed + 1)

    # ---- warmup: cover the executable set, then fence ----
    engine.warmup(widths)
    _drain([engine.submit(pool[w]) for w in sorted(set(widths))])
    warm_stats = engine.stats
    compiles_warmup = warm_stats.compiles

    # ---- steady phase: mixed-width replay, drain once ----
    rng = np.random.default_rng(seed + 2)
    sequence = rng.choice(list(pool), size=n_requests)
    futures = []
    start = time.perf_counter()
    for w in sequence:
        t0 = time.perf_counter()
        futures.append(engine.submit(pool[int(w)], rtol=rtol))
        latency_hist.observe((time.perf_counter() - t0) * 1e3)
    _drain(futures)
    wall = time.perf_counter() - start

    steady_stats = engine.stats
    # Speculative accounting (read AFTER the drain: escalations settle at
    # result()-time, so the counters are final here).
    health = engine.health()
    speculated = int(health["counters"]["speculative_dispatches"])
    if engine.spec_resident_bytes:
        esc_rate = float(health["storage"]["escalation_rate"])
        native_stream = int(m) * int(k) * np.dtype(engine.dtype).itemsize
        spec_ratio = (
            engine.spec_resident_bytes + esc_rate * native_stream
        ) / native_stream
    else:
        esc_rate = float("nan")
        spec_ratio = float("nan")
    promo_b, promo_gemm, promo_seq = measure_promotion(
        engine, pool, n_reps=promo_reps
    )
    if trace_jsonl is not None:
        if not engine.flush_traces():
            # A dead sink thread (unwritable path) must not masquerade as
            # a successful capture.
            print(
                f"WARNING: trace sink could not confirm {trace_jsonl} — "
                "the file is missing or incomplete", file=sys.stderr,
            )
        engine.close()  # one sink thread + file handle per config: release
    if metrics_out is not None:
        _ = engine.stats  # refresh the in_flight gauge before exporting
        path = Path(metrics_out)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(registry.snapshot(), indent=2) + "\n")
    return ServeResult(
        n_rows=m,
        n_cols=k,
        n_devices=int(mesh.devices.size),
        strategy=strategy_name,
        dtype=str(engine.dtype),
        kernel=kernel if isinstance(kernel, str) else "custom",
        combine=combine or "default",
        b_star=engine.b_star,
        max_bucket=max_bucket,
        n_requests=n_requests,
        total_cols=int(sum(int(w) for w in sequence)),
        wall_s=wall,
        # The shared histogram IS the percentile implementation (no
        # private percentile math here): exact over the steady window.
        p50_dispatch_ms=latency_hist.percentile(50),
        p99_dispatch_ms=latency_hist.percentile(99),
        compiles_warmup=compiles_warmup,
        compiles_steady=steady_stats.compiles - compiles_warmup,
        hits_steady=steady_stats.hits - warm_stats.hits,
        promo_b=promo_b,
        promo_gemm_s=promo_gemm,
        promo_seq_s=promo_seq,
        dtype_storage=engine.storage,
        resident_bytes=engine.resident_bytes,
        speculated=speculated,
        escalation_rate=esc_rate,
        spec_bandwidth_ratio=spec_ratio,
    )


# -------------------------------------------------------------- solvers
#
# The answer-serving protocol (solvers/; docs/SOLVERS.md): repeated
# solves of A x = b (or eigenpair estimates) through the SAME engine
# submit path as every multiply, against a seeded diagonally-dominant
# SPD operand — valid for all five ops (CG/Chebyshev need SPD, GMRES
# nonsingular, power/Lanczos symmetric), so one generator serves the
# whole --op axis and convergence failures mean something.

SOLVER_CSV_HEADER = (
    "n, n_devices, strategy, dtype, combine, op, solver_kernel, rtol, "
    "maxiter, n_solves, iterations, final_residual, final_value, "
    "time_per_iter_ms, solve_p50_ms, solve_p99_ms, wall_s, "
    "solves_per_s, compiles_warmup, compiles_steady, divergences"
)


@dataclasses.dataclass(frozen=True)
class SolverServeResult:
    """One solver-serve measurement (one CSV row).

    ``iterations``/``final_residual``/``final_value`` are the LAST
    converged solve's telemetry (the trace is seeded, so they are
    reproducible); ``time_per_iter_ms`` is steady-phase wall time over
    total iterations, both summed over CONVERGED solves only — a
    diverged solve burns its full cap and would flatter the per-
    iteration number. Divergences are counted, never folded in.
    """

    n: int
    n_devices: int
    strategy: str
    dtype: str
    combine: str
    op: str
    solver_kernel: str
    rtol: float
    maxiter: int
    n_solves: int
    iterations: int
    final_residual: float
    final_value: float
    time_per_iter_ms: float
    solve_p50_ms: float
    solve_p99_ms: float
    wall_s: float
    compiles_warmup: int
    compiles_steady: int
    divergences: int

    @property
    def solves_per_s(self) -> float:
        if not (self.wall_s > 0):
            return float("nan")
        return self.n_solves / self.wall_s


def solver_csv_path(strategy: str, root=None):
    from .metrics import out_dir

    return out_dir(root) / f"serve_solver_{strategy}.csv"


def append_solver_result(result: SolverServeResult, root=None):
    from ..parallel.distributed import is_main_process
    from .metrics import _append_row

    path = solver_csv_path(result.strategy, root)
    if not is_main_process():
        return path
    row = (
        f"{result.n}, {result.n_devices}, {result.strategy}, "
        f"{result.dtype}, {result.combine}, {result.op}, "
        f"{result.solver_kernel}, "
        f"{result.rtol:g}, {result.maxiter}, {result.n_solves}, "
        f"{result.iterations}, {result.final_residual:.6e}, "
        f"{result.final_value:.6e}, {result.time_per_iter_ms:.4f}, "
        f"{result.solve_p50_ms:.4f}, {result.solve_p99_ms:.4f}, "
        f"{result.wall_s:.6f}, {result.solves_per_s:.2f}, "
        f"{result.compiles_warmup}, {result.compiles_steady}, "
        f"{result.divergences}"
    )
    _append_row(path, SOLVER_CSV_HEADER, row)
    return path


def solver_operand(n: int, dtype, seed: int) -> np.ndarray:
    """Seeded symmetric diagonally-dominant SPD operand: uniform(-1, 1)
    symmetrized, diagonal set to the absolute row sum plus one. Every
    Gershgorin disc then sits in [1, ·] — SPD with a bounded, shape-
    independent condition regime, valid for all five served ops. One
    diagonal entry is boosted 1.5× to isolate the dominant eigenvalue:
    without a spectral gap the eigen ops (power/lanczos) converge like
    (λ₂/λ₁)^k ≈ 1 and every solve would honestly diverge — correct
    behavior, useless benchmark."""
    rng = np.random.default_rng(seed)
    g = rng.uniform(-1.0, 1.0, (n, n))
    a = (g + g.T) / 2.0
    np.fill_diagonal(a, np.abs(a).sum(axis=1) + 1.0)
    a[0, 0] *= 1.5
    return a.astype(dtype)


def gershgorin_interval(a: np.ndarray) -> tuple[float, float]:
    """Enclosing spectral interval from Gershgorin discs — chebyshev's
    required ``interval=(λ_min, λ_max)`` without an eigendecomposition.
    Bounds, not estimates: a wider interval costs Chebyshev iterations
    but never correctness."""
    d = np.abs(np.diag(a)).astype(np.float64)
    r = np.abs(a).astype(np.float64).sum(axis=1) - d
    return float((np.diag(a) - r).min()), float((np.diag(a) + r).max())


def run_serve_solver(
    strategy_name: str,
    mesh,
    n: int,
    *,
    op: str,
    dtype: str = "float32",
    kernel: str = "xla",
    solver_kernel: str = "xla",
    combine: str | None = None,
    stages: int | None = None,
    dtype_storage: str | None = None,
    rtol: float = 1e-6,
    rtol_sweep: "Sequence[float] | None" = None,
    maxiter: int | None = None,
    restart: int | None = None,
    steps: int | None = None,
    n_solves: int = 20,
    donate: bool = True,
    seed: int = 0,
    metrics_out: str | None = None,
    trace_jsonl: str | None = None,
) -> SolverServeResult:
    """Run the solver-serve protocol for one (op, strategy, n, mesh)
    config: one warmup solve (the compile), then ``n_solves`` steady
    solves with fresh seeded right-hand sides (start vectors for the
    eigen ops), each materialized immediately — a solve's latency IS
    submit-to-answer, there is no meaningful dispatch-only number.

    The zero-recompilation criterion carries over verbatim: rtol and
    maxiter are dynamic operands, every steady solve hits the warm
    executable, and the row's ``compiles_steady`` must be 0.
    ``SolverDivergedError`` is counted and tolerated (availability is
    the measurement); any other failure aborts the run.

    ``solver_kernel`` selects the iteration tier (``"xla"`` /
    ``"pallas_fused"`` / ``"auto"`` — engine/core.py): the
    ``--solver-kernel`` A/B that measures the fused tier's
    iteration-latency floor (``data/fused_solver_demo/``).
    ``rtol_sweep`` cycles the steady solves across a tolerance ladder
    instead of one fixed rtol — every solve still hits the SAME warm
    executable (rtol is a dynamic operand), so a sweep row proves
    ``compiles_steady == 0`` across the whole ladder, not just at one
    point; the CSV's rtol column records the tightest swept value.
    """
    from ..engine.core import DEFAULT_SOLVER_MAXITER

    if op not in SOLVER_OPS:
        raise ConfigError(
            f"unknown solver op {op!r}; served ops: {SOLVER_OPS}"
        )
    a = solver_operand(n, dtype, seed)
    interval = gershgorin_interval(a) if op == "chebyshev" else None
    registry = MetricsRegistry()
    engine = MatvecEngine(
        a, mesh, strategy=strategy_name, kernel=kernel,
        solver_kernel=solver_kernel, combine=combine,
        stages=stages, dtype_storage=dtype_storage, dtype=dtype,
        donate=donate, metrics=registry, trace_jsonl=trace_jsonl,
    )
    solve_hist = registry.histogram(
        "serve_solve_latency_ms",
        "steady-phase submit-entry to materialized-answer host time",
        window=max(n_solves, 1),
    )
    rng = np.random.default_rng(seed + 1)
    rhs_pool = [
        rng.standard_normal(n).astype(engine.dtype)
        for _ in range(n_solves + 1)
    ]

    rtols = tuple(rtol_sweep) if rtol_sweep else (rtol,)

    def solve(b, i=0):
        return engine.submit(
            op=op, rhs=b, rtol=rtols[i % len(rtols)], maxiter=maxiter,
            restart=restart, steps=steps, interval=interval,
        ).result()

    # ---- warmup: one solve compiles the loop (and its verification
    # matvec) for this op's bucket; tolerate divergence the same way the
    # steady phase does — warmup's job is the executable, not the answer.
    try:
        solve(rhs_pool[-1])
    except SolverDivergedError:
        pass
    warm_stats = engine.stats
    compiles_warmup = warm_stats.compiles

    # ---- steady phase: every solve must hit the warm executable ----
    divergences = 0
    total_iters = 0
    converged_s = 0.0
    last_iters, last_resid, last_value = 0, float("nan"), float("nan")
    start = time.perf_counter()
    for i in range(n_solves):
        t0 = time.perf_counter()
        try:
            res = solve(rhs_pool[i], i)
        except SolverDivergedError:
            divergences += 1
            continue
        dt = time.perf_counter() - t0
        solve_hist.observe(dt * 1e3)
        converged_s += dt
        total_iters += res.n_iters
        last_iters = res.n_iters
        last_resid = res.residual_norm
        last_value = res.value
    wall = time.perf_counter() - start
    steady_stats = engine.stats

    if trace_jsonl is not None:
        if not engine.flush_traces():
            print(
                f"WARNING: trace sink could not confirm {trace_jsonl} — "
                "the file is missing or incomplete", file=sys.stderr,
            )
        engine.close()
    if metrics_out is not None:
        _ = engine.stats  # refresh the in_flight gauge before exporting
        path = Path(metrics_out)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(registry.snapshot(), indent=2) + "\n")
    return SolverServeResult(
        n=n,
        n_devices=int(mesh.devices.size),
        strategy=strategy_name,
        dtype=str(engine.dtype),
        combine=combine or "default",
        op=op,
        solver_kernel=solver_kernel,
        rtol=min(rtols),
        maxiter=DEFAULT_SOLVER_MAXITER if maxiter is None else int(maxiter),
        n_solves=n_solves,
        iterations=last_iters,
        final_residual=last_resid,
        final_value=last_value,
        time_per_iter_ms=(
            converged_s * 1e3 / total_iters if total_iters else float("nan")
        ),
        solve_p50_ms=solve_hist.percentile(50),
        solve_p99_ms=solve_hist.percentile(99),
        wall_s=wall,
        compiles_warmup=compiles_warmup,
        compiles_steady=steady_stats.compiles - compiles_warmup,
        divergences=divergences,
    )


def tune_serve(
    strategies: Sequence[str],
    sizes: Sequence[tuple[int, int]],
    meshes,
    dtype: str,
    *,
    max_bucket: int = 32,
    kernel: str = "xla",
    measure: str = "auto",
    min_gain: float | None = None,
    prune_margin: float | None = None,
    seed: int = 0,
    log=print,
) -> None:
    """Pre-pass for ``--tune``: populate every tuning-cache axis a serve
    config consults — local kernels, combine schedules (matvec AND gemm,
    engine construction reads both), and the promotion crossover ``b*``
    over the bucket ladder.

    ``prune_margin`` enables the cost model's predicted pre-ranking
    (``--prune-margin``; docs/COST_MODEL.md) exactly as the CLI tuner
    does: with a calibration in the cache, each axis measures only the
    candidates predicted within the margin of the predicted winner —
    the same ~40 % measurement cut, now on the serve warmup path too.
    An uncalibrated cache measures exhaustively and says so."""
    from ..engine.buckets import bucket_ladder
    from ..tuning import TuningCache, reset_cache
    from ..tuning.search import TUNE_MIN_GAIN, tune_config, tune_promotion

    if min_gain is None:
        min_gain = TUNE_MIN_GAIN
    cache = TuningCache.load()
    log(f"serve tuning pre-pass -> {cache.path}")
    buckets = tuple(b for b in bucket_ladder(max_bucket) if b >= 2)
    for m, k in sizes:
        for mesh in meshes:
            for name in strategies:
                tune_config(
                    name, mesh, m, k, dtype, cache, op="matvec",
                    kernel=kernel, measure=measure, min_gain=min_gain,
                    prune_margin=prune_margin, seed=seed, log=log,
                )
                tune_config(
                    name, mesh, m, k, dtype, cache, op="gemm",
                    n_rhs=max_bucket, kernel=kernel, measure=measure,
                    min_gain=min_gain, prune_margin=prune_margin,
                    seed=seed, log=log,
                )
                tune_promotion(
                    name, mesh, m, k, dtype, cache, buckets=buckets,
                    kernel=kernel, min_gain=min_gain,
                    prune_margin=prune_margin, seed=seed, log=log,
                )
            cache.save()
    cache.save()
    reset_cache()  # serve engines must see the fresh decisions


def run_serve_sweep(args: argparse.Namespace) -> int:
    """The ``--op serve`` driver body shared by this module's CLI and
    ``bench.sweep``. ``--annotate`` scopes the named-span override to this
    run (an in-process caller must not find the process-global flag
    flipped afterwards)."""
    from ..obs.annotations import annotations

    if getattr(args, "annotate", False):
        with annotations(True):  # named spans in every program built below
            return _run_serve_sweep(args)
    return _run_serve_sweep(args)


def _run_serve_sweep(args: argparse.Namespace) -> int:
    from ..parallel.mesh import make_mesh
    from .sweep import (
        SQUARE_SIZES,
        configure_platform,
        device_counts_available,
        resolve_strategies,
    )

    configure_platform(args.platform, args.host_devices)
    strategies = resolve_strategies(args.strategy, "matvec")
    counts = args.devices or device_counts_available()
    sizes = (
        [(s, s) for s in args.sizes] if args.sizes
        else [(s, s) for s in SQUARE_SIZES]
    )
    meshes = {n: make_mesh(n) for n in counts}
    if getattr(args, "tune", False):
        tune_serve(
            strategies, sizes, [meshes[n] for n in counts], args.dtype,
            max_bucket=args.max_bucket, kernel=args.kernel,
            measure=getattr(args, "measure", "auto") or "auto",
            min_gain=getattr(args, "min_gain", None),
            prune_margin=getattr(args, "prune_margin", None),
            seed=args.seed,
        )
    promote = args.promote
    if promote not in (None, "auto"):
        promote = int(promote)
    metrics_out = getattr(args, "metrics_out", None)
    trace_jsonl = getattr(args, "trace_jsonl", None)
    n_tenants = getattr(args, "tenants", None)
    arrival = getattr(args, "arrival", "closed") or "closed"
    concurrency = getattr(args, "concurrency", None) or [1]
    coalesce_arg = getattr(args, "coalesce", None)
    fault_spec = getattr(args, "fault_spec", None)
    poison_rate = getattr(args, "poison_rate", 0.0) or 0.0
    # Load mode engages when the traffic shape asks for it: an open-loop
    # arrival process, offered concurrency, an explicit coalesce
    # request, or chaos mode (faults are a load-protocol feature — the
    # loops there tolerate per-request failures). The bare legacy
    # invocation stays on the sequential protocol (promotion check
    # included).
    load_mode = (
        arrival != "closed"
        or any(c > 1 for c in concurrency)
        or coalesce_arg is not None
        or fault_spec is not None
        or poison_rate > 0
    )
    # Uncoalesced first so `--coalesce both` leaves the coalesced run's
    # snapshot in --metrics-out (the batching panel's input).
    coalesce_modes = {
        None: (True,), "on": (True,), "off": (False,),
        "both": (False, True),
    }[coalesce_arg]
    window_ms = getattr(args, "window_ms", "auto")
    if window_ms not in (None, "auto"):
        window_ms = float(window_ms)
    flush_width = getattr(args, "flush_width", "auto")
    if flush_width not in (None, "auto"):
        flush_width = int(flush_width)
    # Solver mode: --op selects a served solver; the namespace attr is
    # solver_op because bench.sweep forwards its own args.op ("serve").
    solver_op = getattr(args, "solver_op", "matvec") or "matvec"
    n_done = 0
    for m, k in sizes:
        for name in strategies:
            for n_dev in counts:
                mesh = meshes[n_dev]
                if solver_op != "matvec":
                    try:
                        result = run_serve_solver(
                            name, mesh, m, op=solver_op,
                            dtype=args.dtype, kernel=args.kernel,
                            combine=args.combine,
                            stages=getattr(args, "stages", None),
                            dtype_storage=getattr(
                                args, "dtype_storage", None
                            ),
                            solver_kernel=getattr(
                                args, "solver_kernel", "xla"
                            ) or "xla",
                            rtol=getattr(args, "rtol", 1e-6),
                            rtol_sweep=getattr(args, "rtol_sweep", None),
                            maxiter=getattr(args, "maxiter", None),
                            restart=getattr(args, "restart", None),
                            steps=getattr(args, "steps", None),
                            n_solves=args.n_requests,
                            seed=args.seed,
                            metrics_out=metrics_out,
                            trace_jsonl=trace_jsonl,
                        )
                    except MatvecError as e:
                        print(f"skip {name} {m}x{m} p={n_dev}: {e}")
                        continue
                    if not args.no_csv:
                        path = append_solver_result(result, args.data_root)
                    else:
                        path = None
                    print(
                        f"serve-solver {result.op} {name} {m}x{m} "
                        f"p={n_dev} tier={result.solver_kernel} "
                        f"solves={result.n_solves} "
                        f"iters={result.iterations} "
                        f"resid={result.final_residual:.3e} "
                        f"t/iter={result.time_per_iter_ms:.3f}ms "
                        f"p50={result.solve_p50_ms:.2f}ms "
                        f"p99={result.solve_p99_ms:.2f}ms "
                        f"compiles={result.compiles_warmup}+"
                        f"{result.compiles_steady} "
                        f"div={result.divergences}"
                    )
                    if path is not None:
                        print(f"CSV: {path}")
                    n_done += 1
                    continue
                if n_tenants:
                    # Multi-tenant trace mode (engine/registry.py): takes
                    # precedence over the load/sequential protocols.
                    # --global-sched both runs the greedy baseline first,
                    # then the scheduled run on the SAME seeded trace
                    # (docs/SCHEDULING.md's A/B protocol).
                    gsched_modes = {
                        None: (False,), "off": (False,), "on": (True,),
                        "both": (False, True),
                    }[getattr(args, "global_sched", None)]
                    for gsched_on in gsched_modes:
                        try:
                            result = run_serve_multitenant(
                                name, mesh, m, k, dtype=args.dtype,
                                kernel=args.kernel, combine=args.combine,
                                stages=getattr(args, "stages", None),
                                dtype_storage=getattr(
                                    args, "dtype_storage", None
                                ),
                                n_tenants=n_tenants,
                                zipf_a=getattr(args, "zipf_a", 1.1),
                                hbm_budget=getattr(
                                    args, "hbm_budget", None
                                ),
                                pin_hot=getattr(args, "pin_hot", 0),
                                tenant_quota=getattr(
                                    args, "tenant_quota", None
                                ),
                                n_requests=args.n_requests,
                                max_bucket=args.max_bucket,
                                promote=promote, seed=args.seed,
                                metrics_out=metrics_out,
                                fault_spec=fault_spec,
                                fault_seed=getattr(args, "fault_seed", 0),
                                poison_rate=poison_rate,
                                poison_tenant=getattr(
                                    args, "poison_tenant", None
                                ),
                                integrity_gate=getattr(
                                    args, "integrity_gate", False
                                ),
                                breaker_reset_s=getattr(
                                    args, "breaker_reset_s", 30.0
                                ),
                                global_sched=gsched_on,
                                deadline_ms=getattr(
                                    args, "deadline_ms", None
                                ),
                                rate=getattr(args, "rate", None)
                                if getattr(args, "deadline_ms", None)
                                is not None else None,
                                max_in_flight=getattr(
                                    args, "max_in_flight", None
                                ),
                                demand_weight=getattr(
                                    args, "demand_weight", 0.0
                                ) if gsched_on else 0.0,
                                decision_jsonl=getattr(
                                    args, "decision_jsonl", None
                                ) if gsched_on else None,
                                reshard=getattr(
                                    args, "reshard", "off"
                                ) if gsched_on else "off",
                            )
                        except MatvecError as e:
                            print(f"skip {name} {m}x{k} p={n_dev}: {e}")
                            continue
                        if not args.no_csv:
                            path = append_multitenant_result(
                                result, args.data_root
                            )
                        else:
                            path = None
                        all_row = result.rows[-1]
                        sched_suffix = ""
                        if getattr(args, "deadline_ms", None) is not None:
                            sched_suffix = (
                                f" deadline={result.deadline_ms:.1f}ms "
                                f"expires={result.deadline_expires} "
                                f"rejected={all_row.rejected} "
                                f"p99={result.p99_e2e_ms:.2f}ms"
                            )
                        print(
                            f"serve-tenants {name} {m}x{k} p={n_dev} "
                            f"tenants={result.n_tenants} "
                            f"zipf_a={result.zipf_a} "
                            "budget="
                            f"{result.budget_tenants if result.hbm_budget else 'inf'} "
                            f"gsched={'on' if gsched_on else 'off'} "
                            f"{result.rps:.1f} req/s "
                            f"hit={result.hit_rate:.3f} "
                            f"(lru floor {result.lru_floor:.3f}) "
                            f"evictions={all_row.evictions} "
                            f"quota_rej={all_row.quota_rejections} "
                            f"ok={all_row.availability:.3f}"
                            + sched_suffix
                        )
                        if path is not None:
                            print(f"CSV: {path}")
                        n_done += 1
                    continue
                if not load_mode:
                    try:
                        result = run_serve(
                            name, mesh, m, k, dtype=args.dtype,
                            kernel=args.kernel, combine=args.combine,
                            stages=getattr(args, "stages", None),
                            dtype_storage=getattr(
                                args, "dtype_storage", None
                            ),
                            n_requests=args.n_requests,
                            max_bucket=args.max_bucket, promote=promote,
                            seed=args.seed,
                            metrics_out=metrics_out,
                            trace_jsonl=trace_jsonl,
                            rtol=getattr(args, "spec_rtol", None),
                        )
                    except MatvecError as e:
                        print(f"skip {name} {m}x{k} p={n_dev}: {e}")
                        continue
                    if not args.no_csv:
                        path = append_serve_result(result, args.data_root)
                    else:
                        path = None
                    storage_suffix = (
                        f" storage={result.dtype_storage} "
                        f"resident={result.resident_bytes / 1e6:.2f}MB"
                        if result.dtype_storage != "native" else ""
                    )
                    if result.speculated:
                        storage_suffix += (
                            f" spec={result.speculated} "
                            f"esc_rate={result.escalation_rate:.4f} "
                            f"bw_ratio={result.spec_bandwidth_ratio:.3f}"
                        )
                    print(
                        f"serve {name} {m}x{k} p={n_dev} "
                        f"b*={result.b_star} {result.rps:.1f} req/s "
                        f"{result.cols_per_s:.1f} cols/s "
                        f"p50={result.p50_dispatch_ms:.3f}ms "
                        f"p99={result.p99_dispatch_ms:.3f}ms "
                        f"compiles={result.compiles_warmup}+"
                        f"{result.compiles_steady} "
                        f"promo x{result.promo_speedup:.2f} "
                        f"@b={result.promo_b}"
                        + storage_suffix
                    )
                    if path is not None:
                        print(f"CSV: {path}")
                    n_done += 1
                    continue
                for n_clients in concurrency:
                    for coalesce in coalesce_modes:
                        try:
                            result = run_serve_load(
                                name, mesh, m, k, dtype=args.dtype,
                                kernel=args.kernel, combine=args.combine,
                                stages=getattr(args, "stages", None),
                                dtype_storage=getattr(
                                    args, "dtype_storage", None
                                ),
                                n_requests=args.n_requests,
                                max_bucket=args.max_bucket,
                                promote=promote,
                                concurrency=n_clients, coalesce=coalesce,
                                arrival=arrival,
                                rate=getattr(args, "rate", 500.0),
                                burst=getattr(args, "burst", 8),
                                window_ms=window_ms,
                                max_window_ms=getattr(
                                    args, "max_window_ms",
                                    DEFAULT_MAX_WINDOW_MS,
                                ),
                                flush_width=flush_width,
                                seed=args.seed,
                                metrics_out=metrics_out,
                                trace_jsonl=trace_jsonl,
                                events_jsonl=getattr(
                                    args, "events_jsonl", None
                                ),
                                slo_out=getattr(args, "slo_out", None),
                                flight_dir=getattr(
                                    args, "flight_dir", None
                                ),
                                fault_spec=fault_spec,
                                fault_seed=getattr(args, "fault_seed", 0),
                                poison_rate=poison_rate,
                                integrity_gate=getattr(
                                    args, "integrity_gate", False
                                ),
                                breaker_reset_s=getattr(
                                    args, "breaker_reset_s", 30.0
                                ),
                            )
                        except MatvecError as e:
                            print(
                                f"skip {name} {m}x{k} p={n_dev} "
                                f"c={n_clients}: {e}"
                            )
                            continue
                        if not args.no_csv:
                            path = append_serve_result(
                                result, args.data_root
                            )
                        else:
                            path = None
                        chaos_suffix = (
                            f" ok={result.success_rate:.3f} "
                            f"failed={result.failed_requests} "
                            f"retries={result.retries} "
                            f"downgrades={result.downgrades}"
                            if (fault_spec is not None or poison_rate > 0)
                            else ""
                        )
                        print(
                            f"serve-load {name} {m}x{k} p={n_dev} "
                            f"{arrival} c={n_clients} "
                            f"coalesce={'on' if coalesce else 'off'} "
                            f"{result.rps:.1f} req/s "
                            f"p50={result.p50_dispatch_ms:.3f}ms "
                            f"p99={result.p99_dispatch_ms:.3f}ms "
                            f"width={result.mean_batch_width:.2f} "
                            f"ratio={result.coalesce_ratio:.2f} "
                            f"compiles={result.compiles_warmup}+"
                            f"{result.compiles_steady}"
                            + chaos_suffix
                        )
                        if path is not None:
                            print(f"CSV: {path}")
                        n_done += 1
    if n_done and metrics_out is not None:
        # Per-config snapshot: with several configs the file holds the
        # LAST one (each run_serve rewrites it; traces append).
        print(f"metrics: {metrics_out}")
    if n_done and trace_jsonl is not None:
        print(f"trace: {trace_jsonl}")
    print(f"{n_done} serve configs measured")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m matvec_mpi_multiplier_tpu.bench.serve",
        description="Serve-throughput benchmark: mixed-width request "
        "stream against a resident sharded A through the serving engine "
        "(engine/).",
    )
    p.add_argument(
        "--strategy", nargs="+", default=["all"],
        help=f"strategies to serve: {available_strategies()} or 'all'",
    )
    p.add_argument("--devices", nargs="+", type=int, default=None)
    p.add_argument("--sizes", nargs="+", type=int, default=None)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--kernel", default="xla")
    p.add_argument(
        "--combine", default=None,
        help="combine schedule (or 'auto' for the tuning-cache winner)",
    )
    p.add_argument(
        "--stages", type=int, default=None,
        help="with --combine overlap: pin the staged schedule's stage "
        "count S (default: the tuned fifth axis, clamped per shape)",
    )
    p.add_argument(
        "--dtype-storage", dest="dtype_storage", default=None,
        choices=["native", "int8", "int8c", "fp8", "auto", "speculate"],
        help="resident-A storage format (ops/quantize.py): quantize A "
        "once at residency and serve from the low-bit payload; 'auto' "
        "consults the tuned sixth axis (native on a miss); 'speculate' "
        "arms the int8c speculative tier beside native (requests opt in "
        "via --spec-rtol). CSV rows record the resolved format + "
        "resident bytes",
    )
    p.add_argument(
        "--spec-rtol", dest="spec_rtol", type=float, default=None,
        help="per-request relative tolerance for matvec serving: with "
        "--dtype-storage speculate, every steady-phase request is served "
        "from the int8c tier with an on-device residual check, escalating "
        "to native only on a miss (ops/speculative.py). Default None = "
        "exact/native for every request",
    )
    p.add_argument(
        "--n-requests", type=int, default=200,
        help="steady-phase request count",
    )
    p.add_argument(
        "--max-bucket", type=int, default=32,
        help="widest batch bucket (power-of-two ladder below it)",
    )
    p.add_argument(
        "--promote", default="auto",
        help="GEMV->GEMM crossover b*: 'auto' (tuned), an int, or 'never'",
    )
    p.add_argument(
        "--op", dest="solver_op", default="matvec",
        choices=["matvec"] + list(SOLVER_OPS),
        help="serve answers instead of multiplies (solvers/; "
        "docs/SOLVERS.md): each request is one compiled-loop solve of "
        "A x = b (cg/gmres/chebyshev) or an eigenpair estimate "
        "(power/lanczos) against a seeded SPD operand; --n-requests "
        "becomes the steady solve count and rows land in "
        "serve_solver_<strategy>.csv",
    )
    p.add_argument(
        "--solver-kernel", default="xla",
        choices=["xla", "pallas_fused", "auto"],
        help="with --op cg|chebyshev: the iteration tier — XLA's fusion "
        "schedule, the fused Pallas whole-iteration kernel "
        "(ops/pallas_solver.py; interpret-gated off-TPU), or the tuned "
        "decision (tuning.lookup_solver_kernel)",
    )
    p.add_argument(
        "--rtol", type=float, default=1e-6,
        help="with --op <solver>: relative convergence tolerance (a "
        "DYNAMIC operand — changing it never recompiles)",
    )
    p.add_argument(
        "--rtol-sweep", nargs="+", type=float, default=None,
        help="with --op <solver>: cycle steady solves across this rtol "
        "ladder instead of one fixed --rtol — proves compiles_steady=0 "
        "across the whole ladder (rtol is a dynamic operand)",
    )
    p.add_argument(
        "--maxiter", type=int, default=None,
        help="with --op <solver>: iteration cap (dynamic operand; "
        "default: the engine's DEFAULT_SOLVER_MAXITER)",
    )
    p.add_argument(
        "--restart", type=int, default=None,
        help="with --op gmres: restart length (STATIC — part of the "
        "executable's bucket key)",
    )
    p.add_argument(
        "--steps", type=int, default=None,
        help="with --op lanczos: Krylov steps (STATIC — part of the "
        "executable's bucket key)",
    )
    p.add_argument(
        "--arrival", choices=["closed", "poisson", "burst"],
        default="closed",
        help="traffic shape: closed-loop clients (--concurrency) or an "
        "open-loop arrival process at --rate req/s",
    )
    p.add_argument(
        "--rate", type=float, default=500.0,
        help="with --arrival poisson|burst: offered request rate (req/s)",
    )
    p.add_argument(
        "--burst", type=int, default=8,
        help="with --arrival burst: simultaneous arrivals per burst",
    )
    p.add_argument(
        "--concurrency", nargs="+", type=int, default=None,
        help="closed-loop client counts to sweep (the offered-concurrency "
        "axis; any value engages load mode)",
    )
    p.add_argument(
        "--coalesce", choices=["on", "off", "both"], default=None,
        help="serve through the arrival-window batching scheduler "
        "(engine/scheduler.py); 'both' measures each config uncoalesced "
        "then coalesced on the same trace. Any value engages load mode",
    )
    p.add_argument(
        "--window-ms", default="auto",
        help="coalescing window: 'auto' (adaptive from the arrival-rate "
        "estimator) or a fixed window in ms",
    )
    p.add_argument(
        "--max-window-ms", type=float, default=DEFAULT_MAX_WINDOW_MS,
        help="adaptive coalescing window cap (ms)",
    )
    p.add_argument(
        "--flush-width", default="auto",
        help="batch width that flushes the window early: 'auto' (the "
        "tuned promotion point b*) or an int",
    )
    p.add_argument(
        "--tenants", type=int, default=None,
        help="multi-tenant trace mode (engine/registry.py): register N "
        "seeded tenant matrices in a matrix registry and drive a Zipf-"
        "popularity trace against --hbm-budget; one CSV row per tenant "
        "(availability/hit-rate/eviction columns) plus an ALL summary "
        "row in serve_tenants_<strategy>.csv. Takes precedence over the "
        "load/sequential protocols",
    )
    p.add_argument(
        "--zipf-a", type=float, default=1.1,
        help="with --tenants: Zipf popularity exponent (p(rank) ∝ "
        "rank^-a; higher = more skew toward hot tenants)",
    )
    p.add_argument(
        "--hbm-budget", default=None, metavar="BYTES|Nx",
        help="with --tenants: resident-payload budget — plain bytes, or "
        "a payload multiple like '2.5x' (room for 2.5 tenants of this "
        "shape). Omit for unlimited (accounting still runs)",
    )
    p.add_argument(
        "--pin-hot", type=int, default=0,
        help="with --tenants: warm-pin the K most popular tenants "
        "(eviction-exempt) before the trace",
    )
    p.add_argument(
        "--tenant-quota", default=None, metavar="N|tenant-i=N,...",
        help="with --tenants: max_in_flight admission quota — a bare "
        "int for every tenant, or 'tenant-0=4' to throttle named "
        "tenants only (the chaos overlay's quota-pressure knob)",
    )
    p.add_argument(
        "--global-sched", choices=["on", "off", "both"], default=None,
        dest="global_sched",
        help="with --tenants: route submits through the cost-model-"
        "driven global scheduler (engine/global_scheduler.py; "
        "docs/SCHEDULING.md) — predicted-time admission, cross-tenant "
        "interleaving/coalescing, demand-aware eviction. 'both' runs "
        "the greedy baseline then the scheduled run on the SAME seeded "
        "trace (the A/B protocol of data/gsched_demo/)",
    )
    p.add_argument(
        "--reshard", choices=["auto", "off"], default="off",
        help="with --tenants --global-sched on: arm the online-"
        "resharding crossover trigger (docs/RESHARDING.md) — when the "
        "cost model predicts another layout beats a tenant's current "
        "one by more than the amortized migration collectives over its "
        "EWMA demand horizon, the scheduler migrates the resident A "
        "on-device (MatrixRegistry.reshard). 'off' keeps every tenant "
        "frozen in its registered layout — the baseline arm of the "
        "data/reshard_demo/ drifting-shape A/B "
        "(scripts/reshard_study.py)",
    )
    p.add_argument(
        "--deadline-ms", type=float, default=None, dest="deadline_ms",
        help="with --tenants: SLO overlay — every request carries this "
        "deadline anchored at its SCHEDULED arrival (paced at --rate "
        "req/s), so loop lag consumes deadline budget; rows gain "
        "deadline_expires/rejected and end-to-end p50/p99 columns",
    )
    p.add_argument(
        "--max-in-flight", type=int, default=None, dest="max_in_flight",
        help="with --tenants: per-engine backpressure high-water mark "
        "(engine/core.py) — overload queues at the gate instead of "
        "enqueueing unboundedly, which is what greedy deadline-expires "
        "under (and predicted-time admission rejects fast instead)",
    )
    p.add_argument(
        "--demand-weight", type=float, default=2.0, dest="demand_weight",
        help="with --global-sched on|both: weight of the predicted-"
        "demand term in the registry's eviction score (0 = the PR 9 "
        "recency+cost score; engine/registry.py)",
    )
    p.add_argument(
        "--decision-jsonl", default=None, metavar="FILE",
        dest="decision_jsonl",
        help="with --global-sched: mirror every scheduling decision "
        "(admit/reject/interleave/evict/flush, each with predicted_s "
        "and reason) to FILE via the obs sink thread",
    )
    p.add_argument(
        "--fault-spec", default=None, metavar="SPEC",
        help="chaos mode: seeded fault-injection plan, e.g. "
        "'dispatch:device_error:p=0.05;dispatch:nan:times=2' "
        "(grammar: resilience/faults.py; engages load mode and, by "
        "default, the retry/breaker recovery policy — see "
        "docs/RESILIENCE.md). NOTE compile-site specs only fire for "
        "executables NOT pre-compiled by warmup (fallback tiers, "
        "shrunken buckets) — preferred configs are warm by the time "
        "the plan arms; the bench warns when a compile spec never "
        "matched",
    )
    p.add_argument(
        "--fault-seed", type=int, default=0,
        help="seed for the FaultPlan's deterministic injection draws "
        "(and the retry policy's jitter)",
    )
    p.add_argument(
        "--poison-rate", type=float, default=0.0,
        help="chaos mode: fraction of requests (seeded choice) marked "
        "with the poison payload signature — each fails its dispatch "
        "deterministically, exercising the scheduler's batch bisection",
    )
    p.add_argument(
        "--poison-tenant", default=None, metavar="TENANT",
        help="with --tenants and --poison-rate: plant the poison "
        "signature only in this tenant's requests (the isolation "
        "overlay's per-tenant blast radius)",
    )
    p.add_argument(
        "--integrity-gate", action="store_true",
        help="refuse NaN/Inf results at materialization "
        "(engine_integrity_failures_total counts refusals; with "
        "coalescing the gate applies per request slice)",
    )
    p.add_argument(
        "--breaker-reset-s", type=float, default=30.0,
        help="chaos mode: circuit-breaker open->half-open cooldown "
        "seconds (lower it so short traces exercise recovery)",
    )
    p.add_argument(
        "--tune", action="store_true",
        help="pre-pass: measure kernels, combines (matvec+gemm) and the "
        "promotion crossover for every config, persisting to the tuning "
        "cache",
    )
    p.add_argument(
        "--min-gain", type=float, default=None,
        help="with --tune: hysteresis margin (default 0.05; raise on "
        "noisy shared hosts — see the sweep CLI's flag of the same name)",
    )
    p.add_argument(
        "--prune-margin", type=float, default=None, dest="prune_margin",
        help="with --tune: cost-model predicted pre-ranking — measure "
        "only candidates predicted within this margin of the predicted "
        "winner (the CLI tuner's flag; ~40%% fewer measurements with a "
        "calibrated cache, exhaustive + a log line without one)",
    )
    p.add_argument(
        "--measure", choices=["auto", "loop", "chain", "sync"],
        default="auto",
        help="with --tune: timing method for combine measurement "
        "(bench/timing.py)",
    )
    p.add_argument(
        "--metrics-out", default=None, metavar="FILE",
        help="write the metrics snapshot (engine counters + dispatch-"
        "latency histogram, one JSON) after each config; render with "
        "`python -m matvec_mpi_multiplier_tpu.obs metrics FILE`. With "
        "several configs the file holds the last one",
    )
    p.add_argument(
        "--trace-jsonl", default=None, metavar="FILE",
        help="stream one request-lifecycle span tree per request "
        "(submit->gate->pad->exec_lookup->dispatch->materialize) to FILE "
        "via the obs sink thread; summarize with "
        "`python -m matvec_mpi_multiplier_tpu.obs trace FILE`",
    )
    p.add_argument(
        "--events-jsonl", default=None, metavar="FILE",
        help="(load mode) stream the correlated event timeline — "
        "scheduler decisions, swaps, retries, failures, all carrying "
        "request_id/cause_id — to FILE; reconstruct one request with "
        "`python -m matvec_mpi_multiplier_tpu.obs timeline FILE RID`",
    )
    p.add_argument(
        "--slo-out", default=None, metavar="FILE",
        help="(load mode) evaluate the declared SLOs (obs/slo.py "
        "DEFAULT_TARGETS) over the run and write the burn-rate "
        "evaluation JSON; render with "
        "`python -m matvec_mpi_multiplier_tpu.obs slo FILE`",
    )
    p.add_argument(
        "--flight-dir", default=None, metavar="DIR",
        help="(load mode) arm the flight recorder: auto-dump a post-"
        "mortem bundle (last events + metric snapshots + SLO state) "
        "into DIR on any typed failure; render with "
        "`python -m matvec_mpi_multiplier_tpu.obs dump BUNDLE`",
    )
    p.add_argument(
        "--annotate", action="store_true",
        help="enable named device-trace spans (strategy local-GEMV/"
        "combine bodies, overlap stage{i}/compute|combine) in every "
        "program this run builds — pair with a profiler capture "
        "(docs/OBSERVABILITY.md)",
    )
    p.add_argument("--data-root", default=None)
    p.add_argument("--no-csv", action="store_true")
    p.add_argument("--platform", default=None)
    p.add_argument("--host-devices", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.promote == "never":
        args.promote = None
    return run_serve_sweep(args)


if __name__ == "__main__":
    sys.exit(main())

"""Interprocedural value-flow engine for the retrace-hazard rules.

The keyspace auditor (``keyspace.py``) proves the ENUMERATED compile
surface is warm-covered; this engine hunts the code shapes that mint
executables OUTSIDE the enumerated space — the jit-cache fragmenters the
grep-shaped rules cannot see because they are properties of how values
FLOW, not of single call sites:

- #17 ``traced-python-branch`` — ``if``/``while``/``assert`` on a value
  that reaches a traced body: every distinct value retraces (or raises
  ``TracerBoolConversionError`` outright).
- #18 ``weak-type-cache-split`` — a dtype-less Python literal flowing
  into a jitted call: weak-type promotion keys a second executable for
  the same shapes.
- #19 ``unhashable-static-arg`` — a dict/list/lambda reaching a
  ``jit``/``lower`` static position: ``TypeError: unhashable`` at the
  first dispatch.
- #20 ``host-sync-on-tracer`` — ``int()``/``float()``/``np.asarray``
  applied to a traced value in engine/solver paths: a silent device
  round-trip the ``# sync-ok`` grep lint can't see (it only knows
  blocking METHOD names, not which VALUES are tracers).

Like the lock-graph layer this is whole-program (the per-file rule
checks share one cached analysis keyed on a content hash), jax-free
(pure ``ast`` — it must run at tier-1 ``--rules`` speed), and
deliberately shallow where precision would cost speed: taint is
flow-insensitive within a function, propagated to a fixpoint across
direct calls resolved by name (same module, ``self.`` methods, then a
unique bare name anywhere in the corpus — the lockgraph resolution
doctrine). Attribute reads that are static under trace
(``.shape``/``.ndim``/``.dtype``/...) strip tracer taint, as do
``len``/``isinstance``/``is`` — the idioms traced code legitimately
branches on.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
from pathlib import Path
from typing import Iterator

from .corpus import SourceFile, iter_corpus, source_file

_PKG = "matvec_mpi_multiplier_tpu"

DATAFLOW_RULES = (
    "traced-python-branch",
    "weak-type-cache-split",
    "unhashable-static-arg",
    "host-sync-on-tracer",
)

# Taint facets.
TRACED = "traced"      # value may be a jax tracer
WEAK = "weak"          # dtype-less python scalar (weak-type promotion)
UNHASH = "unhashable"  # dict/list/set/lambda/comprehension


def dataflow_scope(rel: str) -> bool:
    """The engine analyzes (and rules #17–#19 report over) the package —
    tests/scripts drive engines from host code where these hazards are
    the *caller's* business, not serving-path regressions."""
    return rel.startswith(f"{_PKG}/")


def sync_scope(rel: str) -> bool:
    """Rule #20 reports over the engine/solver serving paths — the AOT
    dispatch discipline those modules own."""
    return rel.startswith(f"{_PKG}/engine/") or rel.startswith(
        f"{_PKG}/solvers/"
    )


# jit entry points: the wrapped function's params become tracers and the
# call result is a jitted binding (rules #18/#19 check its call sites).
_JIT_NAMES = frozenset({"jax.jit", "jit", "jax.pjit", "pjit"})

# Higher-order tracing entry points -> positions whose function argument
# is traced. Matched on the alias-resolved dotted name; *suffix* matches
# below catch the package's compat re-exports.
_TRACED_HOF: dict[str, tuple[int, ...]] = {
    "jax.lax.while_loop": (0, 1),
    "jax.lax.scan": (0,),
    "jax.lax.cond": (1, 2),
    "jax.lax.fori_loop": (2,),
    "jax.lax.map": (0,),
    "jax.vmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.jacfwd": (0,),
    "jax.jacrev": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
}
_TRACED_HOF_SUFFIXES: tuple[tuple[str, tuple[int, ...]], ...] = (
    ("shard_map", (0,)),
    ("pallas_call", (0,)),
)

# Attribute reads that are STATIC under trace — branching on them is the
# legitimate idiom, so they strip tracer taint. ``block`` is the
# quantized container's pytree AUX field (ops/quantize.py
# tree_flatten): under shard_map/jit the leaves (q, scales) are
# tracers but aux data stays a python int.
_STATIC_ATTRS = frozenset({
    "shape", "ndim", "dtype", "size", "itemsize", "weak_type", "sharding",
    "aval", "nbytes", "block",
})

# Calls whose result is static regardless of argument taint.
_STRIP_CALLS = frozenset({
    "len", "isinstance", "issubclass", "hasattr", "type", "id", "callable",
    "repr", "str", "format",
})

# Host-materialization calls: applied to a tracer they either sync or
# fail; their results are host values (python scalars stay WEAK).
_HOST_SYNC_CALLS = frozenset({
    "int", "float", "bool", "complex",
    "numpy.asarray", "numpy.array", "numpy.asanyarray",
})
_WEAK_RESULT_CALLS = frozenset({"int", "float", "round", "abs"})


@dataclasses.dataclass
class _Binding:
    """A name bound to a jitted callable (``g = jax.jit(f, ...)`` or a
    ``@jit``-decorated function) — the call-site contract rules #18/#19
    check against."""

    name: str
    static_nums: tuple[int, ...] = ()
    static_names: tuple[str, ...] = ()


@dataclasses.dataclass
class _Func:
    """One analyzed function (or a file's module-level pseudo-function)."""

    rel: str
    qual: str
    name: str
    node: ast.AST           # FunctionDef / AsyncFunctionDef / Module
    params: tuple[str, ...]
    cls: str | None
    static_params: set = dataclasses.field(default_factory=set)
    traced_root: bool = False   # params are tracers (jit/HOF boundary)
    ctx_traced: bool = False    # body may execute under trace
    env: dict = dataclasses.field(default_factory=dict)
    ret: frozenset = frozenset()
    # Own-body node index, computed once at collect time: the fixpoint
    # re-runs `_local_pass` several times per function, and re-walking
    # the AST each pass dominated the build profile.
    binds: list = dataclasses.field(default_factory=list)
    sites: list = dataclasses.field(default_factory=list)

    @property
    def body(self) -> list:
        return self.node.body


_BIND_NODES = (
    ast.Assign, ast.AnnAssign, ast.AugAssign, ast.For, ast.AsyncFor,
    ast.With, ast.AsyncWith, ast.Return, ast.NamedExpr,
)
_SITE_NODES = (ast.If, ast.While, ast.Assert, ast.Call)
_STMT_BEARING = (ast.stmt, ast.ExceptHandler, ast.match_case)


def _walk_own(body: list) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested function /
    lambda bodies (those are separate ``_Func``s with their own taint
    context). The guard is on the POPPED node, not the pushed child —
    a def sitting directly in the statement list (or a module's
    top-level defs) must not leak its locals into the enclosing env."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _const_static_nums(node: ast.expr | None) -> tuple[int, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


def _const_static_names(node: ast.expr | None) -> tuple[str, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            elt.value for elt in node.elts
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str)
        )
    return ()


_UNRESOLVED = object()  # memo sentinel: "not computed yet" != "None"


class Program:
    """The whole-program taint analysis: built once per corpus content
    hash, consumed by the per-file rule checks."""

    def __init__(self, root: Path) -> None:
        self.root = Path(root)
        self.funcs: dict[tuple[str, str], _Func] = {}
        self.by_file: dict[str, dict[str, _Func]] = {}
        self.by_bare: dict[str, list[_Func]] = {}
        self.by_method: dict[tuple[str, str], list[_Func]] = {}
        self.by_method_name: dict[str, list[_Func]] = {}
        self.by_node: dict[int, _Func] = {}
        self.modules: dict[str, _Func] = {}
        self.bindings: dict[tuple[str, str], _Binding] = {}
        self.aliases: dict[str, dict[str, str]] = {}
        self.findings: dict[str, dict[str, list]] = {
            rule: {} for rule in DATAFLOW_RULES
        }
        self.callers: dict[tuple[str, str], set] = {}
        self._dirty: set[tuple[str, str]] = set()
        self._resolve_cache: dict[tuple[str, str | None, int], object] = {}
        self._dotted_cache: dict[tuple[str, int], str | None] = {}
        self._changed = False
        self._build()

    # ---- construction ----

    def _build(self) -> None:
        sources: list[SourceFile] = []
        for path in iter_corpus(self.root):
            rel = path.relative_to(self.root).as_posix()
            if not dataflow_scope(rel):
                continue
            try:
                sources.append(source_file(path, self.root))
            except (SyntaxError, UnicodeDecodeError):
                continue  # rules.py reports parse errors separately
        for sf in sources:
            self._collect(sf)
        for sf in sources:
            self._mark_traced(sf)
        # Interprocedural fixpoint over a worklist: taint facets only
        # ever GROW (a finite monotone lattice), so re-processing only
        # functions whose inputs changed terminates — and keeps the
        # whole-program pass at tier-1 --rules speed.
        pending = list(self.funcs)
        in_queue = set(pending)
        rounds = 0
        limit = 50 * max(1, len(self.funcs))
        while pending and rounds < limit:
            rounds += 1
            key = pending.pop()
            in_queue.discard(key)
            fn = self.funcs[key]
            self._seed(fn)
            ret_before = fn.ret
            ctx_before = fn.ctx_traced
            for _ in range(4):
                self._dirty.clear()
                changed = self._local_pass(fn)
                for dirty_key in self._dirty:
                    if dirty_key != key and dirty_key not in in_queue:
                        pending.append(dirty_key)
                        in_queue.add(dirty_key)
                if not changed:
                    break
            if fn.ret != ret_before or fn.ctx_traced != ctx_before:
                for caller in self.callers.get(key, ()):
                    if caller not in in_queue:
                        pending.append(caller)
                        in_queue.add(caller)
        for fn in self.funcs.values():
            self._check(fn)

    def _collect(self, sf: SourceFile) -> None:
        self.aliases[sf.rel] = dict(sf.aliases)
        file_funcs: dict[str, _Func] = {}
        module = _Func(
            rel=sf.rel, qual="<module>", name="<module>", node=sf.tree,
            params=(), cls=None,
        )
        self._index(module)
        self.modules[sf.rel] = module
        self.funcs[(sf.rel, "<module>")] = module
        self.by_node[id(sf.tree)] = module

        def visit(node: ast.AST, cls: str | None, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name, f"{prefix}{child.name}.")
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qual = f"{prefix}{child.name}"
                    params = tuple(
                        a.arg for a in (
                            child.args.posonlyargs + child.args.args
                            + child.args.kwonlyargs
                        )
                    )
                    fn = _Func(
                        rel=sf.rel, qual=qual, name=child.name, node=child,
                        params=params, cls=cls,
                    )
                    self._index(fn)
                    self.funcs[(sf.rel, qual)] = fn
                    self.by_node[id(child)] = fn
                    file_funcs.setdefault(child.name, fn)
                    self.by_bare.setdefault(child.name, []).append(fn)
                    if cls is not None:
                        self.by_method.setdefault(
                            (cls, child.name), []
                        ).append(fn)
                        self.by_method_name.setdefault(
                            child.name, []
                        ).append(fn)
                    visit(child, cls, f"{qual}.<locals>.")
                elif isinstance(child, _STMT_BEARING):
                    # Defs are statements; only statement-bearing nodes
                    # (stmt bodies, except handlers, match cases) can
                    # contain one. Expression subtrees hold at most
                    # lambdas, which this collector never models — so
                    # pruning them is exact, not an approximation.
                    visit(child, cls, prefix)

        visit(sf.tree, None, "")
        self.by_file[sf.rel] = file_funcs

    def _index(self, fn: _Func) -> None:
        """One own-body walk, bucketing the nodes the taint pass
        (``binds``) and the rule checks (``sites``) iterate."""
        for node in _walk_own(fn.body):
            if isinstance(node, _BIND_NODES):
                fn.binds.append(node)
            if isinstance(node, _SITE_NODES):
                fn.sites.append(node)

    def _dotted(self, rel: str, expr: ast.expr) -> str | None:
        key = (rel, id(expr))
        hit = self._dotted_cache.get(key, _UNRESOLVED)
        if hit is not _UNRESOLVED:
            return hit
        parts: list[str] = []
        node = expr
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            out = None
        else:
            aliases = self.aliases.get(rel, {})
            parts.append(aliases.get(node.id, node.id))
            out = ".".join(reversed(parts))
        self._dotted_cache[key] = out
        return out

    def _hof_positions(self, dotted: str | None) -> tuple[int, ...] | None:
        if dotted is None:
            return None
        if dotted in _JIT_NAMES:
            return (0,)
        hit = _TRACED_HOF.get(dotted)
        if hit is not None:
            return hit
        for suffix, positions in _TRACED_HOF_SUFFIXES:
            if dotted == suffix or dotted.endswith("." + suffix):
                return positions
        return None

    def _resolve(
        self, rel: str, cls: str | None, expr: ast.expr
    ) -> _Func | None:
        """Resolve a call target to an analyzed function: same-module
        name, ``self.method`` (same class first), then a UNIQUE bare
        name anywhere in the program. Memoized per call site — the
        fixpoint re-evaluates expressions many times."""
        key = (rel, cls, id(expr))
        hit = self._resolve_cache.get(key, _UNRESOLVED)
        if hit is not _UNRESOLVED:
            return hit
        out = self._resolve_uncached(rel, cls, expr)
        self._resolve_cache[key] = out
        return out

    def _resolve_uncached(
        self, rel: str, cls: str | None, expr: ast.expr
    ) -> _Func | None:
        if isinstance(expr, ast.Name):
            fn = self.by_file.get(rel, {}).get(expr.id)
            if fn is not None:
                return fn
            candidates = self.by_bare.get(expr.id, [])
            if len(candidates) == 1:
                return candidates[0]
            return None
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            if cls is not None:
                same = [
                    f for f in self.by_method.get((cls, expr.attr), [])
                    if f.rel == rel
                ]
                if same:
                    return same[0]
            candidates = self.by_method_name.get(expr.attr, [])
            if len(candidates) == 1:
                return candidates[0]
        return None

    def _apply_static(self, fn: _Func, binding: _Binding) -> None:
        params = [p for p in fn.params if p != "self"]
        for i in binding.static_nums:
            if 0 <= i < len(params):
                fn.static_params.add(params[i])
        fn.static_params.update(
            n for n in binding.static_names if n in fn.params
        )

    def _mark_traced(self, sf: SourceFile) -> None:
        rel = sf.rel
        for node in sf.nodes(
            ast.Assign, ast.Call, ast.FunctionDef, ast.AsyncFunctionDef
        ):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                call = node.value
                dotted = self._dotted(rel, call.func)
                if dotted in _JIT_NAMES:
                    binding = _Binding(
                        name="?",
                        static_nums=self._kw_nums(call),
                        static_names=self._kw_names(call),
                    )
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            binding.name = tgt.id
                            self.bindings[(rel, tgt.id)] = binding
                    if call.args:
                        target = call.args[0]
                        fn = self._resolve(rel, None, target)
                        if fn is not None:
                            fn.traced_root = fn.ctx_traced = True
                            self._apply_static(fn, binding)
            if isinstance(node, ast.Call):
                positions = self._hof_positions(
                    self._dotted(rel, node.func)
                )
                if positions is not None:
                    for i in positions:
                        if i < len(node.args):
                            fn = self._resolve(rel, None, node.args[i])
                            if fn is not None:
                                fn.traced_root = fn.ctx_traced = True
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                for dec in node.decorator_list:
                    binding = self._jit_decorator(rel, dec)
                    if binding is None:
                        continue
                    fn = self.by_node.get(id(node))
                    if fn is not None:
                        fn.traced_root = fn.ctx_traced = True
                        self._apply_static(fn, binding)
                    binding.name = node.name
                    self.bindings[(rel, node.name)] = binding

    def _kw_nums(self, call: ast.Call) -> tuple[int, ...]:
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                return _const_static_nums(kw.value)
        return ()

    def _kw_names(self, call: ast.Call) -> tuple[str, ...]:
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                return _const_static_names(kw.value)
        return ()

    def _jit_decorator(
        self, rel: str, dec: ast.expr
    ) -> _Binding | None:
        dotted = self._dotted(rel, dec)
        if dotted in _JIT_NAMES:
            return _Binding(name="?")
        if isinstance(dec, ast.Call):
            inner = self._dotted(rel, dec.func)
            if inner in _JIT_NAMES:
                return _Binding(
                    name="?", static_nums=self._kw_nums(dec),
                    static_names=self._kw_names(dec),
                )
            if inner in ("functools.partial", "partial") and dec.args:
                if self._dotted(rel, dec.args[0]) in _JIT_NAMES:
                    return _Binding(
                        name="?", static_nums=self._kw_nums(dec),
                        static_names=self._kw_names(dec),
                    )
        return None

    # ---- taint ----

    def _seed(self, fn: _Func) -> None:
        if fn.traced_root:
            for p in fn.params:
                if p == "self" or p in fn.static_params:
                    continue
                if TRACED not in fn.env.get(p, frozenset()):
                    fn.env[p] = fn.env.get(p, frozenset()) | {TRACED}
                    self._changed = True

    def _merge(self, fn: _Func, name: str, taint: frozenset) -> bool:
        old = fn.env.get(name, frozenset())
        new = old | taint
        if new != old:
            fn.env[name] = new
            return True
        return False

    def _bind(
        self,
        fn: _Func,
        target: ast.expr,
        taint: frozenset,
        value: ast.expr | None = None,
    ) -> bool:
        changed = False
        if isinstance(target, ast.Name):
            changed |= self._merge(fn, target.id, taint)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if (
                isinstance(value, (ast.Tuple, ast.List))
                and len(value.elts) == len(target.elts)
                and not any(
                    isinstance(e, ast.Starred) for e in target.elts
                )
            ):
                # `a, b = x, [y]` — element-wise, so the display's
                # UNHASH lands only on the name actually bound to it.
                for elt, velt in zip(target.elts, value.elts):
                    changed |= self._bind(
                        fn, elt, self._taint(fn, velt), velt
                    )
            else:
                # Unpacking a container yields ELEMENTS — the
                # container's own unhashability does not transfer.
                for elt in target.elts:
                    changed |= self._bind(fn, elt, taint - {UNHASH})
        elif isinstance(target, ast.Starred):
            changed |= self._bind(fn, target.value, taint)
        return changed

    def _taint(self, fn: _Func, node: ast.expr) -> frozenset:
        if isinstance(node, ast.Name):
            local = fn.env.get(node.id)
            if local is not None:
                return local
            module = self.modules.get(fn.rel)
            if module is not None and module is not fn:
                return module.env.get(node.id, frozenset())
            return frozenset()
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool):
                return frozenset()
            if isinstance(node.value, (int, float, complex)):
                return frozenset({WEAK})
            return frozenset()
        if isinstance(node, ast.Attribute):
            base = self._taint(fn, node.value)
            if node.attr in _STATIC_ATTRS:
                return base - {TRACED, WEAK}
            return base - {WEAK}
        if isinstance(node, ast.Subscript):
            # Indexing yields an ELEMENT: a tracer stays a tracer, but
            # the container's unhashability does not ride along.
            return self._taint(fn, node.value) - {UNHASH}
        if isinstance(node, ast.BinOp):
            # JAX weak-type promotion: weak ⊗ weak stays weak, but a
            # weak scalar against a strong array yields a STRONG array
            # — so WEAK survives only when BOTH sides carry it.
            left = self._taint(fn, node.left)
            right = self._taint(fn, node.right)
            out = (left | right) - {WEAK}
            if WEAK in left and WEAK in right:
                out |= {WEAK}
            return out
        if isinstance(node, ast.UnaryOp):
            return self._taint(fn, node.operand)
        if isinstance(node, ast.BoolOp):
            out: frozenset = frozenset()
            for v in node.values:
                out |= self._taint(fn, v)
            return out
        if isinstance(node, ast.Compare):
            # A comparison's result is a bool (or a traced bool array)
            # — never a weak literal or an unhashable container.
            out = self._taint(fn, node.left)
            for c in node.comparators:
                out |= self._taint(fn, c)
            out -= {WEAK, UNHASH}
            if all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ):
                out -= {TRACED}
            return out
        if isinstance(node, ast.Call):
            return self._call_taint(fn, node)
        if isinstance(node, ast.Tuple):
            out = frozenset()
            for elt in node.elts:
                out |= self._taint(fn, elt)
            return out
        if isinstance(node, (ast.List, ast.Set)):
            out = frozenset({UNHASH})
            for elt in node.elts:
                out |= self._taint(fn, elt)
            return out
        if isinstance(node, ast.Dict):
            out = frozenset({UNHASH})
            for v in node.values:
                if v is not None:
                    out |= self._taint(fn, v)
            return out
        if isinstance(node, ast.Lambda):
            return frozenset({UNHASH})
        if isinstance(
            node,
            (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp),
        ):
            return frozenset({UNHASH})
        if isinstance(node, ast.IfExp):
            return self._taint(fn, node.body) | self._taint(fn, node.orelse)
        if isinstance(node, ast.Starred):
            return self._taint(fn, node.value)
        if isinstance(node, ast.NamedExpr):
            return self._taint(fn, node.value)
        if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
            return frozenset()
        return frozenset()

    def _call_taint(self, fn: _Func, call: ast.Call) -> frozenset:
        dotted = self._dotted(fn.rel, call.func)
        arg_taints = [self._taint(fn, a) for a in call.args]
        kw_taints = {
            kw.arg: self._taint(fn, kw.value)
            for kw in call.keywords if kw.arg is not None
        }
        merged: frozenset = frozenset()
        for t in arg_taints:
            merged |= t
        for t in kw_taints.values():
            merged |= t
        if isinstance(call.func, ast.Attribute):
            # Method calls: the receiver's taint rides the result
            # (x.sum() of a tracer is a tracer).
            merged |= self._taint(fn, call.func.value)
        callee = self._resolve(fn.rel, fn.cls, call.func)
        if callee is not None and callee is not fn:
            ckey = (callee.rel, callee.qual)
            self.callers.setdefault(ckey, set()).add((fn.rel, fn.qual))
            changed = False
            params = [p for p in callee.params if p != "self"]
            for i, t in enumerate(arg_taints):
                if i < len(params) and t:
                    changed |= self._merge(callee, params[i], t)
            for name, t in kw_taints.items():
                if name in callee.params and t:
                    changed |= self._merge(callee, name, t)
            if fn.ctx_traced and not callee.ctx_traced:
                callee.ctx_traced = True
                changed = True
            if changed:
                self._dirty.add(ckey)
            return callee.ret
        if dotted in _STRIP_CALLS:
            return frozenset()
        if dotted in _HOST_SYNC_CALLS:
            if dotted in _WEAK_RESULT_CALLS:
                return frozenset({WEAK})
            return frozenset()
        # Unresolved call: tracer taint flows through (jnp/lax results
        # of traced operands are traced); weak/unhashable do not (call
        # results are not python literals or displays).
        return frozenset({TRACED} if TRACED in merged else ())

    def _local_pass(self, fn: _Func) -> bool:
        changed = False
        for node in fn.binds:
            if isinstance(node, ast.Assign):
                t = self._taint(fn, node.value)
                for tgt in node.targets:
                    changed |= self._bind(fn, tgt, t, node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                changed |= self._bind(
                    fn, node.target, self._taint(fn, node.value),
                    node.value,
                )
            elif isinstance(node, ast.AugAssign):
                t = self._taint(fn, node.value) | self._taint(
                    fn, node.target
                )
                changed |= self._bind(fn, node.target, t)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                # Iteration yields ELEMENTS of the iterable — a traced
                # element stays traced, list-ness does not transfer.
                changed |= self._bind(
                    fn, node.target,
                    self._taint(fn, node.iter) - {UNHASH},
                )
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        changed |= self._bind(
                            fn, item.optional_vars,
                            self._taint(fn, item.context_expr),
                        )
            elif isinstance(node, ast.Return) and node.value is not None:
                new = fn.ret | self._taint(fn, node.value)
                if new != fn.ret:
                    fn.ret = new
                    changed = True
            elif isinstance(node, ast.NamedExpr):
                changed |= self._bind(
                    fn, node.target, self._taint(fn, node.value)
                )
        if changed:
            self._changed = True
        return changed

    # ---- rule checks ----

    def _emit(self, rule: str, fn: _Func, node: ast.AST, msg: str) -> None:
        self.findings[rule].setdefault(fn.rel, []).append((node, msg))

    def _static_positions(
        self, binding: _Binding, call: ast.Call
    ) -> Iterator[tuple[ast.expr, str]]:
        for i, arg in enumerate(call.args):
            if i in binding.static_nums:
                yield arg, f"position {i}"
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in binding.static_names:
                yield kw.value, f"argname {kw.arg!r}"

    def _check(self, fn: _Func) -> None:
        for node in fn.sites:
            if fn.ctx_traced and isinstance(
                node, (ast.If, ast.While, ast.Assert)
            ):
                test = node.test
                if TRACED in self._taint(fn, test):
                    kind = type(node).__name__.lower()
                    self._emit(
                        "traced-python-branch", fn, node,
                        f"Python `{kind}` on a traced value inside a "
                        f"trace context ({fn.qual}) — retraces per value "
                        f"or raises TracerBoolConversionError; use "
                        f"lax.cond/jnp.where or branch on static "
                        f".shape/.ndim/.dtype",
                    )
            if not isinstance(node, ast.Call):
                continue
            call = node
            dotted = self._dotted(fn.rel, call.func)
            if (
                fn.ctx_traced
                and dotted in _HOST_SYNC_CALLS
                and any(
                    TRACED in self._taint(fn, a)
                    for a in list(call.args)
                    + [kw.value for kw in call.keywords if kw.arg]
                )
            ):
                self._emit(
                    "host-sync-on-tracer", fn, call,
                    f"{dotted}() on a traced value inside a trace "
                    f"context ({fn.qual}) — a silent device round-trip "
                    f"that blocks dispatch; keep the value on device "
                    f"(jnp.*) or hoist the conversion out of the traced "
                    f"body",
                )
            binding = self._call_binding(fn, call)
            if binding is None:
                continue
            static_args = dict(
                (id(expr), where)
                for expr, where in self._static_positions(binding, call)
            )
            for expr, where in self._static_positions(binding, call):
                taint = self._taint(fn, expr)
                if UNHASH in taint:
                    self._emit(
                        "unhashable-static-arg", fn, expr,
                        f"unhashable value reaches static {where} of "
                        f"jitted `{binding.name}` — jit static args are "
                        f"cache keys and must be hashable; pass a tuple "
                        f"or a frozen config object",
                    )
            for i, expr in enumerate(call.args):
                if id(expr) in static_args:
                    continue
                self._check_weak(fn, binding, expr)
            for kw in call.keywords:
                if kw.arg is None or id(kw.value) in static_args:
                    continue
                self._check_weak(fn, binding, kw.value)

    def _check_weak(
        self, fn: _Func, binding: _Binding, expr: ast.expr
    ) -> None:
        taint = self._taint(fn, expr)
        if WEAK in taint and TRACED not in taint:
            self._emit(
                "weak-type-cache-split", fn, expr,
                f"dtype-less Python scalar flows into jitted "
                f"`{binding.name}` — weak-type promotion mints a second "
                f"executable for the same shapes; wrap it "
                f"(jnp.float32(...)) or pass an array",
            )

    def _call_binding(self, fn: _Func, call: ast.Call) -> _Binding | None:
        if isinstance(call.func, ast.Name):
            binding = self.bindings.get((fn.rel, call.func.id))
            if binding is not None:
                return binding
        if isinstance(call.func, ast.Call):
            # Immediate dispatch: jax.jit(f, static_argnums=...)(args).
            dotted = self._dotted(fn.rel, call.func.func)
            if dotted in _JIT_NAMES:
                return _Binding(
                    name=self._dotted(fn.rel, call.func.args[0])
                    if call.func.args else "<jitted>",
                    static_nums=self._kw_nums(call.func),
                    static_names=self._kw_names(call.func),
                )
        return None


# ---- cache + rule registration (the lockgraph pattern) ----

# root -> (generation, content signature, program).
_CACHE: dict[str, tuple[int, tuple, Program]] = {}
_GENERATION = [0]


def new_generation() -> None:
    """Invalidate the once-per-run corpus validation (rules.run_rules
    calls this at entry; a direct ``analyze`` caller that mutates files
    between calls must call it too)."""
    _GENERATION[0] += 1


def analyze(root: Path) -> Program:
    """The corpus's value-flow program, rebuilt only when an in-scope
    file's content changes, validated at most once per rule-engine run."""
    root = Path(root)
    key = str(root.resolve())
    gen = _GENERATION[0]
    cached = _CACHE.get(key)
    if cached is not None and cached[0] == gen:
        return cached[2]
    sig = []
    for path in iter_corpus(root):
        rel = path.relative_to(root).as_posix()
        if dataflow_scope(rel):
            sig.append(
                (rel, hashlib.sha1(path.read_bytes()).hexdigest())
            )
    sig_t = tuple(sig)
    if cached is not None and cached[1] == sig_t:
        program = cached[2]
    else:
        program = Program(root)
    _CACHE[key] = (gen, sig_t, program)
    return program


def _check_for(rule: str):
    def check(sf: SourceFile) -> Iterator[tuple[ast.AST, str]]:
        yield from analyze(sf.root).findings[rule].get(sf.rel, [])

    return check


def register_dataflow_rules(register) -> None:
    """Hook the four value-flow rules into the ordinary rule registry
    (rules.py calls this before computing MARKERS)."""
    register(
        "traced-python-branch", "traced-branch-ok",
        "if/while/assert on a value that reaches a jit-traced body "
        "(retraces per value or raises TracerBoolConversionError)",
        dataflow_scope,
    )(_check_for("traced-python-branch"))
    register(
        "weak-type-cache-split", "weak-type-ok",
        "dtype-less Python literal flowing into a jitted call (weak-type "
        "promotion splits the executable cache on the same shapes)",
        dataflow_scope,
    )(_check_for("weak-type-cache-split"))
    register(
        "unhashable-static-arg", "static-arg-ok",
        "dict/list/lambda reaching a jit/lower static position "
        "(unhashable cache key fails at first dispatch)",
        dataflow_scope,
    )(_check_for("unhashable-static-arg"))
    register(
        "host-sync-on-tracer", "tracer-sync-ok",
        "int()/float()/np.asarray on a traced value in engine/solver "
        "paths (a silent device round-trip the sync-ok grep cannot see)",
        sync_scope,
    )(_check_for("host-sync-on-tracer"))

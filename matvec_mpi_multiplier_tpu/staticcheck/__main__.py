"""Staticcheck CLI — the single lint entry point CI and tests share.

Usage::

    python -m matvec_mpi_multiplier_tpu.staticcheck            # rules + keyspace + HLO
    python -m matvec_mpi_multiplier_tpu.staticcheck --rules    # AST rules only, ~1 s
    python -m matvec_mpi_multiplier_tpu.staticcheck --lockgraph  # rules #13-#15 only
    python -m matvec_mpi_multiplier_tpu.staticcheck --keyspace  # ExecKey-space audit
    python -m matvec_mpi_multiplier_tpu.staticcheck --hlo-audit  # schedule + memory
    python -m matvec_mpi_multiplier_tpu.staticcheck --memory-audit
    python -m matvec_mpi_multiplier_tpu.staticcheck --json
    python -m matvec_mpi_multiplier_tpu.staticcheck --write-golden
    python -m matvec_mpi_multiplier_tpu.staticcheck --list

``scripts/tier1.sh --lint-only`` runs ``--rules`` (fail-fast: the AST
layer — the lock-graph auditor included — never initializes a device
backend; the parent package import still pulls jax in, but no
compile/trace work runs). ``--hlo-audit`` lowers every audited config on
an abstract 8-device CPU mesh and runs BOTH artifact layers (collective
schedule + compiled-artifact memory); ``--memory-audit`` runs the
memory layer alone (donation → aliasing, peak liveness). This process
forces the virtual-device flags itself, so it works from any shell.
``--root`` points the rule layer at another corpus (the
seeded-violation agreement test).

``--keyspace`` runs the static ExecKey-space compile-surface audit
(staticcheck/keyspace.py): a pure symbolic enumeration — no mesh, no
lowering — checked against ``data/staticcheck/golden_keyspace.json``
and the ``steady ⊆ warmup`` compile budget. ``--keyspace
--write-golden`` blesses the keyspace golden alone; a bare
``--write-golden`` blesses both it and the HLO schedule table.

Exit status (distinct per failure class, worst-first):

* ``0`` — clean
* ``1`` — AST rule findings (incl. the lock-graph and value-flow rules)
* ``2`` — usage/environment error
* ``3`` — artifact-audit failures (schedule/bytes/dequant/donation/
  peak/fingerprint, or ``keyspace-steady-unwarmed`` — the tree violates
  an artifact invariant)
* ``4`` — golden drift only (``hlo-golden``/``hlo-census``/
  ``keyspace-golden`` — the tree and a committed table disagree;
  re-bless or revert)
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

EXIT_CLEAN = 0
EXIT_RULES = 1
EXIT_USAGE = 2
EXIT_HLO = 3
EXIT_DRIFT = 4


def exit_status(findings) -> int:
    """The CLI's verdict for a findings list: rule findings dominate,
    then hard artifact-audit failures (HLO + keyspace), then golden
    drift (severity ``"drift"``)."""
    if not findings:
        return EXIT_CLEAN
    if any(
        not (f.rule.startswith("hlo-") or f.rule.startswith("keyspace-"))
        for f in findings
    ):
        return EXIT_RULES
    if any(f.severity != "drift" for f in findings):
        return EXIT_HLO
    return EXIT_DRIFT


def _force_cpu_mesh() -> None:
    """Pin the abstract audit mesh BEFORE jax initializes (same contract
    as tests/conftest.py). An inherited device-count flag is REPLACED, not
    kept — the audit needs its exact mesh regardless of the shell's own
    XLA_FLAGS tuning."""
    import re

    from .hlo import AUDIT_DEVICES

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    ).strip()
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={AUDIT_DEVICES}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    # Match the test tier (tests/conftest.py): x64 on. The schedule
    # census is width-insensitive, but the memory audit's peak-liveness
    # walk counts every tensor — scalar constants change width under
    # x64, so the CLI and the suite must lower in the same mode or the
    # golden peaks drift by a few bytes between them.
    jax.config.update("jax_enable_x64", True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m matvec_mpi_multiplier_tpu.staticcheck",
        description=(
            "AST lint rules (incl. the lock-graph concurrency auditor) + "
            "lowered-HLO schedule and compiled-artifact memory audits "
            "(docs/STATIC_ANALYSIS.md)"
        ),
    )
    parser.add_argument(
        "--rules", action="store_true",
        help="run the AST rule layer (default: rules + HLO audit)",
    )
    parser.add_argument(
        "--lockgraph", action="store_true",
        help="run ONLY the lock-graph concurrency rules (#13-#15: "
        "lock-mixed-guard, lock-order-inversion, callback-under-lock)",
    )
    parser.add_argument(
        "--keyspace", action="store_true",
        help="run the static ExecKey-space compile-surface audit "
        "(symbolic enumeration vs golden_keyspace.json + the "
        "steady-subset-of-warmup compile budget; no device backend)",
    )
    parser.add_argument(
        "--hlo-audit", action="store_true",
        help="run the lowered-HLO audit (collective schedule + "
        "compiled-artifact memory)",
    )
    parser.add_argument(
        "--memory-audit", action="store_true",
        help="run the compiled-artifact memory audit alone (donation -> "
        "aliasing, peak liveness vs the quantized ceilings)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable findings on stdout (per-finding rule, "
        "severity and marker fields)",
    )
    parser.add_argument(
        "--rule", action="append", metavar="NAME",
        help="restrict the rule layer to NAME (repeatable)",
    )
    parser.add_argument(
        "--root", type=Path, default=None,
        help="corpus root for the RULE layer only (default: this "
        "checkout); the HLO audit always runs against this checkout's "
        "strategies and golden table",
    )
    parser.add_argument(
        "--write-golden", action="store_true",
        help="re-lower every audited config and bless the golden "
        "schedule table (data/staticcheck/golden_schedule.json)",
    )
    parser.add_argument(
        "--list", action="store_true",
        help="list the rule catalogue and exit",
    )
    args = parser.parse_args(argv)

    from .findings import render_json, render_text
    from .lockgraph import LOCKGRAPH_RULES
    from .rules import RULES, get_rule

    if args.list:
        width = max(len(n) for n in RULES)
        for name, rule in sorted(RULES.items()):
            marker = f"# {rule.marker}:" if rule.marker else "(no marker)"
            print(f"{name:<{width}}  {marker:<14}  {rule.description}")
        return EXIT_CLEAN

    if args.rule:
        try:
            for name in args.rule:
                get_rule(name)
        except KeyError as e:
            print(f"staticcheck: {e.args[0]}", file=sys.stderr)
            return EXIT_USAGE

    explicit = (
        args.rules or args.lockgraph or args.hlo_audit
        or args.memory_audit or args.keyspace
    )
    run_rules_layer = args.rules or not explicit
    run_hlo_layer = args.hlo_audit or not explicit
    run_memory_only = args.memory_audit and not args.hlo_audit
    run_keyspace_layer = args.keyspace or not explicit
    if args.write_golden:
        # A bare --write-golden blesses every golden (schedule +
        # keyspace); with an explicit layer flag it blesses only the
        # layers that run — `--keyspace --write-golden` stays symbolic
        # (no mesh, no lowering).
        run_keyspace_layer = True
        if args.hlo_audit or args.memory_audit or not explicit:
            run_hlo_layer = True
            run_memory_only = False

    findings = []
    if run_rules_layer or args.lockgraph:
        from .rules import run_rules

        selected = args.rule
        if args.lockgraph and not run_rules_layer:
            selected = list(LOCKGRAPH_RULES) + (args.rule or [])
        findings.extend(run_rules(root=args.root, rules=selected))

    if run_keyspace_layer:
        from .keyspace import run_keyspace_audit, write_golden_keyspace

        if args.write_golden:
            try:
                path = write_golden_keyspace()
            except ValueError as e:
                print(f"staticcheck: {e}", file=sys.stderr)
                return EXIT_USAGE
            print(
                f"staticcheck: golden keyspace table written to {path}",
                file=sys.stderr,
            )
        # Like the HLO audit, --root does not reach this layer: the
        # enumerated keyspace and its golden are properties of THIS
        # checkout's engine, not of an alternate lint corpus.
        findings.extend(run_keyspace_audit())

    if run_hlo_layer or run_memory_only:
        _force_cpu_mesh()
        from .hlo import run_hlo_audit, write_golden

        try:
            # Note: --root deliberately does NOT reach the audit — the
            # lowered schedules and the golden table are properties of
            # THIS checkout, not of an alternate lint corpus.
            if args.write_golden:
                path = write_golden()
                print(f"staticcheck: golden schedule table written to {path}",
                      file=sys.stderr)
            findings.extend(run_hlo_audit(
                schedule=not run_memory_only,
                solvers=not run_memory_only,
                fused_solvers=not run_memory_only,
            ))
        except RuntimeError as e:
            print(f"staticcheck: {e}", file=sys.stderr)
            return EXIT_USAGE

    findings = sorted(set(findings))
    print(render_json(findings) if args.json else render_text(findings))
    return exit_status(findings)


if __name__ == "__main__":
    sys.exit(main())

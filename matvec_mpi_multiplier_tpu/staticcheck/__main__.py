"""Staticcheck CLI — the single lint entry point CI and tests share.

Usage::

    python -m matvec_mpi_multiplier_tpu.staticcheck            # rules + HLO audit
    python -m matvec_mpi_multiplier_tpu.staticcheck --rules    # AST rules only, ~1 s
    python -m matvec_mpi_multiplier_tpu.staticcheck --hlo-audit
    python -m matvec_mpi_multiplier_tpu.staticcheck --json
    python -m matvec_mpi_multiplier_tpu.staticcheck --write-golden
    python -m matvec_mpi_multiplier_tpu.staticcheck --list

``scripts/tier1.sh --lint-only`` runs ``--rules`` (fail-fast: the AST
layer never initializes a device backend — the parent package import
still pulls jax in, but no compile/trace work runs). ``--hlo-audit``
lowers every audited config on
an abstract 8-device CPU mesh — this process forces the virtual-device
flags itself, so it works from any shell. ``--root`` points the rule layer
at another corpus (the seeded-violation agreement test). Exit status: 0
clean, 1 findings, 2 usage/environment error.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path


def _force_cpu_mesh() -> None:
    """Pin the abstract audit mesh BEFORE jax initializes (same contract
    as tests/conftest.py). An inherited device-count flag is REPLACED, not
    kept — the audit needs its exact mesh regardless of the shell's own
    XLA_FLAGS tuning."""
    import re

    from .hlo import AUDIT_DEVICES

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    ).strip()
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={AUDIT_DEVICES}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m matvec_mpi_multiplier_tpu.staticcheck",
        description=(
            "AST lint rules + lowered-HLO collective-schedule audit "
            "(docs/STATIC_ANALYSIS.md)"
        ),
    )
    parser.add_argument(
        "--rules", action="store_true",
        help="run the AST rule layer (default: rules + HLO audit)",
    )
    parser.add_argument(
        "--hlo-audit", action="store_true",
        help="run the lowered-HLO collective-schedule audit",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable findings on stdout",
    )
    parser.add_argument(
        "--rule", action="append", metavar="NAME",
        help="restrict the rule layer to NAME (repeatable)",
    )
    parser.add_argument(
        "--root", type=Path, default=None,
        help="corpus root for the RULE layer only (default: this "
        "checkout); the HLO audit always runs against this checkout's "
        "strategies and golden table",
    )
    parser.add_argument(
        "--write-golden", action="store_true",
        help="re-lower every audited config and bless the golden "
        "schedule table (data/staticcheck/golden_schedule.json)",
    )
    parser.add_argument(
        "--list", action="store_true",
        help="list the rule catalogue and exit",
    )
    args = parser.parse_args(argv)

    from .findings import render_json, render_text
    from .rules import RULES, get_rule

    if args.list:
        width = max(len(n) for n in RULES)
        for name, rule in sorted(RULES.items()):
            marker = f"# {rule.marker}:" if rule.marker else "(no marker)"
            print(f"{name:<{width}}  {marker:<14}  {rule.description}")
        return 0

    if args.rule:
        try:
            for name in args.rule:
                get_rule(name)
        except KeyError as e:
            print(f"staticcheck: {e.args[0]}", file=sys.stderr)
            return 2

    run_rules_layer = args.rules or not (args.rules or args.hlo_audit)
    run_hlo_layer = args.hlo_audit or not (args.rules or args.hlo_audit)
    if args.write_golden:
        run_hlo_layer = True

    findings = []
    if run_rules_layer:
        from .rules import run_rules

        findings.extend(run_rules(root=args.root, rules=args.rule))

    if run_hlo_layer:
        _force_cpu_mesh()
        from .hlo import run_hlo_audit, write_golden

        try:
            # Note: --root deliberately does NOT reach the audit — the
            # lowered schedules and the golden table are properties of
            # THIS checkout, not of an alternate lint corpus.
            if args.write_golden:
                path = write_golden()
                print(f"staticcheck: golden schedule table written to {path}",
                      file=sys.stderr)
            findings.extend(run_hlo_audit())
        except RuntimeError as e:
            print(f"staticcheck: {e}", file=sys.stderr)
            return 2

    findings = sorted(set(findings))
    print(render_json(findings) if args.json else render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())

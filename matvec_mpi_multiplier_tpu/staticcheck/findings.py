"""Findings: the one result type both analysis layers report.

A finding is (rule, file, line, message) — file repo-relative, line
1-indexed (0 for whole-artifact findings like a golden-table mismatch).
Reporters render the same list as ``file:line: [rule] message`` text (the
CI log format) or as JSON (``--json``, the machine face the seeded-corpus
agreement test compares across entry points).
"""

from __future__ import annotations

import dataclasses
import json


# Rules whose findings mean "the committed golden table disagrees with
# the tree" rather than "the tree violates an invariant" — a distinct
# severity (and CLI exit status) because the remedy is different:
# re-bless the table, or revert the schedule/keyspace change.
DRIFT_RULES = frozenset({"hlo-golden", "hlo-census", "keyspace-golden"})


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation (or audit mismatch), sorted file-then-line.

    ``severity`` is ``"error"`` for invariant violations and ``"drift"``
    for golden-table disagreements (:data:`DRIFT_RULES`); ``marker`` is
    the ``# <marker>: <reason>`` comment that could exempt this finding
    (None for rules without an escape hatch)."""

    path: str   # repo-relative posix path ("" for repo-level findings)
    line: int   # 1-indexed; 0 when no single line applies
    rule: str   # rule slug, e.g. "engine-host-sync"
    message: str
    severity: str = "error"
    marker: str | None = None

    def __post_init__(self):
        # The rule, not the construction site, owns the severity: a
        # drift-rule Finding is "drift" even when a future call site
        # forgets to say so (the CLI's exit-code classes depend on it).
        if self.rule in DRIFT_RULES and self.severity == "error":
            object.__setattr__(self, "severity", "drift")

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}" if self.line else (self.path or "-")

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def dedup(findings) -> list[Finding]:
    """Sorted view, duplicate-free by (path, line, rule): alias chains
    can hit one line twice, and one site reached through two scope
    predicates (or two message spellings of the same violation) is still
    ONE finding to fix — the first (lowest-sorting) message wins."""
    out: dict[tuple[str, int, str], Finding] = {}
    for f in sorted(findings):
        out.setdefault((f.path, f.line, f.rule), f)
    return list(out.values())


def render_text(findings) -> str:
    lines = [f"{f.location}: [{f.rule}] {f.message}" for f in findings]
    n = len(findings)
    lines.append(
        "staticcheck: ok (0 findings)" if n == 0
        else f"staticcheck: {n} finding{'s' if n != 1 else ''}"
    )
    return "\n".join(lines)


def render_json(findings, **extra) -> str:
    by_rule: dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    payload = {
        "findings": [f.as_dict() for f in findings],
        "counts": {"total": len(findings), "by_rule": by_rule},
        **extra,
    }
    return json.dumps(payload, indent=2, sort_keys=True)

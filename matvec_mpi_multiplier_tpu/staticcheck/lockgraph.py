"""Lock-graph concurrency auditor: whole-program lock analysis.

The serving engine is a genuinely concurrent system — a dozen-plus locks
across ``engine/``, ``obs/`` and ``resilience/`` guard the registry
ledger, breakers, scheduler queues and exec cache — and every recent
review pass caught real races by hand (PR 9: phantom HBM ledger charge,
quota overrun N-1 deep, ``health()`` racing ``_walk_ladder``). The
line-level rules (#8, #11) pin *what may not happen under a lock*; this
module analyzes *how the locks compose*, whole-program, as rules
#13–#15 in the ordinary registry (markers, fixtures and CLI plumbing
inherit):

* **#13 ``lock-mixed-guard``** (marker ``unguarded-ok``) — per-class
  guard-set inference: a ``self._*`` attribute written under a
  ``with self._lock``-style context somewhere but read (or written)
  with no lock held elsewhere is a torn/stale-state hazard. The repo's
  ``*_locked``-suffix helper convention (``_take_locked``,
  ``_evict_for_locked`` — "caller holds the lock") is built in: their
  bodies count as guarded, and *calling* a ``*_locked`` helper with no
  lock held is itself a finding.
* **#14 ``lock-order-inversion``** (marker ``lock-order-ok``) — the
  cross-class lock-acquisition order graph: an edge A→B is recorded
  whenever code acquires B while holding A, directly or through a
  method call (resolved via ``self`` methods, constructor-annotated
  attribute types, and name-based fallback over the corpus — the alias
  discipline ``corpus.py`` established for imports, extended to
  methods). A cycle means two threads can take the same locks in
  opposite orders and deadlock; the audit fails on any cycle. A marker
  on an edge's acquisition/call site removes that edge.
* **#15 ``callback-under-lock``** (marker ``callback-ok``) — invoking a
  callback/listener (``*listener*``/``*callback*``/``*hook*``/
  ``on_*``-named callables, directly or transitively through resolved
  method calls) while holding a lock runs UNKNOWN code under a held
  mutex — the exact shape of the PR 9 ledger bug, where the engine's
  residency listener fired under the residency bookkeeping lock and
  re-entered the registry. Deliberate, documented exceptions (the
  registry's reentrant victim-release path) carry the marker.

Scope: ``engine/``, ``obs/``, ``resilience/`` and ``tuning/`` — the
subsystems with locks (tuning rides along so a future cache mutex is
covered the day it appears). Pure AST work: this module must stay
jax-import-free so ``scripts/tier1.sh --lint-only`` keeps its budget.

The analysis is whole-program (the graph spans files), while the rule
engine is per-file: ``analyze(root)`` builds one :class:`LockGraph` per
corpus (cached, keyed by file content) and each rule's per-file check
reads its slice of the findings out of it.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
from pathlib import Path
from typing import Iterator

from .corpus import SourceFile, iter_corpus, source_file

_PKG = "matvec_mpi_multiplier_tpu"

# The concurrent subsystems the auditor covers.
SCOPE_DIRS = ("engine", "obs", "resilience", "tuning")

LOCKGRAPH_RULES = (
    "lock-mixed-guard", "lock-order-inversion", "callback-under-lock",
)

# Context-manager / attribute name fragments that mark a lock (same
# vocabulary as rules #8/#11).
_LOCKISH = ("lock", "cond", "mutex")
# Callee-name fragments that mark a callback (the listener/hook surface
# the engine, registry and breakers expose).
_CALLBACK_FRAGMENTS = ("listener", "callback", "hook")
_LOCKED_SUFFIX = "_locked"

# Receiver-mutating method names: `self._pending.append(x)` is a WRITE
# to self._pending for guard purposes, not a read of the binding.
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update", "remove",
    "discard", "pop", "popleft", "popitem", "clear", "setdefault",
})

# Guard token for `*_locked` helper bodies: "guarded by whatever lock the
# caller holds" — compatible with every own lock in the guard check,
# invisible to the order graph (which uses the real own-lock ids).
_ANY = ("<caller>", "<locked-helper>")

_LOCK_FACTORIES = ("threading.Lock", "threading.RLock", "threading.Condition")


def lockgraph_scope(rel: str) -> bool:
    return any(rel.startswith(f"{_PKG}/{d}/") for d in SCOPE_DIRS)


def _is_lockish(name: str) -> bool:
    return any(f in name.lower() for f in _LOCKISH)


def _is_callbackish(name: str) -> bool:
    n = name.lower()
    return (
        any(f in n for f in _CALLBACK_FRAGMENTS)
        or n.startswith("on_")
        or n.startswith("_on_")
    )


def _fmt_lock(lock: tuple[str, str]) -> str:
    return f"{lock[0]}.{lock[1]}"


# --------------------------------------------------------- per-file model


@dataclasses.dataclass
class _Access:
    attr: str
    kind: str               # "read" | "write"
    held: frozenset         # lock ids (incl. _ANY in *_locked helpers)
    node: ast.AST


@dataclasses.dataclass
class _CallSite:
    target: tuple           # ("self", name) | ("attr", base, name) | ("name", name)
    held: frozenset
    node: ast.AST


@dataclasses.dataclass
class _Acquire:
    lock: tuple[str, str]   # lock id (owner, attr)
    held: frozenset
    node: ast.AST


class _Method:
    __slots__ = (
        "cls", "name", "sf", "node", "accesses", "calls", "acquires",
        "is_locked_helper", "is_init",
    )

    def __init__(self, cls: "_Class | None", name: str, sf: SourceFile,
                 node: ast.AST):
        self.cls = cls
        self.name = name
        self.sf = sf
        self.node = node
        self.accesses: list[_Access] = []
        self.calls: list[_CallSite] = []
        self.acquires: list[_Acquire] = []
        self.is_locked_helper = name.endswith(_LOCKED_SUFFIX)
        self.is_init = name == "__init__"


class _Class:
    __slots__ = ("name", "sf", "methods", "own_locks", "attr_types")

    def __init__(self, name: str, sf: SourceFile):
        self.name = name
        self.sf = sf
        self.methods: dict[str, _Method] = {}
        self.own_locks: set[str] = set()      # lockish self attrs
        self.attr_types: dict[str, str] = {}  # self attr -> annotated class


def _ann_name(ann: ast.AST | None) -> str | None:
    """The terminal class name of a parameter annotation (string
    annotations unquoted, `a.b.C` -> `C`, Optional-ish wrappers ignored)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip("'\"").split(".")[-1].split("[")[0].strip()
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Attribute):
        return ann.attr
    return None


def _self_chain(expr: ast.AST) -> list[str] | None:
    """`self.a.b` -> ["self", "a", "b"]; None for non-self-rooted chains."""
    parts: list[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self":
        parts.append("self")
        return list(reversed(parts))
    return None


class _MethodWalker:
    """One method body, walked with the held-lock set threaded through:
    records attribute accesses, lock acquisitions and call sites.
    Deferred bodies (nested def/lambda) are skipped — they run under
    whatever lock state exists at call time, not this one."""

    def __init__(self, sf: SourceFile, cls: _Class | None, meth: _Method):
        self.sf = sf
        self.cls = cls
        self.meth = meth

    def run(self) -> None:
        held: frozenset = frozenset()
        if self.meth.is_locked_helper and self.cls is not None:
            held = frozenset(
                {(self.cls.name, lk) for lk in self.cls.own_locks}
            ) | {_ANY}
        body = getattr(self.meth.node, "body", [])
        for stmt in body:
            self._visit(stmt, held)

    # ---- lock identification ----

    def _lock_of(self, expr: ast.AST) -> tuple[str, str] | None:
        """The lock a with-item acquires, as an (owner, attr) id — or
        None for a non-lockish context manager (a trace span)."""
        ctx = self.cls.name if self.cls is not None else f"<{self.sf.rel}>"
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Attribute) and _is_lockish(sub.attr):
                chain = _self_chain(sub)
                if chain is None:
                    # with eng._b_lock: — a lock reached through a local
                    # or parameter. Owner unknown here; a context-scoped
                    # placeholder that _normalize_locks unifies by unique
                    # lock-attr name across the corpus (so a direct AB/BA
                    # through a local is still a cycle).
                    root = sub.value
                    base = root.id if isinstance(root, ast.Name) else "expr"
                    return (f"?{ctx}.{base}", sub.attr)
                if len(chain) == 2 and self.cls is not None:
                    # with self._lock:
                    return (self.cls.name, chain[1])
                if len(chain) == 3 and self.cls is not None:
                    # with self.registry._lock: — owner via the annotated
                    # attribute type when known; otherwise a placeholder
                    # scoped to THIS class+attr (so unrelated classes'
                    # `?engine` never collide into phantom edges) that
                    # LockGraph._normalize_locks unifies by unique lock
                    # attr name across the corpus.
                    owner = self.cls.attr_types.get(
                        chain[1], f"?{self.cls.name}.{chain[1]}"
                    )
                    return (owner, chain[2])
            elif isinstance(sub, ast.Name) and _is_lockish(sub.id):
                # with _default_lock: (a module-level mutex)
                owner = (
                    self.cls.name if self.cls is not None
                    else f"<{self.sf.rel}>"
                )
                return (owner, sub.id)
        return None

    # ---- the walk ----

    def _visit(self, node: ast.AST, held: frozenset) -> None:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return  # deferred body
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # Items acquire left-to-right: `with self._a, self._b:` holds
            # _a while acquiring _b, so each item's acquisition event
            # carries the locks the EARLIER items already took (the
            # AB/BA inversion the order graph exists to catch).
            cur = held
            for item in node.items:
                self._visit(item.context_expr, cur)
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    # Anchored to the context EXPRESSION (one line), not
                    # the With node — a With spans its whole body, and a
                    # marker deep inside the block must not exempt the
                    # acquisition edge recorded at its head.
                    self.meth.acquires.append(
                        _Acquire(lock, cur, item.context_expr)
                    )
                    cur = cur | {lock}
            for stmt in node.body:
                self._visit(stmt, cur)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, held)
            return
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            # self.charged[k] = v — a write to self.charged.
            chain = _self_chain(node.value)
            if chain is not None and len(chain) == 2:
                self._access(chain[1], "write", held, node)
                self._visit(node.slice, held)
                return
        if isinstance(node, ast.Attribute):
            chain = _self_chain(node)
            if chain is not None and len(chain) == 2:
                kind = (
                    "write" if isinstance(node.ctx, (ast.Store, ast.Del))
                    else "read"
                )
                self._access(chain[1], kind, held, node)
                return
            # fall through: visit the base (self.engine.submit reads
            # self.engine on the way down)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _access(self, attr: str, kind: str, held: frozenset,
                node: ast.AST) -> None:
        self.meth.accesses.append(_Access(attr, kind, held, node))

    def _visit_call(self, call: ast.Call, held: frozenset) -> None:
        fn = call.func
        target = None
        if isinstance(fn, ast.Attribute):
            chain = _self_chain(fn)
            if chain is not None and len(chain) == 2:
                # self.method(...) / self._listener(...). Invoking IS
                # reading the attribute: a callable attr written under a
                # lock and called bare must register as a bare read
                # (class methods are never written attrs, so this is
                # noise-free for ordinary method calls).
                target = ("self", chain[1])
                self._access(chain[1], "read", held, fn)
            elif chain is not None and len(chain) == 3:
                # self.registry.prefetch(...)
                target = ("attr", chain[1], chain[2])
                self._access(chain[1], "read", held, fn.value)
            else:
                # entry.engine.submit(...) — name-based fallback
                target = ("name", fn.attr)
                self._visit(fn.value, held)
            # receiver-mutating method on a self attribute is a write
            if (
                chain is not None and len(chain) == 3
                and fn.attr in _MUTATORS
            ):
                # self._pending.append(...): rewrite the read recorded
                # above into a write (last recorded access is the base).
                self.meth.accesses[-1] = _Access(
                    chain[1], "write", held, fn.value
                )
        elif isinstance(fn, ast.Name):
            target = ("name", fn.id)
        else:
            self._visit(fn, held)
        if target is not None:
            self.meth.calls.append(_CallSite(target, held, call))
        for arg in call.args:
            self._visit(arg, held)
        for kw in call.keywords:
            self._visit(kw.value, held)


# ------------------------------------------------------- the whole program


class LockGraph:
    """One corpus's lock analysis: classes, methods, the acquisition
    graph, and the per-rule findings, keyed by repo-relative path."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self.classes: dict[str, _Class] = {}
        self.module_funcs: dict[str, list[_Method]] = {}
        self.methods_by_name: dict[str, list[_Method]] = {}
        self.all_methods: list[_Method] = []
        # rule -> rel -> [(node, message)]
        self.findings: dict[str, dict[str, list[tuple[ast.AST, str]]]] = {
            rule: {} for rule in LOCKGRAPH_RULES
        }
        # rel -> line spans where a '# lock-order-ok:' marker actually
        # DROPPED an edge. This rule consumes its marker before cycle
        # detection (an exempted edge suppresses the whole cycle, so no
        # raw finding ever surfaces at the marked site — or at its
        # sibling edges); the stale-marker audit must take these spans
        # as live coverage or every working exemption looks rotted.
        self.marker_hits: dict[str, set[int]] = {}
        self._build()
        self._normalize_locks()
        self._refine_locked_helpers()
        self._infer_guards()
        self._build_graph()
        self._check_callbacks()

    # ---- corpus ingestion ----

    def _build(self) -> None:
        for path in iter_corpus(self.root):
            rel = path.relative_to(self.root).as_posix()
            if not lockgraph_scope(rel):
                continue
            try:
                sf = source_file(path, self.root)
            except (SyntaxError, UnicodeDecodeError):
                continue  # run_rules owns the parse-error finding
            for node in sf.nodes(ast.ClassDef):
                self._ingest_class(sf, node)
            for node in sf.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    meth = _Method(None, node.name, sf, node)
                    _MethodWalker(sf, None, meth).run()
                    self.module_funcs.setdefault(node.name, []).append(meth)
                    self.all_methods.append(meth)

    def _ingest_class(self, sf: SourceFile, node: ast.ClassDef) -> None:
        cls = _Class(node.name, sf)
        methods = [
            n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        # Pass 1: own locks (lockish self attrs assigned a threading
        # factory, or entered as a context) and annotated attr types.
        for m in methods:
            params = {
                a.arg: _ann_name(a.annotation) for a in m.args.args
            }
            for sub in ast.walk(m):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    chain = _self_chain(sub.targets[0])
                    if chain is None or len(chain) != 2:
                        continue
                    attr = chain[1]
                    q = (
                        sf.qualname(sub.value.func)
                        if isinstance(sub.value, ast.Call) else None
                    )
                    if q in _LOCK_FACTORIES and _is_lockish(attr):
                        cls.own_locks.add(attr)
                    if m.name == "__init__" and isinstance(
                        sub.value, ast.Name
                    ):
                        ann = params.get(sub.value.id)
                        if ann:
                            cls.attr_types[attr] = ann
                elif isinstance(sub, (ast.With, ast.AsyncWith)):
                    for item in sub.items:
                        for inner in ast.walk(item.context_expr):
                            chain = (
                                _self_chain(inner)
                                if isinstance(inner, ast.Attribute) else None
                            )
                            if (
                                chain is not None and len(chain) == 2
                                and _is_lockish(chain[1])
                            ):
                                cls.own_locks.add(chain[1])
        # Pass 2: walk bodies with the held-lock context.
        for m in methods:
            meth = _Method(cls, m.name, sf, m)
            _MethodWalker(sf, cls, meth).run()
            cls.methods[m.name] = meth
            self.methods_by_name.setdefault(m.name, []).append(meth)
            self.all_methods.append(meth)
        self.classes[cls.name] = cls

    # ---- lock-id normalization ----

    def _normalize_locks(self) -> None:
        """Unify unresolved foreign-lock placeholders (`with
        self.other._residency_lock:` where ``other`` carries no type
        annotation) with the class that owns a lock of that attr name —
        when exactly ONE class in the corpus does. Without this, a
        direct AB/BA acquisition through an unannotated attribute would
        produce two never-unifying nodes and the cycle would be
        invisible; with a non-unique attr name (every metrics class
        calls its mutex ``_lock``) the placeholder is kept — ambiguity
        must not fabricate phantom edges."""
        owners: dict[str, list[str]] = {}
        for cls in self.classes.values():
            for lk in cls.own_locks:
                owners.setdefault(lk, []).append(cls.name)

        def norm(lock):
            if lock == _ANY or not lock[0].startswith("?"):
                return lock
            unique = owners.get(lock[1], [])
            return (unique[0], lock[1]) if len(unique) == 1 else lock

        for m in self.all_methods:
            for a in m.acquires:
                a.lock = norm(a.lock)
                a.held = frozenset(norm(lk) for lk in a.held)
            for acc in m.accesses:
                acc.held = frozenset(norm(lk) for lk in acc.held)
            for call in m.calls:
                call.held = frozenset(norm(lk) for lk in call.held)

    def _refine_locked_helpers(self) -> None:
        """Tighten the ``*_locked`` helpers' assumed held set from "all
        of the class's own locks" to the union of what their callers
        ACTUALLY hold at the call sites. On a one-lock class the two are
        identical; on a multi-lock class the conservative assumption
        fabricates edges from locks no execution path holds — a phantom
        deadlock cycle the author would have to mark away. Helpers with
        no observed lock-holding caller keep the conservative set (a
        helper exercised only from fixtures must not silently lose its
        guard semantics)."""
        for cls in self.classes.values():
            if not cls.own_locks:
                continue
            assumed = frozenset(
                (cls.name, lk) for lk in cls.own_locks
            ) | {_ANY}
            for helper in cls.methods.values():
                if not helper.is_locked_helper:
                    continue
                callers_held: set = set()
                for caller in cls.methods.values():
                    for call in caller.calls:
                        if (
                            call.target == ("self", helper.name)
                            and call.held
                        ):
                            callers_held |= {
                                lk for lk in call.held if lk != _ANY
                            }
                if not callers_held:
                    continue
                actual = frozenset(callers_held) | {_ANY}

                def swap(held):
                    # Inside the helper every event's held set contains
                    # the symbolic assumption (plus any locks the body
                    # acquired on top — those survive the swap).
                    return (held - assumed) | actual if _ANY in held \
                        else held

                for a in helper.acquires:
                    a.held = swap(a.held)
                for acc in helper.accesses:
                    acc.held = swap(acc.held)
                for call in helper.calls:
                    call.held = swap(call.held)

    # ---- resolution ----

    def _resolve(self, meth: _Method, target: tuple) -> list[_Method]:
        """Call targets a site may reach: `self` methods exactly, typed
        attributes exactly, then the name-based corpus fallback."""
        kind = target[0]
        if kind == "self" and meth.cls is not None:
            own = meth.cls.methods.get(target[1])
            if own is not None:
                return [own]
            return self._by_name(target[1])
        if kind == "attr" and meth.cls is not None:
            base, name = target[1], target[2]
            tname = meth.cls.attr_types.get(base)
            if tname is not None and tname in self.classes:
                m = self.classes[tname].methods.get(name)
                return [m] if m is not None else []
            return self._by_name(name)
        return self._by_name(target[-1])

    def _by_name(self, name: str) -> list[_Method]:
        if name in self.classes:
            init = self.classes[name].methods.get("__init__")
            return [init] if init is not None else []
        return list(self.methods_by_name.get(name, [])) + list(
            self.module_funcs.get(name, [])
        )

    def _add(self, rule: str, sf: SourceFile, node: ast.AST,
             message: str) -> None:
        self.findings[rule].setdefault(sf.rel, []).append((node, message))

    # ---- rule #13: guard-set inference ----

    def _infer_guards(self) -> None:
        for cls in self.classes.values():
            if not cls.own_locks:
                continue
            writes: dict[str, set] = {}
            write_site: dict[str, ast.AST] = {}
            for meth in cls.methods.values():
                if meth.is_init:
                    continue
                for acc in meth.accesses:
                    if acc.kind == "write" and acc.held:
                        writes.setdefault(acc.attr, set()).update(acc.held)
                        write_site.setdefault(acc.attr, acc.node)
            for meth in cls.methods.values():
                if meth.is_init:
                    continue
                for acc in meth.accesses:
                    locks = writes.get(acc.attr)
                    if locks is None or _is_lockish(acc.attr):
                        continue
                    if self._guarded(acc.held, locks):
                        continue
                    site = write_site[acc.attr]
                    named = sorted(
                        _fmt_lock(lk) for lk in locks if lk != _ANY
                    ) or ["the caller-held lock"]
                    held_names = sorted(
                        _fmt_lock(lk) for lk in acc.held if lk != _ANY
                    )
                    how = (
                        "with no lock held" if not held_names else
                        f"holding only {', '.join(held_names)} — not a "
                        "lock it is written under"
                    )
                    self._add(
                        "lock-mixed-guard", cls.sf, acc.node,
                        f"self.{acc.attr} is written under "
                        f"{', '.join(named)} (e.g. line "
                        f"{getattr(site, 'lineno', '?')}) but "
                        f"{'written' if acc.kind == 'write' else 'read'} "
                        f"here {how} — a concurrent writer can "
                        "tear or stale this access (guard it, or mark a "
                        "deliberate racy read with '# unguarded-ok: "
                        "<reason>')",
                    )
                # Calling a *_locked helper with no lock held breaks the
                # convention the helper's name promises.
                for call in meth.calls:
                    if (
                        call.target[0] == "self"
                        and call.target[1].endswith(_LOCKED_SUFFIX)
                        and not call.held
                        and not meth.is_locked_helper
                    ):
                        self._add(
                            "lock-mixed-guard", cls.sf, call.node,
                            f"{call.target[1]}() is a *_locked helper "
                            "(caller-holds-the-lock convention) invoked "
                            "with no lock held",
                        )

    @staticmethod
    def _guarded(held: frozenset, write_locks: set) -> bool:
        """An access is guarded when it holds one of the locks the
        attribute is written under. ``_ANY`` appears in ``held`` only
        inside a ``*_locked`` helper (guarded by the caller's lock, by
        convention); it is deliberately NOT honored on the write side —
        helper-body writes also stamp the class's real own locks, so a
        read under a *different* object's lock must still miss the
        intersection and be flagged (the wrong-lock case)."""
        if not held:
            return False
        if _ANY in held:
            return True
        return bool((held & write_locks) - {_ANY})

    # ---- rule #14: the acquisition-order graph ----

    def _acquires_transitive(self) -> dict[int, frozenset]:
        """Fixpoint: every lock a method may acquire during its
        execution, directly or through resolved calls."""
        acq: dict[int, set] = {
            id(m): {a.lock for a in m.acquires} for m in self.all_methods
        }
        targets: dict[int, list[_Method]] = {}
        for m in self.all_methods:
            outs: list[_Method] = []
            for call in m.calls:
                outs.extend(self._resolve(m, call.target))
            targets[id(m)] = outs
        changed = True
        while changed:
            changed = False
            for m in self.all_methods:
                cur = acq[id(m)]
                for t in targets[id(m)]:
                    extra = acq[id(t)] - cur
                    if extra:
                        cur |= extra
                        changed = True
        return {k: frozenset(v) for k, v in acq.items()}

    def _build_graph(self) -> None:
        acq = self._acquires_transitive()
        # edge (held, acquired) -> [(sf, node, via)]
        edges: dict[tuple, list] = {}

        def add_edge(h, lk, sf, node, via):
            if h == lk or h == _ANY or lk == _ANY:
                return
            if "lock-order-ok:" in sf.span_comments(node):
                # Marker drops the edge before cycle detection; record
                # the consumed span so the stale audit sees it as live.
                first = getattr(node, "lineno", 0)
                last = getattr(node, "end_lineno", first) or first
                self.marker_hits.setdefault(sf.rel, set()).update(
                    range(first, last + 1)
                )
                return
            edges.setdefault((h, lk), []).append((sf, node, via))

        for m in self.all_methods:
            for a in m.acquires:
                for h in a.held:
                    add_edge(h, a.lock, m.sf, a.node, "direct acquisition")
            for call in m.calls:
                if not call.held:
                    continue
                for t in self._resolve(m, call.target):
                    for lk in acq[id(t)]:
                        for h in call.held:
                            add_edge(
                                h, lk, m.sf, call.node,
                                f"call to {call.target[-1]}()",
                            )
        self.edges = edges
        # Cycle detection over the lock digraph.
        graph: dict[tuple, set] = {}
        for (h, lk) in edges:
            graph.setdefault(h, set()).add(lk)
        for cycle in _find_cycles(graph):
            path = " -> ".join(_fmt_lock(lk) for lk in cycle)
            pairs = list(zip(cycle, cycle[1:]))
            for pair in pairs:
                for sf, node, via in edges.get(pair, []):
                    self._add(
                        "lock-order-inversion", sf, node,
                        f"acquiring {_fmt_lock(pair[1])} while holding "
                        f"{_fmt_lock(pair[0])} ({via}) closes the lock "
                        f"cycle {path} — two threads taking these locks "
                        "in opposite orders deadlock; release before "
                        "acquiring, or mark a proven-safe edge with "
                        "'# lock-order-ok: <reason>'",
                    )

    # ---- rule #15: callbacks under a lock ----

    def _check_callbacks(self) -> None:
        # Fixpoint: does a method invoke a callback (directly, or through
        # self/typed-attr/name-resolved calls)? Direct invocation =
        # calling a callbackish NAME.
        invokes: dict[int, str | None] = {}
        for m in self.all_methods:
            direct = None
            for call in m.calls:
                if _is_callbackish(call.target[-1]):
                    direct = call.target[-1]
                    break
            invokes[id(m)] = direct
        changed = True
        while changed:
            changed = False
            for m in self.all_methods:
                if invokes[id(m)]:
                    continue
                for call in m.calls:
                    for t in self._resolve(m, call.target):
                        via = invokes[id(t)]
                        if via:
                            invokes[id(m)] = via
                            changed = True
                            break
                    if invokes[id(m)]:
                        break

        for m in self.all_methods:
            for call in m.calls:
                if not call.held:
                    continue
                name = call.target[-1]
                held = sorted(
                    _fmt_lock(lk) for lk in call.held if lk != _ANY
                ) or ["the caller-held lock"]
                if _is_callbackish(name):
                    self._add(
                        "callback-under-lock", m.sf, call.node,
                        f"{name}() invoked while holding "
                        f"{', '.join(held)}: a callback is unknown code "
                        "under a held mutex (the PR 9 ledger-bug shape) — "
                        "invoke it after release, or mark a documented "
                        "exception with '# callback-ok: <reason>'",
                    )
                    continue
                # Transitive: suppressed when the target is a *_locked
                # helper of the same class — its own (caller-held) direct
                # site already carries the finding/marker.
                if (
                    call.target[0] == "self"
                    and name.endswith(_LOCKED_SUFFIX)
                ):
                    continue
                for t in self._resolve(m, call.target):
                    via = invokes[id(t)]
                    if via:
                        self._add(
                            "callback-under-lock", m.sf, call.node,
                            f"{name}() invokes the {via} callback while "
                            f"{', '.join(held)} is held (the PR 9 "
                            "ledger-bug shape) — restructure to fire "
                            "after release, or mark a documented "
                            "exception with '# callback-ok: <reason>'",
                        )
                        break


def _find_cycles(graph: dict) -> list[list]:
    """Cycles in the lock digraph, one representative per cyclic SCC
    (Tarjan would be overkill at this node count): DFS from each node,
    reporting the first closed walk found back to it."""
    cycles = []
    seen_cycles = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        visited = set()
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    cycle = path + [start]
                    key = frozenset(cycle)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        cycles.append(cycle)
                elif nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))
    return cycles


# ----------------------------------------------------------- cache + rules


# root -> (generation, content signature, graph). The content signature
# (per-file sha1) decides whether to rebuild; the generation decides
# whether to even RE-READ the corpus — run_rules bumps it once per
# invocation, so the 3 rules' per-file checks share one validation pass
# instead of re-hashing the corpus O(files x rules) times.
_CACHE: dict[str, tuple[int, tuple, LockGraph]] = {}
_GENERATION = [0]


def new_generation() -> None:
    """Invalidate the once-per-run corpus validation (rules.run_rules
    calls this at entry; a direct ``analyze`` caller that mutates files
    between calls must call it too)."""
    _GENERATION[0] += 1


def analyze(root: Path) -> LockGraph:
    """The corpus's lock graph, rebuilt only when an in-scope file's
    content changes, and validated at most once per rule-engine run
    (the rule engine calls per file; the analysis is whole-program)."""
    root = Path(root)
    key = str(root.resolve())
    gen = _GENERATION[0]
    cached = _CACHE.get(key)
    if cached is not None and cached[0] == gen:
        return cached[2]
    sig = []
    for path in iter_corpus(root):
        rel = path.relative_to(root).as_posix()
        if lockgraph_scope(rel):
            sig.append(
                (rel, hashlib.sha1(path.read_bytes()).hexdigest())
            )
    sig_t = tuple(sig)
    if cached is not None and cached[1] == sig_t:
        graph = cached[2]
    else:
        graph = LockGraph(root)
    _CACHE[key] = (gen, sig_t, graph)
    return graph


def _check_for(rule: str):
    def check(sf: SourceFile) -> Iterator[tuple[ast.AST, str]]:
        yield from analyze(sf.root).findings[rule].get(sf.rel, [])

    return check


def register_lockgraph_rules(register) -> None:
    """Hook the three lock-graph rules into the ordinary rule registry
    (rules.py calls this before computing MARKERS)."""
    register(
        "lock-mixed-guard", "unguarded-ok",
        "attribute written under a lock somewhere but accessed bare "
        "elsewhere (torn/stale shared state — the hazard PR-9-era "
        "reviews kept catching by hand)",
        lockgraph_scope,
    )(_check_for("lock-mixed-guard"))
    register(
        "lock-order-inversion", "lock-order-ok",
        "cycle in the cross-class lock-acquisition order graph (two "
        "threads taking the same locks in opposite orders can deadlock)",
        lockgraph_scope,
        # This rule consumes its marker inside the graph build (an
        # exempted edge never reaches cycle detection), so it reports
        # the consumed spans for the stale-marker audit itself.
        covered=lambda sf: analyze(sf.root).marker_hits.get(sf.rel, ()),
    )(_check_for("lock-order-inversion"))
    register(
        "callback-under-lock", "callback-ok",
        "callback/listener invoked while holding a lock (unknown code "
        "under a held mutex — the PR 9 ledger-bug shape)",
        lockgraph_scope,
    )(_check_for("callback-under-lock"))

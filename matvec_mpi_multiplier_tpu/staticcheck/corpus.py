"""Corpus discovery + per-file analysis context.

The scanned tree matches what the old grep lint covered: the package,
tests/, scripts/, and the two top-level entry files. Each file is parsed
once into a :class:`SourceFile` carrying the AST, the real comment map
(via ``tokenize`` — so marker exemptions live in comments only, never in
strings), and an import-alias table that resolves attribute chains to
fully-qualified dotted names (``from jax import lax; lax.psum`` →
``jax.lax.psum`` — the alias blindness that made the regex rules
evadable).
"""

from __future__ import annotations

import ast
import io
import tokenize
from pathlib import Path

SCAN_ROOTS = ("matvec_mpi_multiplier_tpu", "tests", "scripts")
SCAN_FILES = ("bench.py", "__graft_entry__.py")


def repo_root() -> Path:
    """The checkout root: two levels above this package."""
    return Path(__file__).resolve().parents[2]


def iter_corpus(root: Path | None = None) -> list[Path]:
    """Every Python source the rules scan, sorted (missing roots skipped —
    an installed package may not ship tests/)."""
    root = Path(root) if root is not None else repo_root()
    paths: list[Path] = []
    for sub in SCAN_ROOTS:
        base = root / sub
        if base.is_dir():
            paths.extend(sorted(base.rglob("*.py")))
    for name in SCAN_FILES:
        p = root / name
        if p.is_file():
            paths.append(p)
    return paths


class SourceFile:
    """One parsed corpus file: AST + comments + import-alias resolution."""

    def __init__(self, path: Path, root: Path):
        self.path = Path(path)
        self.root = Path(root)
        self.rel = self.path.relative_to(self.root).as_posix()
        self.text = self.path.read_text()
        # May raise SyntaxError — run_rules turns that into a finding.
        self.tree = ast.parse(self.text, filename=str(self.path))
        self._comments: dict[int, str] | None = None
        self._aliases: dict[str, str] | None = None
        self._by_type: dict[type, list[ast.AST]] | None = None

    def nodes(self, *types: type) -> list[ast.AST]:
        """All nodes of the given AST types, from ONE cached full walk —
        the shared index flat rules iterate instead of each re-walking
        the tree (≈15 rules × every file adds up). Grouped by type, so
        relative source order holds within a type but not across types;
        every consumer filters by isinstance and sorts findings later."""
        if self._by_type is None:
            by: dict[type, list[ast.AST]] = {}
            for node in ast.walk(self.tree):
                by.setdefault(type(node), []).append(node)
            self._by_type = by
        if len(types) == 1:
            return self._by_type.get(types[0], [])
        out: list[ast.AST] = []
        for t in types:
            out.extend(self._by_type.get(t, ()))
        return out

    @property
    def comments(self) -> dict[int, str]:
        """{lineno: comment text without the leading '#'} — real comments
        only, so a marker inside a string literal exempts nothing."""
        if self._comments is None:
            found: dict[int, str] = {}
            try:
                for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline
                ):
                    if tok.type == tokenize.COMMENT:
                        found[tok.start[0]] = tok.string.lstrip("#").strip()
            except tokenize.TokenizeError:
                pass  # already surfaced as a parse finding
            self._comments = found
        return self._comments

    @property
    def aliases(self) -> dict[str, str]:
        """Local name → fully-qualified dotted module/object path, from
        every import statement in the file (module- and function-level)."""
        if self._aliases is None:
            table: dict[str, str] = {}
            for node in self.nodes(ast.Import, ast.ImportFrom):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        if a.asname:
                            table[a.asname] = a.name
                        else:
                            # `import jax.numpy` binds the top name "jax".
                            top = a.name.split(".", 1)[0]
                            table[top] = top
                elif isinstance(node, ast.ImportFrom) and node.module:
                    if node.level:
                        continue  # relative: never a jax/numpy/json target
                    for a in node.names:
                        table[a.asname or a.name] = f"{node.module}.{a.name}"
            self._aliases = table
        return self._aliases

    def qualname(self, node: ast.AST) -> str | None:
        """Resolve a Name/Attribute chain to its imported dotted path
        (``jnp.asarray`` → ``jax.numpy.asarray``); bare un-imported names
        resolve to themselves (builtins like ``open``)."""
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.qualname(node.value)
            return f"{base}.{node.attr}" if base else None
        return None

    def span_comments(self, node: ast.AST) -> str:
        """All comment text on the physical lines a node spans — where a
        ``# <marker>: <reason>`` exemption may sit."""
        first = getattr(node, "lineno", 0)
        last = getattr(node, "end_lineno", first) or first
        return " ".join(
            self.comments[ln] for ln in range(first, last + 1)
            if ln in self.comments
        )


# One parse per file per run, shared by every analysis layer: the rule
# loop, the lock-graph auditor and the value-flow engine all consume the
# same corpus, and each used to re-parse it. Keyed by absolute path;
# validated by CONTENT, not mtime, so an edit between calls (the
# fixture/mutation tests do this) always invalidates.
_SF_CACHE: dict[str, SourceFile] = {}


def source_file(path: Path, root: Path) -> SourceFile:
    """The shared parsed view of ``path`` (see ``_SF_CACHE``). Raises
    ``SyntaxError``/``UnicodeDecodeError`` like the constructor; failed
    parses are never cached."""
    key = str(Path(path).resolve())
    text = Path(path).read_text()
    hit = _SF_CACHE.get(key)
    if hit is not None and hit.text == text and hit.root == Path(root):
        return hit
    sf = SourceFile(path, root)
    _SF_CACHE[key] = sf
    return sf

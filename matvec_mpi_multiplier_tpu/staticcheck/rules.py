"""The AST rule engine: registry, exemption markers, and the rule catalogue.

Each rule is a small checker over one parsed :class:`~.corpus.SourceFile`,
scoped to the paths where its invariant holds, with an optional exemption
marker. A finding on a statement is suppressed when any comment on the
statement's physical lines carries ``# <marker>: <reason>`` — and the
marker registry enforces that every marker occurrence in a rule's scope is
a real comment with a non-empty reason (the justification-not-escape-hatch
contract ``tests/test_lint.py`` parameterizes over :data:`MARKERS`).

Rule catalogue (docs/STATIC_ANALYSIS.md has the long form):

========================  ===========  ====================================
rule                      marker       invariant
========================  ===========  ====================================
shard-map-direct          —            shard_map refs only via utils/compat
engine-host-sync          sync-ok      no host syncs on engine dispatch
overlap-unchunked-        overlap-ok   no full-width all_gather/psum in
collective                             staged-overlap schedule bodies
hot-path-blocking-io      obs-ok       no file I/O on the dispatch hot path
fp64-implicit-promotion   fp64-ok      no implicit float64 in traced code
import-time-jnp           import-ok    no jnp work at module import time
mutable-default-arg       default-ok   no mutable default arguments
scheduler-lock-across-    lock-ok      no engine dispatch/drain entered
dispatch                               while holding a scheduler lock
silent-except             swallow-ok   broad except blocks must re-raise,
                                       record the failure, or justify
quant-fp64-scale          quant-ok     no float64 in quantization scale
                                       math (quantized-storage helpers)
device-transfer-under-    registry-ok  no device transfer, dispatch, or
registry-lock                          sync while holding a registry/
                                       residency mutex in engine/
measurement-in-           admit-ok     the admission hot path consults
admission-path                         predictions but never measures —
                                       no timing-harness calls, no
                                       perf_counter, no sync, no sleep
                                       in engine/global_scheduler.py
lock-mixed-guard          unguarded-ok attributes written under a lock
                                       are never accessed bare
                                       (lockgraph.py — whole-program)
lock-order-inversion      lock-order-  the cross-class lock-acquisition
                          ok           order graph stays acyclic
callback-under-lock       callback-ok  no callback/listener invocation
                                       while holding a lock (the PR 9
                                       ledger-bug shape)
metric-label-cardinality  cardinality- no labeled/dynamic metric names
                          ok           built per loop iteration (every
                                       distinct name is a live series
                                       forever)
========================  ===========  ====================================

The first four are the old grep rules from ``scripts/tier1.sh`` /
``tests/test_lint.py``, now alias-aware and string/docstring-proof; the
last four are inexpressible as greps. The engine host-sync and hot-path
I/O rules scope over ``engine/`` as a prefix, so the batching scheduler
(``engine/scheduler.py``) is covered by construction; the lock rule is
its own flush-loop discipline (a flush must swap the batch out under the
lock and dispatch only after releasing it — an engine dispatch can block
in the backpressure drain, and a blocked flush must not freeze
admission).
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Callable, Iterable, Iterator

from .corpus import SourceFile, iter_corpus, repo_root, source_file
from .dataflow import new_generation as dataflow_new_generation
from .dataflow import register_dataflow_rules
from .findings import Finding, dedup
from .lockgraph import new_generation as lockgraph_new_generation
from .lockgraph import register_lockgraph_rules

# ------------------------------------------------------------ framework

_PKG = "matvec_mpi_multiplier_tpu"


@dataclasses.dataclass(frozen=True)
class Rule:
    """One registered invariant: where it applies, how it checks, how a
    deliberate exception is marked."""

    name: str                       # slug used in findings and --rule
    marker: str | None              # "<marker>: <reason>" comment exempts
    description: str                # one line, shown by --list
    scope: Callable[[str], bool]    # repo-relative posix path predicate
    check: Callable[[SourceFile], Iterator[tuple[ast.AST, str]]]
    # Line spans where the rule consumed its marker INTERNALLY (before
    # any finding could surface — lock-order-inversion drops exempted
    # edges ahead of cycle detection, which also suppresses the sibling
    # edges of the cycle). The stale-marker audit unions these into its
    # live coverage; None for rules whose raw findings reach run_rules.
    covered: Callable[[SourceFile], Iterable[int]] | None = None


RULES: dict[str, Rule] = {}


def _register(name, marker, description, scope, covered=None):
    def deco(fn):
        RULES[name] = Rule(name, marker, description, scope, fn, covered)
        return fn

    return deco


def get_rule(name: str) -> Rule:
    try:
        return RULES[name]
    except KeyError:
        raise KeyError(
            f"unknown rule {name!r}; available: {sorted(RULES)}"
        ) from None


def _markers() -> dict[str, str]:
    return {r.marker: r.name for r in RULES.values() if r.marker}


def _exempt(sf: SourceFile, node: ast.AST, marker: str) -> bool:
    return f"{marker}:" in sf.span_comments(node)


def _marker_reason_findings(
    sf: SourceFile, rules: Iterable[Rule]
) -> Iterator[Finding]:
    """Every marker occurrence in an in-scope file must carry a reason.
    (Marker text inside strings never exempts — comments only — so only
    comments are validated.)"""
    for rule in rules:
        if not rule.marker:
            continue
        token = f"{rule.marker}:"
        if token not in sf.text:
            continue  # skip the tokenize pass for marker-free files
        for lineno, comment in sf.comments.items():
            if token in comment and not comment.split(token, 1)[1].strip():
                yield Finding(
                    sf.rel, lineno, "marker-missing-reason",
                    f"'# {token}' without a reason (the {rule.name} "
                    f"exemption marker documents WHY, or it is an escape "
                    f"hatch)",
                )


STALE_MARKER = "stale-ok"


def _stale_marker_findings(
    sf: SourceFile, rules: Iterable[Rule], covered: dict[str, set[int]]
) -> Iterator[Finding]:
    """Exemption markers must sit where their rule actually FIRES —
    exemptions rot as code changes, and a rotted one silently blesses
    the next real finding at that site. ``covered`` maps each in-scope
    rule's marker to the line spans its raw (pre-exemption) findings
    touched this run; a marker comment outside every span is stale.
    ``# stale-ok: reason`` keeps a deliberately anticipatory marker."""
    stale_token = f"{STALE_MARKER}:"
    for rule in rules:
        if not rule.marker:
            continue
        token = f"{rule.marker}:"
        if token not in sf.text:
            continue  # skip the tokenize pass for marker-free files
        live = covered.get(rule.marker, set())
        for lineno, comment in sf.comments.items():
            if token not in comment or lineno in live:
                continue
            if stale_token in comment:
                if not comment.split(stale_token, 1)[1].strip():
                    yield Finding(
                        sf.rel, lineno, "marker-missing-reason",
                        f"'# {stale_token}' without a reason (the "
                        f"stale-marker escape hatch documents WHY the "
                        f"marker is kept ahead of its rule)",
                    )
                continue
            yield Finding(
                sf.rel, lineno, "stale-marker",
                f"'# {token}' comment but {rule.name} no longer fires "
                f"at this site — the exemption has rotted; drop the "
                f"marker, or keep it deliberately with "
                f"'# {stale_token} reason'",
                marker=STALE_MARKER,
            )


def run_rules(
    root: Path | None = None,
    rules: Iterable[str] | None = None,
) -> list[Finding]:
    """Run the (selected) rule catalogue over the corpus under ``root``
    (the repo by default). Returns sorted, deduplicated findings — empty
    means the tree is clean."""
    root = Path(root) if root is not None else repo_root()
    selected = (
        list(RULES.values()) if rules is None
        else [get_rule(n) for n in rules]
    )
    # One corpus validation per run for the whole-program lock-graph and
    # value-flow rules (their per-file checks share the run's analysis).
    lockgraph_new_generation()
    dataflow_new_generation()
    findings: list[Finding] = []
    for path in iter_corpus(root):
        try:
            sf = source_file(path, root)
        except (SyntaxError, UnicodeDecodeError) as e:
            rel = path.relative_to(root).as_posix()
            findings.append(
                Finding(rel, getattr(e, "lineno", 0) or 0, "parse-error",
                        f"unparseable source: {e}")
            )
            continue
        in_scope = [r for r in selected if r.scope(sf.rel)]
        # marker -> line numbers its rule's RAW findings span, feeding
        # the stale-marker audit below.
        covered: dict[str, set[int]] = {}
        for rule in in_scope:
            if rule.marker and rule.covered is not None:
                covered.setdefault(rule.marker, set()).update(
                    rule.covered(sf)
                )
            for node, message in rule.check(sf):
                if rule.marker:
                    lineno = getattr(node, "lineno", 0)
                    end = getattr(node, "end_lineno", None) or lineno
                    covered.setdefault(rule.marker, set()).update(
                        range(lineno, end + 1)
                    )
                    if _exempt(sf, node, rule.marker):
                        continue
                findings.append(
                    Finding(sf.rel, getattr(node, "lineno", 0), rule.name,
                            message, marker=rule.marker)
                )
        findings.extend(_marker_reason_findings(sf, in_scope))
        findings.extend(_stale_marker_findings(sf, in_scope, covered))
    return dedup(findings)


def check_marker_reasons(
    marker: str, root: Path | None = None
) -> list[Finding]:
    """Reason-required check for ONE marker over its rule's scope — the
    per-marker face ``tests/test_lint.py`` parameterizes over."""
    rule = get_rule(MARKERS[marker])
    root = Path(root) if root is not None else repo_root()
    findings: list[Finding] = []
    for path in iter_corpus(root):
        rel = path.relative_to(root).as_posix()
        if not rule.scope(rel):
            continue
        try:
            sf = source_file(path, root)
        except (SyntaxError, UnicodeDecodeError):
            continue  # run_rules owns the parse-error finding
        findings.extend(_marker_reason_findings(sf, [rule]))
    return dedup(findings)


# ----------------------------------------------------------- AST helpers


def _calls(tree: ast.AST) -> Iterator[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def _has_float_literal(nodes: Iterable[ast.AST]) -> bool:
    for root in nodes:
        for node in ast.walk(root):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, float
            ):
                return True
    return False


def _import_time_nodes(tree: ast.Module) -> Iterator[ast.AST]:
    """Expressions executed at import: module/class bodies plus function
    decorators and default-argument expressions — but never the deferred
    function/lambda bodies themselves."""
    stack: list[ast.AST] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(node.decorator_list)
            stack.extend(_defaults(node.args))
        elif isinstance(node, ast.Lambda):
            stack.extend(_defaults(node.args))
        elif isinstance(node, ast.ClassDef):
            stack.extend(node.decorator_list)
            stack.extend(node.body)
        else:
            yield node
            stack.extend(ast.iter_child_nodes(node))


def _defaults(args: ast.arguments) -> list[ast.AST]:
    return list(args.defaults) + [d for d in args.kw_defaults if d]


# ------------------------------------------------------ scope predicates


def _all_but_compat(rel: str) -> bool:
    return rel != f"{_PKG}/utils/compat.py"


def _engine(rel: str) -> bool:
    return rel.startswith(f"{_PKG}/engine/")


def _overlap_bodies(rel: str) -> bool:
    return rel in (f"{_PKG}/parallel/ring.py", f"{_PKG}/ops/pallas_collective.py")


def _hot_path(rel: str) -> bool:
    # engine/ plus the obs in-memory layer; the sink thread and the obs CLI
    # are the two files allowed to touch the filesystem by design.
    if _engine(rel):
        return True
    return rel.startswith(f"{_PKG}/obs/") and rel not in (
        f"{_PKG}/obs/sink.py", f"{_PKG}/obs/__main__.py",
    )


def _package(rel: str) -> bool:
    return rel.startswith(f"{_PKG}/")


# -------------------------------------------------------------- catalogue


def _is_shard_map_path(q: str) -> bool:
    return q == "jax.shard_map" or q.startswith("jax.experimental.shard_map")


@_register(
    "shard-map-direct", None,
    "direct jax.shard_map / jax.experimental.shard_map reference outside "
    "utils/compat.py (the cross-version shim chokepoint)",
    _all_but_compat,
)
def _check_shard_map(sf: SourceFile):
    msg = (
        "direct shard_map reference; route it through "
        f"{_PKG}.utils.compat so a JAX API bump stays a one-file change"
    )
    # Any hit — import, attribute chain, or aliased bare name — requires
    # the literal text somewhere in the file (the alias's own import
    # line at minimum), so skip the per-name resolution scan without it.
    if "shard_map" not in sf.text:
        return
    for node in sf.nodes(
        ast.ImportFrom, ast.Import, ast.Attribute, ast.Name
    ):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod in ("jax", "jax.experimental") and any(
                a.name == "shard_map" for a in node.names
            ):
                yield node, msg
            elif mod.startswith("jax.experimental.shard_map"):
                yield node, msg
        elif isinstance(node, ast.Import):
            if any(
                a.name.startswith("jax.experimental.shard_map")
                for a in node.names
            ):
                yield node, msg
        elif isinstance(node, (ast.Attribute, ast.Name)):
            # Name catches the bare-alias call site (`from jax import
            # shard_map as sm; sm(...)`); the alias table resolves it. A
            # local name that merely spells "shard_map" resolves to itself
            # and stays clean.
            if _is_shard_map_path(sf.qualname(node) or ""):
                yield node, msg


_SYNC_ATTRS = ("block_until_ready", "device_get")
_SYNC_CALLS = ("numpy.asarray", "numpy.array", "jax.numpy.asarray")


@_register(
    "engine-host-sync", "sync-ok",
    "host synchronization on the engine dispatch path (breaks the async "
    "submit contract)",
    _engine,
)
def _check_host_sync(sf: SourceFile):
    for call in sf.nodes(ast.Call):
        fn = call.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if attr in _SYNC_ATTRS:
            yield call, (
                f"{attr}() host-syncs; a dispatch-path round-trip turns "
                "async submit into per-request blocking (move it to "
                "bench/serve.py or mark the deliberate materialization "
                "point)"
            )
        elif (sf.qualname(fn) or "") in _SYNC_CALLS:
            yield call, (
                f"{ast.unparse(fn)}() materializes device values on the "
                "dispatch path (host staging belongs behind a "
                "'# sync-ok: <reason>' marker)"
            )


_FULL_WIDTH = ("jax.lax.all_gather", "jax.lax.psum")


@_register(
    "overlap-unchunked-collective", "overlap-ok",
    "full-width collective inside a staged-overlap schedule body "
    "(re-serializes the transfer the S-stage pipeline exists to hide)",
    _overlap_bodies,
)
def _check_overlap(sf: SourceFile):
    for call in sf.nodes(ast.Call):
        q = sf.qualname(call.func)
        if q in _FULL_WIDTH:
            yield call, (
                f"un-chunked {q}() in an overlap schedule body: stage the "
                "collective (1/S of the bytes per issue) or mark a "
                "deliberate chunked use"
            )


# "open" in the attribute set covers Path.open()-style method calls, which
# the old grep's `\bopen\(` matched too (word boundary after the dot).
_IO_ATTRS = ("open", "write", "write_text", "write_bytes")
_IO_CALLS = ("open", "io.open", "json.dump")


@_register(
    "hot-path-blocking-io", "obs-ok",
    "blocking file I/O on the engine dispatch hot path (file writes go "
    "through the obs sink thread)",
    _hot_path,
)
def _check_blocking_io(sf: SourceFile):
    for call in sf.nodes(ast.Call):
        fn = call.func
        q = sf.qualname(fn) or ""
        if q in _IO_CALLS:
            yield call, (
                f"{q}() blocks on the filesystem; route writes through "
                "obs/sink.py (the sink thread) or mark a non-hot-path "
                "write"
            )
        elif isinstance(fn, ast.Attribute) and fn.attr in _IO_ATTRS:
            yield call, (
                f".{fn.attr}() blocks on the filesystem; route writes "
                "through obs/sink.py (the sink thread) or mark a "
                "non-hot-path write"
            )


# jnp constructors: {qualified name: positional index of dtype}. Under the
# test tier's x64 mode their default dtype is float64, so a missing dtype
# is an implicit promotion: always for the default-float family below,
# and for array/asarray whenever a Python float literal flows in.
_JNP_CTOR_DTYPE_POS = {
    "jax.numpy.array": 1,
    "jax.numpy.asarray": 1,
    "jax.numpy.zeros": 1,
    "jax.numpy.ones": 1,
    "jax.numpy.empty": 1,
    "jax.numpy.full": 2,
    "jax.numpy.eye": 3,
    "jax.numpy.arange": 3,
    "jax.numpy.linspace": 5,
}
_JNP_DEFAULT_FLOAT = (
    "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.empty", "jax.numpy.eye",
)
_F64_CTORS = ("numpy.float64", "jax.numpy.float64")


@_register(
    "fp64-implicit-promotion", "fp64-ok",
    "implicit float64 promotion (bare float literals / np.float64 scalars "
    "flowing into traced bodies under x64)",
    _package,
)
def _check_fp64(sf: SourceFile):
    for call in sf.nodes(ast.Call):
        q = sf.qualname(call.func) or ""
        if q in _F64_CTORS:
            yield call, (
                f"{q}() builds a float64 scalar; in a bf16/f32 pipeline "
                "this silently promotes every downstream op (use the "
                "operand's dtype, or mark a deliberate fp64 tier)"
            )
            continue
        for kw in call.keywords:
            if kw.arg == "dtype" and sf.qualname(kw.value) == "float":
                yield call, (
                    "dtype=float is float64 under x64; name the width "
                    "explicitly"
                )
        pos = _JNP_CTOR_DTYPE_POS.get(q)
        if pos is None:
            continue
        has_dtype = len(call.args) > pos or any(
            kw.arg == "dtype" for kw in call.keywords
        )
        if has_dtype:
            continue
        if q in _JNP_DEFAULT_FLOAT:
            yield call, (
                f"{q}() without a dtype defaults to float64 under x64 "
                "(the test tier); pass the intended dtype"
            )
        elif _has_float_literal(call.args):
            yield call, (
                f"{q}() over Python float literals without a dtype makes "
                "a float64 constant under x64; pass the intended dtype"
            )


@_register(
    "import-time-jnp", "import-ok",
    "jnp work executed at module import time (initializes the backend / "
    "traces before any caller chose a platform)",
    _package,
)
def _check_import_time_jnp(sf: SourceFile):
    for top in _import_time_nodes(sf.tree):
        if not isinstance(top, ast.Call):
            continue
        q = sf.qualname(top.func) or ""
        if q == "jax.numpy" or q.startswith("jax.numpy."):
            yield top, (
                f"{q}() runs at import time — backend init and constant "
                "materialization before any caller chose a platform; "
                "compute it lazily or with numpy"
            )


def _scheduler(rel: str) -> bool:
    return rel == f"{_PKG}/engine/scheduler.py"


# Calls that enter the engine's dispatch path (or block draining it).
# Holding the scheduler's admission lock across any of these turns a
# backpressure stall into a total admission freeze.
_DISPATCH_CALLS = ("submit", "warmup", "block_until_ready")
# Context-manager name fragments that mark a scheduler lock (Lock,
# RLock, Condition — the flush loop's admission guard).
_LOCKISH = ("lock", "cond", "mutex")


def _lockish_with(node: ast.With) -> bool:
    for item in node.items:
        expr = item.context_expr
        # `with self._cond:` / `with lock:` / `with self._lock.acquire()`…
        for sub in ast.walk(expr):
            name = (
                sub.attr if isinstance(sub, ast.Attribute)
                else sub.id if isinstance(sub, ast.Name) else None
            )
            if name is not None and any(
                frag in name.lower() for frag in _LOCKISH
            ):
                return True
    return False


def _walk_excluding_deferred(nodes: Iterable[ast.AST]) -> Iterator[ast.AST]:
    """Walk statements executed *inside* a with-block, skipping function
    and lambda bodies (deferred — they run under whatever lock state
    exists at call time, not this one)."""
    stack: list[ast.AST] = list(nodes)
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@_register(
    "scheduler-lock-across-dispatch", "lock-ok",
    "engine dispatch (or blocking drain) entered while holding a "
    "scheduler lock: swap the batch out under the lock, dispatch after "
    "releasing it",
    _scheduler,
)
def _check_lock_across_dispatch(sf: SourceFile):
    for node in sf.nodes(ast.With):
        if not _lockish_with(node):
            continue
        for inner in _walk_excluding_deferred(node.body):
            if not isinstance(inner, ast.Call):
                continue
            fn = inner.func
            attr = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if attr in _DISPATCH_CALLS:
                yield inner, (
                    f"{attr}() under a held scheduler lock: an engine "
                    "dispatch can block in the backpressure drain, and a "
                    "blocked flush must not freeze admission — take the "
                    "batch out under the lock and dispatch after "
                    "releasing it"
                )


# A broad handler is "silent" unless its body does one of these: re-raise
# (any Raise node), call something that records the failure — a metrics
# counter (.inc/.observe), a future/breaker outcome (_fail/fail/
# set_exception/record_failure), a collection it parks the error in
# (.append/.put) — or bind the exception to an error-ish name
# (`self._error = e`, `last_exc = e`). The heuristic is deliberately
# generous about HOW a failure is recorded and strict about the
# alternative: a handler that does none of these has made an exception
# disappear, which in a serving system turns faults into wrong answers.
_RECORDING_CALLS = frozenset({
    "inc", "observe", "append", "put", "fail", "_fail", "set_exception",
    "record", "record_failure", "warning", "error", "exception",
})
_ERRORISH_NAME_FRAGMENTS = ("error", "exc", "failure", "fault")
_BROAD_EXCEPTIONS = ("Exception", "BaseException")


def _handler_is_broad(sf: SourceFile, handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True  # bare except:
    types = (
        handler.type.elts if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    for t in types:
        if (sf.qualname(t) or "") in _BROAD_EXCEPTIONS:
            return True
    return False


def _name_of(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _handler_records(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = _name_of(node.func)
            if name is not None and name in _RECORDING_CALLS:
                return True
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for target in targets:
                name = _name_of(target)
                if name is not None and any(
                    frag in name.lower()
                    for frag in _ERRORISH_NAME_FRAGMENTS
                ):
                    return True
    return False


@_register(
    "silent-except", "swallow-ok",
    "broad `except Exception`/bare except that neither re-raises, records "
    "the failure (counter/future/error variable), nor carries a "
    "justification marker",
    _package,
)
def _check_silent_except(sf: SourceFile):
    for node in sf.nodes(ast.ExceptHandler):
        if not _handler_is_broad(sf, node):
            continue
        if _handler_records(node):
            continue
        yield node, (
            "broad except block swallows the failure: re-raise, record it "
            "(obs counter, future._fail, an error variable), or mark the "
            "deliberate swallow with '# swallow-ok: <reason>'"
        )


# The fp64-implicit-promotion family, extended over the quantized-storage
# helpers (ops/quantize.py, ops/pallas_quant.py): scale math there runs on
# HOST numpy, whose default float IS float64 — a dtype-less constructor or
# an astype/dtype to f64 silently (a) doubles the scale-plane bytes the
# format's ratio pins assume are fp32 and (b) lies about the error budget
# the scales define. The package-wide fp64 rule only sees jnp
# constructors; this one covers the numpy side, in the quant scope only.
# Marker `quant-ok:` documents the deliberate exceptions (the int8c
# residual is COMPUTED in f64 for exactness, then stored f32).


def _quant_scope(rel: str) -> bool:
    return rel in (
        f"{_PKG}/ops/quantize.py", f"{_PKG}/ops/pallas_quant.py",
    )


_NP_F64_NAMES = ("numpy.float64", "jax.numpy.float64", "float")
# Host constructors whose dtype defaults to float64 for float input.
_NP_DTYPELESS_CTORS = (
    "numpy.asarray", "numpy.array", "numpy.zeros", "numpy.ones",
    "numpy.empty", "numpy.full",
)


def _is_f64_dtype_expr(sf: SourceFile, node: ast.AST) -> bool:
    if (sf.qualname(node) or "") in _NP_F64_NAMES:
        return True
    return isinstance(node, ast.Constant) and node.value == "float64"


@_register(
    "quant-fp64-scale", "quant-ok",
    "float64 in quantization scale math (astype/dtype to f64, or a "
    "dtype-less host constructor defaulting to it) — scales are fp32 by "
    "doctrine",
    _quant_scope,
)
def _check_quant_fp64(sf: SourceFile):
    for call in sf.nodes(ast.Call):
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr == "astype" and any(
            _is_f64_dtype_expr(sf, arg) for arg in call.args
        ):
            yield call, (
                ".astype(float64) in the quant scope: scales and staged "
                "values are fp32 by doctrine (mark the deliberate "
                "exception with '# quant-ok: <reason>')"
            )
            continue
        for kw in call.keywords:
            if kw.arg == "dtype" and _is_f64_dtype_expr(sf, kw.value):
                yield call, (
                    "dtype=float64 in the quant scope: scales are fp32 by "
                    "doctrine"
                )
        q = sf.qualname(fn) or ""
        if q in _NP_DTYPELESS_CTORS:
            has_dtype = any(kw.arg == "dtype" for kw in call.keywords)
            if not has_dtype:
                yield call, (
                    f"{q}() without a dtype in the quant scope defaults "
                    "to float64 for float input; name the width (or mark "
                    "a deliberate dtype passthrough)"
                )


# The multi-tenant registry's lock discipline (engine/registry.py;
# docs/MULTITENANT.md): the registry mutex serializes ADMISSION
# BOOKKEEPING for every tenant, so holding it across a device transfer
# (`device_put` — the swap-in), a dispatch (`submit`/`warmup` can compile
# or block in the backpressure drain), or a host sync
# (`block_until_ready`/`device_get`) turns one tenant's swap into a
# fleet-wide admission freeze. Victim RELEASE under the lock is legal by
# design — dropping references transfers nothing — so `release_residency`
# is deliberately absent from the call set. Scoped to all of engine/ (the
# acceptance bar: no transfer under a registry/residency mutex anywhere
# in the serving subsystem); rule #8 remains the scheduler-specific
# flush-loop discipline. Marker `registry-ok:` documents a sanctioned
# exception.
_REGISTRY_LOCK_CALLS = (
    "device_put", "device_get", "block_until_ready", "ensure_resident",
    "submit", "warmup",
)


@_register(
    "device-transfer-under-registry-lock", "registry-ok",
    "device transfer (device_put), dispatch (submit/warmup/"
    "ensure_resident) or host sync entered while holding a registry/"
    "residency mutex: plan under the lock, place and dispatch after "
    "releasing it",
    _engine,
)
def _check_registry_lock(sf: SourceFile):
    for node in sf.nodes(ast.With):
        if not _lockish_with(node):
            continue
        for inner in _walk_excluding_deferred(node.body):
            if not isinstance(inner, ast.Call):
                continue
            fn = inner.func
            attr = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if attr in _REGISTRY_LOCK_CALLS:
                yield inner, (
                    f"{attr}() under a held registry/residency mutex: a "
                    "transfer or dispatch here freezes every tenant's "
                    "admission behind one tenant's swap — plan victims "
                    "under the lock, device_put/dispatch after releasing "
                    "it (docs/MULTITENANT.md)"
                )


# The global scheduler's admission doctrine (engine/global_scheduler.py;
# docs/SCHEDULING.md): every submit-time decision CONSULTS the calibrated
# cost model — it never MEASURES. A measurement in the admission path
# puts a benchmark in front of every request: a perf_counter pair around
# a dispatch needs the dispatch to finish (a host sync on the submit
# path), a timing-harness call (`time_matvec`, `benchmark_strategy`,
# `calibrate`) runs reps, and a sleep stalls admission for every later
# arrival. Deadline arithmetic uses the injectable monotonic clock, which
# is a read, not a measurement — `time.monotonic` as a default-argument
# REFERENCE stays legal; calling any of the names below in this scope
# does not. Marker `admit-ok:` documents a sanctioned exception.


def _admission_scope(rel: str) -> bool:
    return rel == f"{_PKG}/engine/global_scheduler.py"


_MEASUREMENT_CALLS = (
    "perf_counter", "process_time", "timeit",
    "time_matvec", "benchmark_strategy", "benchmark_gemm", "calibrate",
    "_measure_fn", "block_until_ready", "sleep",
)


@_register(
    "measurement-in-admission-path", "admit-ok",
    "timing/measurement machinery in the global scheduler's admission "
    "path (admission consults predictions; it never times a dispatch)",
    _admission_scope,
)
def _check_admission_measurement(sf: SourceFile):
    for call in sf.nodes(ast.Call):
        fn = call.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if attr in _MEASUREMENT_CALLS:
            yield call, (
                f"{attr}() in the admission path: admission consults the "
                "calibrated cost model and never measures — timing a "
                "dispatch here puts a benchmark (and its host sync) in "
                "front of every request (move it to the tuner/bench, or "
                "mark a deliberate exception with '# admit-ok: <reason>')"
            )


_MUTABLE_FACTORIES = (
    "list", "dict", "set", "collections.defaultdict", "collections.deque",
)


@_register(
    "mutable-default-arg", "default-ok",
    "mutable default argument (shared across calls — and across traces "
    "for functions that end up jitted)",
    _package,
)
def _check_mutable_default(sf: SourceFile):
    for node in sf.nodes(
        ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda
    ):
        for default in _defaults(node.args):
            if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and sf.qualname(default.func) in _MUTABLE_FACTORIES
            ):
                yield default, (
                    "mutable default argument is evaluated once and shared "
                    "across every call (and every trace); default to None "
                    "and construct inside the body"
                )


# Rule #16: metric-series cardinality. The registry stores labeled
# metrics under their full labeled name (obs/registry.py: one string per
# series), so every dynamically-built name is a new series for the
# process's lifetime. Building one per loop iteration — a comprehension
# over requests, a retry loop keying on attempt — leaks series without
# bound and OOMs the snapshot long before anything else complains.
# Dynamic names are legal where the label SOURCE is bounded (tenant ids
# capped by the registered fleet, declared SLO target names); those
# sites say so with '# cardinality-ok: <reason>'. — stale-ok: syntax documentation, not an exemption


_METRIC_CTORS = ("counter", "gauge", "histogram", "rate_estimator",
                 "ewma_gauge")

_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
               ast.SetComp, ast.DictComp, ast.GeneratorExp)


def _is_constructed_name(node: ast.AST) -> bool:
    """A metric-name expression assembled at the call site: f-string,
    string concat/%-format, ``.format()``, or an ``obs.label(...)``
    call. A bare constant or a module-level NAME constant is not."""
    if isinstance(node, ast.JoinedStr):
        return True
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.Add, ast.Mod)
    ):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "format":
            return True
        attr = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if attr == "label":
            return True
    return False


@_register(
    "metric-label-cardinality", "cardinality-ok",
    "labeled/dynamic metric name constructed inside a loop or "
    "comprehension: each distinct name is a live series forever, so a "
    "per-iteration name with an unbounded label source leaks series "
    "without bound",
    _package,
)
def _check_metric_cardinality(sf: SourceFile):
    loops = sf.nodes(*_LOOP_NODES)
    seen: set[int] = set()
    for loop in loops:
        for call in _calls(loop):
            if id(call) in seen:
                continue
            fn = call.func
            attr = fn.attr if isinstance(fn, ast.Attribute) else None
            if attr not in _METRIC_CTORS or not call.args:
                continue
            if not _is_constructed_name(call.args[0]):
                continue
            seen.add(id(call))
            yield call, (
                f"{attr}() with a name built per loop iteration: every "
                "distinct name is a new live series (the registry never "
                "drops one), so an unbounded label source here leaks "
                "memory and floods the snapshot — hoist the series, "
                "bound the source, or mark the bounded case with "
                "'# cardinality-ok: <reason>'"
            )


# Rules #13-#15: the whole-program lock-graph auditor (lockgraph.py)
# registers through the same decorator so markers, fixtures and the CLI
# inherit; registration precedes the MARKERS snapshot below.
register_lockgraph_rules(_register)
register_dataflow_rules(_register)

MARKERS: dict[str, str] = _markers()

# Canonical one-line scope descriptions keyed by scope-predicate name —
# the single vocabulary docs/STATIC_ANALYSIS.md's rule-index table must
# use (tests/test_staticcheck.py's doc-drift gate compares the table's
# scope column against scope_label()).
_SCOPE_LABELS: dict[str, str] = {
    "_all_but_compat": "package minus utils/compat.py",
    "_engine": "engine/",
    "_overlap_bodies": "parallel/ring.py, ops/pallas_collective.py",
    "_hot_path": "engine/ + obs/ (minus sink, CLI)",
    "_package": "package",
    "_scheduler": "engine/scheduler.py",
    "_quant_scope": "ops/quantize.py, ops/pallas_quant.py",
    "_admission_scope": "engine/global_scheduler.py",
    "lockgraph_scope": "engine/, obs/, resilience/, tuning/",
    "dataflow_scope": "package",
    "sync_scope": "engine/ + solvers/",
}


def scope_label(name: str) -> str:
    """The canonical scope string for one rule (doc-drift gate API)."""
    return _SCOPE_LABELS[get_rule(name).scope.__name__]

"""Layer 3: the static ExecKey-space compile-surface auditor.

Every subsystem since the serve bench stakes its p99 claims on the
zero-steady-recompile doctrine (``compiles_steady == 0``), but until now
the invariant was only ever checked *dynamically*, one committed demo at
a time. This layer makes the compile surface a static artifact — the
GSPMD treatment (PAPERS.md) of the partitioned compile surface as a
first-class enumerable object, applied to the engine's ExecKey space.

For each pinned serve configuration (:data:`KEYSPACE_CONFIGS`) the
enumerator walks the engine's actual construction rules symbolically —
bucket ladder × kernel/combine/stages × dtype_storage (including
``speculate``'s two-tier keys) × solver ops/buckets × degradation-ladder
tiers × reshard destinations — and emits the exact finite set of
compilable :class:`~..engine.executables.ExecKey` labels, classified by
WHEN each may compile:

- ``warmup``  — what ``MatvecEngine.warmup()`` compiles (modelled from
  the warmup enumeration: full ladder, or the buckets declared
  ``warm_widths`` route to) plus each declared solver op's preferred
  key (compiled in the serve warm phase by doctrine).
- ``steady``  — what healthy-path request routing can reach, computed by
  *evaluating the routing* (``bucket_for`` over every reachable chunk
  width) — a genuinely different derivation from the warmup model, so
  ``steady ⊆ warmup`` is a checkable invariant, not a tautology.
- ``fault_only`` — degradation-ladder safe tiers, reachable only after a
  breaker trips. Bucket-halving re-enters the ladder at ladder buckets,
  so it adds no keys beyond these.
- ``rollover`` — keys an online ``reshard()`` to a declared destination
  would compile in its one-time post-swap warmup (off the request path).

The table is golden-pinned (``data/staticcheck/golden_keyspace.json``,
blessed via ``--write-golden``): a code change that silently widens the
key space shows up as ``keyspace-golden`` drift, and a change that makes
a steady path reach an un-warmed key is a hard ``keyspace-steady-unwarmed``
error — the static proof of the compile budget
("warmup covers K of N; steady-reachable beyond warmup = 0").

The live half of the story is ``MatvecEngine.exec_keyspace()`` — built
from the engine's own key constructors — which the cross-check tests pin
this symbolic enumeration against, and the committed demos'
``compiles_steady`` counters (test_data_quality.py) tie the static claim
to dynamic evidence.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

from ..engine.buckets import bucket_for, bucket_ladder, split_widths
from ..engine.core import SAFE_KERNEL, SPECULATE
from ..engine.executables import ExecKey
from ..models.base import STORAGE_INCOMPATIBLE_COMBINES
from ..ops.pallas_solver import _FUSED_COMBINES, FUSED_SOLVER_OPS
from ..ops.quantize import NATIVE
from ..solvers.ops import (
    DEFAULT_RESTART,
    DEFAULT_STEPS,
    SOLVER_OPS,
    solver_bucket,
)
from .corpus import repo_root
from .findings import Finding

# Golden location + schema version — bump the schema when the table's
# SHAPE changes (new class, new budget field), re-bless when its CONTENT
# legitimately changes (a new config, a deliberate keyspace change).
GOLDEN_REL = "data/staticcheck/golden_keyspace.json"
KEYSPACE_SCHEMA = 1

_STRATEGIES = ("rowwise", "colwise", "blockwise")


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """One pinned serve configuration — the symbolic mirror of a
    ``MatvecEngine(...)`` construction. Only knobs that mint ExecKeys
    appear; dynamic knobs (rtol, maxiter, interval, window) do not
    exist here because they never mint keys — that absence IS part of
    the audited claim."""

    name: str
    strategy: str
    kernel: str = "xla"
    combine: str | None = None
    stages: int | None = None
    dtype: str = "float32"
    # "native" | "int8" | "int8c" | "fp8" | "speculate"
    dtype_storage: str = NATIVE
    promote: int | None = 8          # b_star; None = per-column only
    max_bucket: int = 32
    warm_widths: tuple[int, ...] | None = None
    solver_ops: tuple[str, ...] = ()
    solver_kernel: str = "xla"       # "xla" | "pallas_fused"
    restart: int = DEFAULT_RESTART
    steps: int = DEFAULT_STEPS
    reshard_to: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class KeySpace:
    """The enumerated compile surface of one :class:`ServeConfig`."""

    warmup: tuple[str, ...]
    steady: tuple[str, ...]
    fault_only: tuple[str, ...]
    rollover: tuple[str, ...]
    budget: dict


def _validate(cfg: ServeConfig) -> None:
    if cfg.strategy not in _STRATEGIES:
        raise ValueError(f"{cfg.name}: unknown strategy {cfg.strategy!r}")
    for op in cfg.solver_ops:
        if op not in SOLVER_OPS:
            raise ValueError(f"{cfg.name}: unknown solver op {op!r}")
    if cfg.solver_kernel == "pallas_fused":
        if cfg.strategy not in _FUSED_COMBINES:
            raise ValueError(
                f"{cfg.name}: pallas_fused has no {cfg.strategy} spelling"
            )
        bad = [op for op in cfg.solver_ops if op not in FUSED_SOLVER_OPS]
        if bad:
            raise ValueError(
                f"{cfg.name}: pallas_fused serves {FUSED_SOLVER_OPS}, "
                f"config declares {bad}"
            )
    for dst in cfg.reshard_to:
        if dst not in _STRATEGIES:
            raise ValueError(f"{cfg.name}: unknown reshard dst {dst!r}")
    if cfg.reshard_to and (
        cfg.combine is not None
        or cfg.stages is not None
        or cfg.solver_kernel != "xla"
    ):
        # Reshard re-validates combine/stages/fused-tier against the
        # destination; the symbolic model covers the conservative
        # combine=None path — declare richer reshard configs only once
        # the model grows the per-destination re-resolution.
        raise ValueError(
            f"{cfg.name}: reshard_to modelling requires combine=None, "
            f"stages=None, solver_kernel='xla'"
        )
    if cfg.promote is not None and cfg.promote < 1:
        raise ValueError(f"{cfg.name}: promote must be >= 1")


def _resolved_storage(cfg: ServeConfig) -> tuple[str, bool]:
    """Mirror ``_resolve_storage_locked``: ``"speculate"`` arms the
    two-tier path with NATIVE primary residency; everything else is the
    declared format."""
    if cfg.dtype_storage == SPECULATE:
        return NATIVE, True
    return cfg.dtype_storage, False


def _primary_combine(cfg: ServeConfig, storage: str) -> str | None:
    """Mirror construction: quantized residency drops A-tiling combines
    (STORAGE_INCOMPATIBLE_COMBINES) to the strategy default."""
    if storage != NATIVE and cfg.combine in STORAGE_INCOMPATIBLE_COMBINES:
        return None
    return cfg.combine


def _combine_label(cfg: ServeConfig, combine: str | None) -> str | None:
    """Mirror ``_combine_label``: staged overlap schedules embed their
    pinned S (``overlap@4``) in the cache identity."""
    if (
        cfg.stages is not None
        and combine is not None
        and combine.startswith("overlap")
    ):
        return f"{combine}@{cfg.stages}"
    return combine


def _spec_combine(combine: str | None) -> str | None:
    """Mirror ``_spec_combine``: the fused speculative program cannot
    run A-tiling schedules — those degrade to the static default."""
    return None if combine in STORAGE_INCOMPATIBLE_COMBINES else combine


def _warm_buckets(cfg: ServeConfig) -> set[int]:
    """The GEMM buckets ``warmup()`` compiles — the warmup enumeration:
    the whole ladder when no widths were declared (any split remainder
    can land on any bucket), else exactly the buckets declared widths
    route to (sub-``b*`` widths ride per-column and warm no bucket)."""
    if cfg.promote is None:
        return set()
    if cfg.warm_widths is None:
        return set(bucket_ladder(cfg.max_bucket))
    buckets: set[int] = set()
    for w in cfg.warm_widths:
        if w < cfg.promote:
            continue
        for chunk in split_widths(w, cfg.max_bucket):
            buckets.add(bucket_for(chunk, cfg.max_bucket))
    return buckets


def _steady_buckets(cfg: ServeConfig) -> set[int]:
    """The GEMM buckets healthy-path routing can reach, by EVALUATING
    the routing: an unconstrained stream splits any promoted request
    into max_bucket chunks plus one remainder, so every width in
    1..max_bucket is a reachable chunk; a declared-widths stream routes
    exactly those widths through ``submit()``'s promote/split rules."""
    if cfg.promote is None:
        return set()
    if cfg.warm_widths is None:
        return {
            bucket_for(w, cfg.max_bucket)
            for w in range(1, cfg.max_bucket + 1)
        }
    buckets: set[int] = set()
    for w in cfg.warm_widths:
        if w < cfg.promote:
            continue  # per-column path: rides the warmed matvec key
        for chunk in split_widths(w, cfg.max_bucket):
            buckets.add(bucket_for(chunk, cfg.max_bucket))
    return buckets


def enumerate_keyspace(cfg: ServeConfig) -> KeySpace:
    """Symbolically enumerate one config's finite compile surface."""
    _validate(cfg)
    storage, speculative = _resolved_storage(cfg)
    combine = _primary_combine(cfg, storage)
    label = _combine_label(cfg, combine)

    def matvec_key() -> ExecKey:
        return ExecKey(
            "matvec", cfg.strategy, cfg.kernel, label, 1, cfg.dtype, storage
        )

    def gemm_key(bucket: int) -> ExecKey:
        return ExecKey(
            "gemm", cfg.strategy, cfg.kernel, label, bucket, cfg.dtype,
            storage,
        )

    def spec_key(op: str, bucket: int) -> ExecKey:
        return ExecKey(
            op, cfg.strategy, cfg.kernel, _spec_combine(combine), bucket,
            cfg.dtype, SPECULATE,
        )

    def solver_key(op: str) -> ExecKey:
        bucket = solver_bucket(op, restart=cfg.restart, steps=cfg.steps)
        if cfg.solver_kernel == "pallas_fused" and op in FUSED_SOLVER_OPS:
            return ExecKey(
                op, cfg.strategy, "pallas_fused",
                _FUSED_COMBINES[cfg.strategy], bucket, cfg.dtype, storage,
            )
        return ExecKey(
            op, cfg.strategy, cfg.kernel, label, bucket, cfg.dtype, storage
        )

    def safe_key(op: str, bucket: int) -> ExecKey:
        return ExecKey(
            op, cfg.strategy, SAFE_KERNEL, None, bucket, cfg.dtype, NATIVE
        )

    warm: set[ExecKey] = {matvec_key()}
    if speculative:
        warm.add(spec_key("matvec", 1))
    for bucket in _warm_buckets(cfg):
        warm.add(gemm_key(bucket))
        if speculative:
            warm.add(spec_key("gemm", bucket))

    steady: set[ExecKey] = {matvec_key()}
    if speculative:
        steady.add(spec_key("matvec", 1))
    for bucket in _steady_buckets(cfg):
        steady.add(gemm_key(bucket))
        if speculative:
            steady.add(spec_key("gemm", bucket))

    fault: set[ExecKey] = set()
    mv_safe = safe_key("matvec", 1)
    if mv_safe != matvec_key():
        fault.add(mv_safe)
    if cfg.promote is not None:
        for bucket in bucket_ladder(cfg.max_bucket):
            g_safe = safe_key("gemm", bucket)
            if g_safe != gemm_key(bucket):
                fault.add(g_safe)

    for op in cfg.solver_ops:
        preferred = solver_key(op)
        warm.add(preferred)
        steady.add(preferred)
        s_safe = safe_key(op, preferred.bucket)
        if s_safe != preferred:
            fault.add(s_safe)

    warm_labels = {k.label() for k in warm}
    steady_labels = {k.label() for k in steady}
    fault_labels = {k.label() for k in fault}
    rollover_labels: set[str] = set()
    steady_beyond = len(steady_labels - warm_labels)
    for dst in cfg.reshard_to:
        dst_cfg = dataclasses.replace(
            cfg, name=f"{cfg.name}->{dst}", strategy=dst, reshard_to=()
        )
        dst_space = enumerate_keyspace(dst_cfg)
        # The destination's one-time post-swap warmup is the rollover
        # compile class; its own steady ⊆ warmup violations roll up into
        # the parent budget so a resharded-into config cannot hide one.
        rollover_labels.update(dst_space.warmup)
        fault_labels.update(dst_space.fault_only)
        steady_beyond += dst_space.budget["steady_beyond_warmup"]

    fault_labels -= warm_labels | steady_labels
    rollover_labels -= warm_labels | steady_labels
    total = len(
        warm_labels | steady_labels | fault_labels | rollover_labels
    )
    return KeySpace(
        warmup=tuple(sorted(warm_labels)),
        steady=tuple(sorted(steady_labels)),
        fault_only=tuple(sorted(fault_labels)),
        rollover=tuple(sorted(rollover_labels)),
        budget={
            "total": total,
            "warmup": len(warm_labels),
            "steady_beyond_warmup": steady_beyond,
        },
    )


# The pinned serve configurations the golden covers — one per compiled-
# surface family the repo serves (plain ladders per strategy, staged
# overlap, quantized residency, the speculative two-tier space, the XLA
# and fused solver tiers, and an online-reshard pair). Adding a config
# here widens the audited surface; the golden must be re-blessed.
KEYSPACE_CONFIGS: tuple[ServeConfig, ...] = (
    ServeConfig(name="rowwise_serve", strategy="rowwise"),
    ServeConfig(
        name="colwise_overlap", strategy="colwise", combine="overlap",
        stages=2,
    ),
    ServeConfig(
        name="blockwise_serve", strategy="blockwise", promote=4,
        max_bucket=16,
    ),
    ServeConfig(
        name="rowwise_int8c", strategy="rowwise", dtype_storage="int8c"
    ),
    ServeConfig(
        name="rowwise_speculate", strategy="rowwise",
        dtype_storage="speculate",
    ),
    ServeConfig(
        name="rowwise_solvers", strategy="rowwise", promote=None,
        solver_ops=SOLVER_OPS,
    ),
    ServeConfig(
        name="rowwise_fused_solvers", strategy="rowwise", promote=None,
        solver_ops=FUSED_SOLVER_OPS, solver_kernel="pallas_fused",
    ),
    ServeConfig(
        name="rowwise_reshard", strategy="rowwise",
        warm_widths=(1, 8, 32), reshard_to=("colwise", "blockwise"),
    ),
)


def keyspace_table(
    configs: tuple[ServeConfig, ...] = KEYSPACE_CONFIGS,
) -> dict:
    """The full audit artifact: every pinned config's enumerated surface
    plus its compile budget, in the golden's JSON shape."""
    table: dict = {"schema": KEYSPACE_SCHEMA, "configs": {}}
    for cfg in configs:
        space = enumerate_keyspace(cfg)
        serve = dataclasses.asdict(cfg)
        serve.pop("name")
        table["configs"][cfg.name] = {
            "serve": serve,
            "warmup": list(space.warmup),
            "steady": list(space.steady),
            "fault_only": list(space.fault_only),
            "rollover": list(space.rollover),
            "budget": dict(space.budget),
        }
    return table


def golden_path(root: str | Path | None = None) -> Path:
    base = Path(root) if root is not None else repo_root()
    return base / GOLDEN_REL


def load_golden(root: str | Path | None = None) -> dict | None:
    path = golden_path(root)
    if not path.exists():
        return None
    return json.loads(path.read_text())


def write_golden_keyspace(root: str | Path | None = None) -> Path:
    """Bless the current enumeration as the golden (the ``--write-golden
    --keyspace`` flow). Refuses to bless a table that violates the
    compile budget — a broken invariant must be fixed, never pinned."""
    table = keyspace_table()
    hard = [f for f in _audit_budget(table) if f.severity != "drift"]
    if hard:
        raise ValueError(
            "refusing to bless a keyspace that violates the compile "
            f"budget: {[f.message for f in hard]}"
        )
    path = golden_path(root)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(table, indent=2, sort_keys=True) + "\n")
    return path


def _canon(value):
    """JSON-canonical form (tuples become lists) so a freshly enumerated
    table compares equal to its round-tripped golden."""
    return json.loads(json.dumps(value, sort_keys=True))


def _audit_budget(table: dict) -> list[Finding]:
    """The hard half of the audit: per config, every steady-reachable
    key must be covered by warmup — the static ``compiles_steady == 0``
    proof. Independent of any golden."""
    findings: list[Finding] = []
    for name, entry in sorted(table.get("configs", {}).items()):
        beyond = sorted(set(entry["steady"]) - set(entry["warmup"]))
        if beyond:
            findings.append(Finding(
                GOLDEN_REL, 0, "keyspace-steady-unwarmed",
                f"config {name}: steady routing reaches "
                f"{len(beyond)} key(s) warmup never compiles: "
                + ", ".join(beyond[:4])
                + ("..." if len(beyond) > 4 else ""),
            ))
        declared = entry["budget"].get("steady_beyond_warmup")
        if declared != len(beyond) and not entry.get("rollover"):
            findings.append(Finding(
                GOLDEN_REL, 0, "keyspace-steady-unwarmed",
                f"config {name}: budget declares steady_beyond_warmup="
                f"{declared} but the table shows {len(beyond)}",
            ))
    return findings


def audit_table(table: dict, golden: dict | None) -> list[Finding]:
    """Full audit: the budget invariant (hard error) plus the golden
    diff (drift — ``keyspace-golden``)."""
    findings = _audit_budget(table)
    if golden is None:
        findings.append(Finding(
            GOLDEN_REL, 0, "keyspace-golden",
            "no golden keyspace table committed; bless with "
            "`python -m matvec_mpi_multiplier_tpu.staticcheck "
            "--keyspace --write-golden`",
        ))
        return findings
    if golden.get("schema") != table["schema"]:
        findings.append(Finding(
            GOLDEN_REL, 0, "keyspace-golden",
            f"golden schema {golden.get('schema')!r} != enumerator "
            f"schema {table['schema']!r}; re-bless",
        ))
        return findings
    got = set(table["configs"])
    want = set(golden.get("configs", {}))
    for name in sorted(want - got):
        findings.append(Finding(
            GOLDEN_REL, 0, "keyspace-golden",
            f"config {name} is golden-pinned but no longer enumerated",
        ))
    for name in sorted(got - want):
        findings.append(Finding(
            GOLDEN_REL, 0, "keyspace-golden",
            f"config {name} is enumerated but not golden-pinned; "
            "re-bless to widen the audited surface",
        ))
    for name in sorted(got & want):
        entry = _canon(table["configs"][name])
        pinned = _canon(golden["configs"][name])
        if entry == pinned:
            continue
        parts = []
        for cls in ("warmup", "steady", "fault_only", "rollover"):
            added = sorted(set(entry[cls]) - set(pinned.get(cls, [])))
            removed = sorted(set(pinned.get(cls, [])) - set(entry[cls]))
            if added:
                parts.append(f"+{cls}: " + ", ".join(added[:3]))
            if removed:
                parts.append(f"-{cls}: " + ", ".join(removed[:3]))
        if entry.get("serve") != pinned.get("serve"):
            parts.append("serve knobs changed")
        if entry.get("budget") != pinned.get("budget"):
            parts.append(
                f"budget {pinned.get('budget')} -> {entry.get('budget')}"
            )
        findings.append(Finding(
            GOLDEN_REL, 0, "keyspace-golden",
            f"config {name} drifted from golden ("
            + "; ".join(parts or ["content differs"]) + ")",
        ))
    return findings


def run_keyspace_audit(root: str | Path | None = None) -> list[Finding]:
    """Enumerate the pinned configs and audit against the committed
    golden — the ``--keyspace`` CLI layer."""
    return audit_table(keyspace_table(), load_golden(root))

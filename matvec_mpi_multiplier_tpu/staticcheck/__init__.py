"""Static analysis for the repo's schedule invariants.

Two layers, one CLI (``python -m matvec_mpi_multiplier_tpu.staticcheck``):

* **AST rule engine** (``rules``): visitor-based lint over the Python
  corpus — the four grep rules ``scripts/tier1.sh`` and ``tests/test_lint.py``
  used to duplicate, reimplemented on the AST (no false positives inside
  strings/docstrings, import aliases resolved), plus rules regex cannot
  express (implicit fp64 promotion, import-time ``jnp`` work, mutable
  default arguments). Exemptions are per-rule ``# <marker>: <reason>``
  comment markers; the marker registry drives the reason-required check.
* **Lowered-HLO auditor** (``hlo``): every registered strategy × combine ×
  kernel config is lowered on an abstract CPU mesh and its StableHLO is
  audited — a collective census pinned against the committed golden
  schedule table (``data/staticcheck/golden_schedule.json``), per-config
  transfer-byte accounting, the staged-overlap chunking assertion
  (``overlap@S`` must lower to S chunked collectives, never one full-width
  one), and a lowering-fingerprint stability gate (same ExecKey → same
  lowering hash — the engine-cache silent-recompile guard).

``scripts/tier1.sh --lint-only`` runs the rule layer fail-fast (pure AST
work, no device backend touched); ``tests/test_lint.py`` and
``tests/test_staticcheck.py`` are the
in-suite adapters over the same engine. One source of truth — the paper's
communication-schedule claims become CI-time compile errors
(docs/STATIC_ANALYSIS.md).
"""

from __future__ import annotations

from .corpus import SCAN_FILES, SCAN_ROOTS, SourceFile, iter_corpus, repo_root
from .findings import DRIFT_RULES, Finding, render_json, render_text
from .lockgraph import LOCKGRAPH_RULES, analyze, lockgraph_scope
from .rules import (
    MARKERS,
    RULES,
    check_marker_reasons,
    get_rule,
    run_rules,
)

__all__ = [
    "DRIFT_RULES",
    "Finding",
    "LOCKGRAPH_RULES",
    "MARKERS",
    "RULES",
    "SCAN_FILES",
    "SCAN_ROOTS",
    "SourceFile",
    "analyze",
    "check_marker_reasons",
    "get_rule",
    "iter_corpus",
    "lockgraph_scope",
    "render_json",
    "render_text",
    "repo_root",
    "run_rules",
]

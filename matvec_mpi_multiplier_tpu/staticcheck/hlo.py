"""Layer 2: the lowered-artifact auditor.

The paper's result is a communication-schedule story — which collectives
each partitioning strategy issues and how many bytes they move. The AST
rules can only check what the *source* says; this layer checks what a
strategy actually *lowers to*: every audited strategy × combine × kernel
config is built on an abstract CPU mesh, lowered to StableHLO (trace-only,
no compile — ~1 s for the whole table), and audited four ways:

* **Collective census** — counts of ``all-reduce`` / ``all-gather`` /
  ``reduce-scatter`` / ``collective-permute`` / ``all-to-all`` ops, pinned
  per config against BOTH the structural formula (what the schedule is
  *supposed* to issue: e.g. ``colwise|overlap@S`` → exactly S chunked
  reduce-scatters) and the committed golden table
  (``data/staticcheck/golden_schedule.json``). Code drift and golden drift
  each trip one side.
* **Transfer-byte accounting** — per-device collective payload (operand
  bytes presented to the interconnect per op, not wire traffic; the wire
  factor — e.g. 2(p−1)/p for a ring all-reduce — is topology's, the
  payload is the schedule's).
* **Staged-overlap chunking** — an ``overlap@S`` / ``overlap_ring@S`` body
  must lower to S chunked collectives carrying 1/S of the un-staged bytes
  each, never one full-width op (the ROADMAP's "overlap measures like the
  un-staged baseline while claiming to overlap" failure mode, made a
  compile-time error).
* **Fingerprint stability** — building the same :class:`ExecKey` twice
  must produce byte-identical lowerings (same sha256). A nondeterministic
  lowering would make the engine's AOT executable cache silently recompile
  (or worse, serve divergent programs) across restarts.

Census caveat, documented because it WILL surprise: ``rowwise|gather``
shows an empty census. Its final gather is a ``with_sharding_constraint``,
which lowers to a sharding custom-call that GSPMD turns into an all-gather
only at *compile* time — the census covers the collectives the program
issues explicitly (everything shard_map bodies do), which is exactly the
set the repo's schedule invariants are about.
"""

from __future__ import annotations

import hashlib
import json
import re
from pathlib import Path
from typing import Iterable, NamedTuple

from .corpus import repo_root
from .findings import Finding, dedup

# The audit operand: one shape/dtype exercises every schedule (divisible by
# the 8-device mesh, its 2x4 grid, and the S∈{2,4} stage ladder).
AUDIT_DEVICES = 8
AUDIT_M = 64
AUDIT_K = 64
AUDIT_DTYPE = "float32"
GOLDEN_REL = "data/staticcheck/golden_schedule.json"
GOLDEN_SCHEMA = 1

# StableHLO op → the census name (the HLO spelling the paper's tables use).
_KINDS = {
    "all_reduce": "all-reduce",
    "all_gather": "all-gather",
    "reduce_scatter": "reduce-scatter",
    "collective_permute": "collective-permute",
    "all_to_all": "all-to-all",
}

_ITEMSIZE = {"float32": 4, "float64": 8, "bfloat16": 2, "float16": 2}
_TENSOR_RE = re.compile(r"tensor<(?:([0-9x]+)x)?([a-z][a-z0-9]*)>")


class AuditConfig(NamedTuple):
    """One audited lowering: a strategy × combine(@stages) × kernel cell."""

    strategy: str
    combine: str
    stages: int | None = None
    kernel: str = "xla"

    @property
    def key(self) -> str:
        combine = self.combine + (
            f"@{self.stages}" if self.stages is not None else ""
        )
        return f"{self.strategy}|{combine}|{self.kernel}"


# The audited table: all three paper strategies across their combine
# families (models/colwise.py COLWISE_COMBINES; the gather family for the
# sharded-output strategies), the staged pair at S ∈ {2, 4}. pallas_ring
# is absent by design: the fused kernel is interpret-gated off-TPU and its
# collective lives inside the pallas call, invisible to StableHLO op
# counting. Kernel axis: "xla" (the tile kernels are interpret-gated too;
# their bodies carry no collectives, so the schedule census is
# kernel-invariant).
AUDIT_CONFIGS: tuple[AuditConfig, ...] = (
    AuditConfig("rowwise", "gather"),
    AuditConfig("rowwise", "ring"),
    AuditConfig("rowwise", "overlap", 2),
    AuditConfig("rowwise", "overlap", 4),
    AuditConfig("colwise", "psum"),
    AuditConfig("colwise", "psum_scatter"),
    AuditConfig("colwise", "ring"),
    AuditConfig("colwise", "ring_overlap"),
    AuditConfig("colwise", "a2a"),
    AuditConfig("colwise", "overlap", 2),
    AuditConfig("colwise", "overlap", 4),
    AuditConfig("colwise", "overlap_ring", 2),
    AuditConfig("colwise", "overlap_ring", 4),
    AuditConfig("blockwise", "gather"),
    AuditConfig("blockwise", "ring"),
    AuditConfig("blockwise", "overlap", 2),
    AuditConfig("blockwise", "overlap", 4),
)


def _audit_mesh():
    import jax

    from ..parallel.mesh import make_mesh

    devices = jax.devices()
    if len(devices) < AUDIT_DEVICES:
        raise RuntimeError(
            f"the HLO audit needs {AUDIT_DEVICES} devices (an abstract CPU "
            f"mesh), got {len(devices)}; run under JAX_PLATFORMS=cpu with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{AUDIT_DEVICES} (the CLI and tests/conftest.py both set this)"
        )
    return make_mesh(AUDIT_DEVICES, devices=devices)


def lower_config(cfg: AuditConfig, mesh):
    """Build and lower one config against the audit operand (trace-only)."""
    import jax
    import numpy as np

    from ..models import get_strategy

    kwargs: dict = {"combine": cfg.combine, "kernel": cfg.kernel}
    if cfg.stages is not None:
        kwargs["stages"] = cfg.stages
    fn = get_strategy(cfg.strategy).build(mesh, **kwargs)
    dtype = np.dtype(AUDIT_DTYPE)
    a = jax.ShapeDtypeStruct((AUDIT_M, AUDIT_K), dtype)
    x = jax.ShapeDtypeStruct((AUDIT_K,), dtype)
    return fn.lower(a, x)


def _tensor_bytes(type_str: str) -> int:
    m = _TENSOR_RE.match(type_str)
    if not m:
        return 0
    dims, elem = m.groups()
    count = 1
    for d in (dims or "").split("x"):
        if d:
            count *= int(d)
    return count * _ITEMSIZE.get(
        {"f32": "float32", "f64": "float64", "bf16": "bfloat16",
         "f16": "float16"}.get(elem, elem),
        0,
    )


def collective_census(lowered) -> tuple[dict[str, int], dict[str, int]]:
    """Walk the lowered StableHLO module: per-kind op counts and per-kind
    payload bytes (sum of operand tensor bytes — the per-device bytes each
    op hands the interconnect)."""
    census: dict[str, int] = {}
    payload: dict[str, int] = {}

    def walk(op):
        for region in op.regions:
            for block in region.blocks:
                for child in block.operations:
                    name = child.operation.name
                    if name.startswith("stablehlo."):
                        kind = _KINDS.get(name.split(".", 1)[1])
                        if kind is not None:
                            census[kind] = census.get(kind, 0) + 1
                            payload[kind] = payload.get(kind, 0) + sum(
                                _tensor_bytes(str(o.type))
                                for o in child.operands
                            )
                    walk(child.operation)

    walk(lowered.compiler_ir(dialect="stablehlo").operation)
    return census, payload


def expected_schedule(
    cfg: AuditConfig, mesh
) -> tuple[dict[str, int], dict[str, int]]:
    """The structural formula: what each schedule must issue, derived from
    the mesh (p devices, (r, c) grid) and the audit operand — the second,
    golden-independent pin on the census. An ``overlap@S`` entry is by
    construction S chunked collectives at 1/S of the un-staged bytes."""
    from ..parallel.mesh import mesh_grid_shape

    p = int(mesh.devices.size)
    r, _c = mesh_grid_shape(mesh)
    m = AUDIT_M
    itemsize = _ITEMSIZE[AUDIT_DTYPE]
    s = cfg.stages or 1

    def entry(**kinds: tuple[int, int]):
        # each kind: (op count, elements per op)
        census = {k: n for k, (n, _) in kinds.items()}
        payload = {k: n * e * itemsize for k, (n, e) in kinds.items()}
        return census, payload

    strat, comb = cfg.strategy, cfg.combine
    if strat in ("rowwise", "colwise"):
        if comb == "gather":
            # with_sharding_constraint: GSPMD's all-gather, invisible to
            # the StableHLO census (module docstring).
            return entry()
        if comb == "psum":
            return entry(**{"all-reduce": (1, m)})
        if comb == "psum_scatter":
            return entry(**{"reduce-scatter": (1, m)})
        if comb in ("ring", "ring_overlap"):
            # p−1 neighbor hops, each moving one m/p accumulator chunk.
            return entry(**{"collective-permute": (p - 1, m // p)})
        if comb == "a2a":
            return entry(**{"all-to-all": (1, m)})
        if comb == "overlap" and strat == "colwise":
            # S chunked reduce-scatters, m/S rows each.
            return entry(**{"reduce-scatter": (s, m // s)})
        if comb == "overlap" and strat == "rowwise":
            # S chunked ring all-gathers: (p−1) hops of m/(p·S) rows each.
            return entry(**{"collective-permute": (s * (p - 1), m // (p * s))})
        if comb == "overlap_ring":
            # S staged ring reduce-scatters: each stage's m/S-row partial
            # rides p−1 hops of m/(p·S)-row accumulator chunks.
            return entry(**{"collective-permute": (s * (p - 1), m // (p * s))})
    if strat == "blockwise":
        if comb == "gather":
            # The in-body reduce-over-grid-columns; the final gather over
            # 'rows' is GSPMD's (as above).
            return entry(**{"all-reduce": (1, m // r)})
        if comb == "ring":
            return entry(**{
                "all-reduce": (1, m // r),
                "collective-permute": (r - 1, m // r),
            })
        if comb == "overlap":
            # Per stage: one chunked psum over grid cols + (r−1) chunked
            # ring-gather hops over grid rows, m/(r·S) rows each.
            return entry(**{
                "all-reduce": (s, m // (r * s)),
                "collective-permute": (s * (r - 1), m // (r * s)),
            })
    raise KeyError(f"no expected-schedule formula for {cfg.key}")


def lowering_fingerprint(lowered) -> str:
    return hashlib.sha256(lowered.as_text().encode()).hexdigest()


def exec_key(cfg: AuditConfig):
    """The engine-cache identity this config dispatches under — the
    fingerprint gate's subject (engine/executables.py records the same
    hash at compile time)."""
    from ..engine.executables import ExecKey

    combine = cfg.combine + (
        f"@{cfg.stages}" if cfg.stages is not None else ""
    )
    return ExecKey(
        op="matvec", strategy=cfg.strategy, kernel=cfg.kernel,
        combine=combine, bucket=1, dtype=AUDIT_DTYPE,
    )


def audit_entry(cfg: AuditConfig, mesh, lowered=None) -> dict:
    """Package one config's observed schedule (lowering it unless the
    caller already has the lowered artifact in hand)."""
    if lowered is None:
        lowered = lower_config(cfg, mesh)
    census, payload = collective_census(lowered)
    return {
        "census": dict(sorted(census.items())),
        "payload_bytes": dict(sorted(payload.items())),
        "payload_total_bytes": sum(payload.values()),
    }


def build_schedule_table(configs: Iterable[AuditConfig] | None = None) -> dict:
    """The full golden-table payload for the current tree."""
    import jax

    mesh = _audit_mesh()
    entries = {
        cfg.key: audit_entry(cfg, mesh)
        for cfg in (configs or AUDIT_CONFIGS)
    }
    return {
        "schema": GOLDEN_SCHEMA,
        "mesh": {
            "devices": AUDIT_DEVICES,
            "grid": list(mesh.devices.shape),
        },
        "operand": {"m": AUDIT_M, "k": AUDIT_K, "dtype": AUDIT_DTYPE},
        "jax_version_at_capture": jax.__version__,
        "configs": entries,
    }


def write_golden(root: Path | None = None, path: Path | None = None) -> Path:
    """Regenerate the committed golden schedule table — the bless step
    after a deliberate schedule change (docs/STATIC_ANALYSIS.md)."""
    root = Path(root) if root is not None else repo_root()
    path = Path(path) if path is not None else root / GOLDEN_REL
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(build_schedule_table(), indent=2) + "\n")
    return path


def run_hlo_audit(
    root: Path | None = None,
    golden_path: Path | None = None,
    configs: Iterable[AuditConfig] | None = None,
    check_fingerprints: bool = True,
) -> list[Finding]:
    """The full audit: census + bytes vs formula and golden, the overlap
    chunking gate (folded into both pins), and fingerprint stability.
    Returns findings; empty means every schedule lowers as pinned."""
    root = Path(root) if root is not None else repo_root()
    golden_path = (
        Path(golden_path) if golden_path is not None else root / GOLDEN_REL
    )
    configs = tuple(configs or AUDIT_CONFIGS)
    findings: list[Finding] = []

    golden_cfgs: dict = {}
    have_golden = golden_path.is_file()
    if have_golden:
        golden = json.loads(golden_path.read_text())
        if golden.get("schema") != GOLDEN_SCHEMA:
            findings.append(Finding(
                GOLDEN_REL, 0, "hlo-golden",
                f"golden schema {golden.get('schema')!r} != "
                f"{GOLDEN_SCHEMA}; regenerate with --write-golden",
            ))
        golden_cfgs = golden.get("configs", {})
    else:
        findings.append(Finding(
            GOLDEN_REL, 0, "hlo-golden",
            "golden collective-schedule table missing; generate it with "
            "`python -m matvec_mpi_multiplier_tpu.staticcheck "
            "--write-golden`",
        ))

    mesh = _audit_mesh()
    for cfg in configs:
        lowered = lower_config(cfg, mesh)
        observed = audit_entry(cfg, mesh, lowered)
        exp_census, exp_payload = expected_schedule(cfg, mesh)

        overlap_hint = ""
        if cfg.stages is not None:
            overlap_hint = (
                f" — a staged overlap body must lower to S={cfg.stages} "
                "chunked collectives (1/S of the un-staged bytes each), "
                "never a full-width one"
            )
        if observed["census"] != dict(sorted(exp_census.items())):
            findings.append(Finding(
                f"<hlo:{cfg.key}>", 0, "hlo-schedule",
                f"collective census {observed['census']} != structural "
                f"expectation {dict(sorted(exp_census.items()))}"
                f"{overlap_hint}",
            ))
        elif observed["payload_bytes"] != dict(sorted(exp_payload.items())):
            findings.append(Finding(
                f"<hlo:{cfg.key}>", 0, "hlo-schedule",
                f"collective payload {observed['payload_bytes']} != "
                f"structural expectation "
                f"{dict(sorted(exp_payload.items()))}{overlap_hint}",
            ))

        if have_golden:
            # Empty/absent "configs" must read as every pin missing, not
            # as a clean audit — a truncated golden would otherwise turn
            # the whole pin layer off silently.
            pinned = golden_cfgs.get(cfg.key)
            if pinned is None:
                findings.append(Finding(
                    GOLDEN_REL, 0, "hlo-golden",
                    f"config {cfg.key} missing from the golden table; "
                    "bless it with --write-golden",
                ))
            elif pinned != observed:
                findings.append(Finding(
                    GOLDEN_REL, 0, "hlo-census",
                    f"{cfg.key}: lowered schedule {observed} != golden "
                    f"{pinned}{overlap_hint}; if the change is deliberate, "
                    "bless it with --write-golden",
                ))

        if check_fingerprints:
            # The census pass's lowering doubles as the first sample; one
            # fresh rebuild probes determinism.
            fp_a = lowering_fingerprint(lowered)
            fp_b = lowering_fingerprint(lower_config(cfg, mesh))
            if fp_a != fp_b:
                findings.append(Finding(
                    f"<hlo:{cfg.key}>", 0, "hlo-fingerprint",
                    f"two lowerings of ExecKey {exec_key(cfg)} hash "
                    f"differently ({fp_a[:12]} vs {fp_b[:12]}): the "
                    "engine's AOT cache would silently recompile (or "
                    "serve divergent programs) across restarts",
                ))

    if have_golden:
        audited = {cfg.key for cfg in AUDIT_CONFIGS}
        for stale in sorted(set(golden_cfgs) - audited):
            findings.append(Finding(
                GOLDEN_REL, 0, "hlo-golden",
                f"golden table pins unknown config {stale}; regenerate "
                "with --write-golden",
            ))
    return dedup(findings)

"""Layer 2: the lowered-artifact auditor.

The paper's result is a communication-schedule story — which collectives
each partitioning strategy issues and how many bytes they move. The AST
rules can only check what the *source* says; this layer checks what a
strategy actually *lowers to*: every audited strategy × combine × kernel
config is built on an abstract CPU mesh, lowered to StableHLO (trace-only,
no compile — ~1 s for the whole table), and audited four ways:

* **Collective census** — counts of ``all-reduce`` / ``all-gather`` /
  ``reduce-scatter`` / ``collective-permute`` / ``all-to-all`` ops, pinned
  per config against BOTH the structural formula (what the schedule is
  *supposed* to issue: e.g. ``colwise|overlap@S`` → exactly S chunked
  reduce-scatters) and the committed golden table
  (``data/staticcheck/golden_schedule.json``). Code drift and golden drift
  each trip one side.
* **Transfer-byte accounting** — per-device collective payload (operand
  bytes presented to the interconnect per op, not wire traffic; the wire
  factor — e.g. 2(p−1)/p for a ring all-reduce — is topology's, the
  payload is the schedule's).
* **Staged-overlap chunking** — an ``overlap@S`` / ``overlap_ring@S`` body
  must lower to S chunked collectives carrying 1/S of the un-staged bytes
  each, never one full-width op (the ROADMAP's "overlap measures like the
  un-staged baseline while claiming to overlap" failure mode, made a
  compile-time error).
* **Fingerprint stability** — building the same :class:`ExecKey` twice
  must produce byte-identical lowerings (same sha256). A nondeterministic
  lowering would make the engine's AOT executable cache silently recompile
  (or worse, serve divergent programs) across restarts.
* **A-operand byte accounting** — per config, the bytes of the lowered
  program's resident-A input parameters (everything but the trailing
  ``x``), pinned as ``a_bytes``/``a_bytes_ratio`` in the golden table.
  The quantized-storage configs (``dtype_storage`` — ops/quantize.py)
  must actually shrink the resident stream: ratio ≤ 0.30× for the
  single-payload formats (int8, fp8 + scale plane), ≤ 0.55× for the
  compensated pair (int8c) — the structural pin behind the PR's
  bandwidth claim.
* **Early-dequant census gate** — a quantized config's lowering must
  never ``convert`` a full-width (local or global) A-shaped low-bit
  tensor to float before the contraction: that is the "silently
  dequantized A" failure mode, where the program stores ¼ the bytes but
  MOVES all of them (the tile-wise scan kernel converts (m, block)
  tiles only). The dequant-first anti-pattern kernel
  (``ops.quantize.matvec_quantized_dequant_first``) exists as the
  known-bad lowering this gate is tested against.

* **Donation → aliasing audit** (``hlo-donation``) — the engine sets
  ``donate_argnums`` on every dispatch and the registry's
  ``HbmAccountant`` silently assumes the RHS buffer is actually reused;
  this gate verifies the donation LOWERED: the compiled artifact's
  ``@main`` RHS argument must carry ``tf.aliasing_output`` (shape-matched
  input-output aliasing) or ``jax.buffer_donor`` (donated, compiler
  chooses), read off the same lowering recipe the engine compiles
  (``engine.executables.lower_artifact`` — one shared accessor, so the
  cache's fingerprint and this audit can never disagree about which
  executable they inspected). Dropping ``donate_argnums`` from the
  dispatch path turns this red (mutation-tested).
* **Peak-liveness estimate** (``hlo-peak-liveness``) — a static
  peak-buffer estimate from the StableHLO: a linear-schedule liveness
  walk over the module (function args live to last use, op results from
  creation to last use, nested regions and calls contributing their own
  peak at the issuing op), pinned per config in the golden table as
  ``peak_bytes``/``peak_bytes_ratio``. Quantized configs must respect
  the :data:`PEAK_LIVENESS_CEILING` ratios against their native
  counterpart's peak — the liveness-level face of the storage ceilings,
  catching a lowering that stores the payload's bytes but materializes
  a dequantized full-width temporary (which the census gate sees
  structurally and this gate sees quantitatively).

* **Served-solver schedule pins** (``hlo-solver-schedule`` /
  ``hlo-solver-loop``) — every ``solvers/ops.py`` program (the engine's
  ``submit(op="cg"|...)`` artifacts) is lowered per strategy × op and
  audited as a whole program: its collective-kind set must EQUAL the
  matvec counterpart's (the loop body's matvec is the only collective
  site; the verified-exit and final true-residual matvecs reuse the same
  combine, so counts — pinned in the golden's ``solvers`` section — may
  exceed the matvec's), and the module must contain ≥ 1
  ``stablehlo.while`` (scan/fori included), so a host-synced residual
  check — which would tear the iteration out of the compiled program and
  re-dispatch k matvecs per solve — is a compile-time error.

The quantized configs' collective census equals their native
counterpart's by construction — the combine operates on the fp32
accumulator partials, never on the payload — so the storage axis is
invisible to the schedule pins and visible only in the A-byte accounting
(the orthogonality GSPMD predicts for per-operand dtype choices).

Census caveat, documented because it WILL surprise: ``rowwise|gather``
shows an empty census. Its final gather is a ``with_sharding_constraint``,
which lowers to a sharding custom-call that GSPMD turns into an all-gather
only at *compile* time — the census covers the collectives the program
issues explicitly (everything shard_map bodies do), which is exactly the
set the repo's schedule invariants are about.
"""

from __future__ import annotations

import hashlib
import json
import re
from pathlib import Path
from typing import Iterable, NamedTuple

from .corpus import repo_root
from .findings import Finding, dedup

# The audit operand: one shape/dtype exercises every schedule (divisible by
# the 8-device mesh, its 2x4 grid, and the S∈{2,4} stage ladder). The
# contraction axis is wide enough that every strategy's shard holds ≥ 2
# full-size quantization groups (ops.quantize.DEFAULT_BLOCK = 128 at 8
# contraction shards needs k ≥ 2048), so the storage configs audit at the
# production block size instead of a clamped one whose scale-plane
# overhead would dominate the byte ratios. The collective payloads are
# functions of m and p only, so the census pins are k-independent.
AUDIT_DEVICES = 8
AUDIT_M = 64
AUDIT_K = 2048
AUDIT_DTYPE = "float32"
GOLDEN_REL = "data/staticcheck/golden_schedule.json"
# Schema 7 over 6: the table gains a top-level "reshards" section pinning
# each online-migration program's collective census and per-device payload
# bytes per (src, dst) strategy pair (parallel/reshard.py; gate id
# hlo-reshard-schedule): a layout migration must be the minimal
# all_to_all/ppermute sequence — a host-transfer-shaped lowering (any
# gather/reduce kind) or a redundant collective turns the audit red.
# Schema 6 over 5: the table gains a top-level "fused_solvers" section
# pinning the fused Pallas iteration tier's jaxpr-level census
# (ops/pallas_solver.py): exactly ONE pallas_call plus the strategy's S
# collective hops per while body, and — for quantized residents — zero
# full-shard low-bit converts outside the kernel (the fused-solver audit
# below; gate ids hlo-fused-solver / hlo-early-dequant).
# Schema 5 over 4: the table gains a top-level "speculative" section
# pinning each fused speculative program's census (the int8c counterpart's
# schedule + at most ONE tiny extra reduction), probe count, and the
# device-predicate output count (the speculative audit below).
# Schema 4 over 3: the table gains a top-level "solvers" section pinning
# each served solver loop's whole-program collective census and
# stablehlo.while count per strategy × op (the solver audit below).
# Schema 3 over 2: every entry additionally pins the compiled-artifact
# memory audit — RHS donation state ("aliased"/"donated") and the static
# peak-liveness estimate (peak_bytes / peak_bytes_ratio).
GOLDEN_SCHEMA = 7

# The solver audit's square operand (the solver ops need m == k). Shares
# the audit mesh's divisibility needs (8 devices, the 2x4 grid); small on
# purpose — the census counts are size-independent, and 15 solver
# lowerings ride every full audit.
SOLVER_AUDIT_N = 256

# The FUSED-solver audit operand, deliberately larger than the XLA solver
# audit's: at n = 2048 every strategy's int8c shard holds ≥ 2 full-size
# quantization groups (ops.quantize.default_block), so a sanctioned
# per-tile upcast inside the kernel and a full-shard dequant outside it
# have DIFFERENT shapes — the extended early-dequant gate can tell them
# apart. (At n = 256 a colwise shard is one block wide and the distinction
# collapses.) The census counts themselves are size-independent.
FUSED_SOLVER_AUDIT_N = 2048

# Audit-side override of the engine's dispatch-path donation spec:
# None means "the engine's own DONATE_ARGNUMS" (engine/executables.py —
# ONE constant, resolved lazily so importing this module never pulls
# jax in). The donation mutation test patches this to () to prove the
# audit goes red when the dispatch path stops donating.
ENGINE_DONATE_ARGNUMS: tuple[int, ...] | None = None

# Resident-A byte-ratio ceilings the quantized configs must meet
# (acceptance pins; docs/QUANTIZATION.md derives them: 1-byte payload +
# fp32 scale plane at 1/block density, ×2 for the compensated pair).
STORAGE_BYTE_CEILING = {"int8": 0.30, "fp8": 0.30, "int8c": 0.55}

# Peak-LIVENESS ceilings (quantized peak vs the native counterpart's
# peak, both per-device — the memory audit's gate). Looser than the
# resident-stream ceilings above because the liveness walk also sees
# schedule temporaries (tile buffers, transpose/reshape copies, the
# scan carry) that scale with m·block rather than with the payload; at
# the audit operand the clean kernels measure 0.52–0.82×. What the gate
# must catch is a lowering that materializes a dequantized full-width A
# temporary — that lands at ≥ 1.1× native (the dequant-first mutation
# test pins both sides of the margin).
PEAK_LIVENESS_CEILING = {"int8": 0.70, "fp8": 0.70, "int8c": 0.90}

# StableHLO op → the census name (the HLO spelling the paper's tables use).
_KINDS = {
    "all_reduce": "all-reduce",
    "all_gather": "all-gather",
    "reduce_scatter": "reduce-scatter",
    "collective_permute": "collective-permute",
    "all_to_all": "all-to-all",
}

_ITEMSIZE = {
    "float32": 4, "float64": 8, "bfloat16": 2, "float16": 2,
    "int8": 1, "float8": 1,
    # Integer/pred widths the peak-liveness walk meets (iota indices,
    # loop counters, masks); irrelevant to the collective payloads.
    "int1": 1, "int16": 2, "int32": 4, "int64": 8, "uint32": 4,
    "uint64": 8,
}
_TENSOR_RE = re.compile(r"tensor<(?:([0-9x]+)x)?([A-Za-z][A-Za-z0-9_]*)>")
# StableHLO element-type spelling → the census name above. f8 variants all
# read as "float8" (1 byte); i8/si8/ui8 as int8.
_ELEM_NAMES = {
    "f32": "float32", "f64": "float64", "bf16": "bfloat16", "f16": "float16",
    "i8": "int8", "si8": "int8", "ui8": "int8",
    "i1": "int1", "i16": "int16", "i32": "int32", "i64": "int64",
    "ui32": "uint32", "ui64": "uint64",
}

_FLOAT_ELEMS = ("f32", "f64", "bf16", "f16")
_LOWBIT_ELEMS = ("i8", "si8", "ui8")


def _elem_name(elem: str) -> str:
    if elem.startswith("f8"):
        return "float8"
    return _ELEM_NAMES.get(elem, elem)


class AuditConfig(NamedTuple):
    """One audited lowering: a strategy × combine(@stages) × kernel ×
    storage cell."""

    strategy: str
    combine: str
    stages: int | None = None
    kernel: str = "xla"
    # Resident-A storage format (ops/quantize.py): "native" audits the
    # plain array path; "int8"/"int8c"/"fp8" audit the quantized
    # residency. Native keys keep their historical spelling (no suffix)
    # so the pre-quantization golden entries survive the schema bump.
    storage: str = "native"

    @property
    def key(self) -> str:
        combine = self.combine + (
            f"@{self.stages}" if self.stages is not None else ""
        )
        base = f"{self.strategy}|{combine}|{self.kernel}"
        return base if self.storage == "native" else f"{base}|{self.storage}"


# The audited table: all three paper strategies across their combine
# families (models/colwise.py COLWISE_COMBINES; the gather family for the
# sharded-output strategies), the staged pair at S ∈ {2, 4}. pallas_ring
# is absent by design: the fused kernel is interpret-gated off-TPU and its
# collective lives inside the pallas call, invisible to StableHLO op
# counting. Kernel axis: "xla" (the tile kernels are interpret-gated too;
# their bodies carry no collectives, so the schedule census is
# kernel-invariant).
AUDIT_CONFIGS: tuple[AuditConfig, ...] = (
    AuditConfig("rowwise", "gather"),
    AuditConfig("rowwise", "ring"),
    AuditConfig("rowwise", "overlap", 2),
    AuditConfig("rowwise", "overlap", 4),
    AuditConfig("colwise", "psum"),
    AuditConfig("colwise", "psum_scatter"),
    AuditConfig("colwise", "ring"),
    AuditConfig("colwise", "ring_overlap"),
    AuditConfig("colwise", "a2a"),
    AuditConfig("colwise", "overlap", 2),
    AuditConfig("colwise", "overlap", 4),
    AuditConfig("colwise", "overlap_ring", 2),
    AuditConfig("colwise", "overlap_ring", 4),
    AuditConfig("blockwise", "gather"),
    AuditConfig("blockwise", "ring"),
    AuditConfig("blockwise", "overlap", 2),
    AuditConfig("blockwise", "overlap", 4),
    # Quantized-storage cells: one per strategy's default schedule plus
    # the format ladder on rowwise (the simplest A-byte story: no
    # in-body collective, so every parameter byte is the payload's).
    # Their census must EQUAL the native counterpart's; their a_bytes
    # must meet STORAGE_BYTE_CEILING; their lowerings must pass the
    # early-dequant gate. fp8 cells are filtered out at audit time on
    # backends whose build lacks the dtype (ops.quantize.fp8_supported).
    AuditConfig("rowwise", "gather", storage="int8"),
    AuditConfig("rowwise", "gather", storage="int8c"),
    AuditConfig("rowwise", "gather", storage="fp8"),
    AuditConfig("colwise", "psum_scatter", storage="int8"),
    AuditConfig("colwise", "psum_scatter", storage="int8c"),
    AuditConfig("blockwise", "gather", storage="int8"),
)


class SolverAuditConfig(NamedTuple):
    """One audited served-solver lowering: a solver op compiled around one
    strategy × combine matvec (``solvers/ops.py::build_solver`` — the
    program the engine's ``submit(op=...)`` path dispatches)."""

    op: str
    strategy: str
    combine: str

    @property
    def key(self) -> str:
        return f"{self.op}|{self.strategy}|{self.combine}"

    @property
    def matvec(self) -> AuditConfig:
        """The matvec counterpart whose collective-kind SET the solver's
        whole-program census must equal (the loop body's matvec is the
        only collective site; everything else rides replicated)."""
        return AuditConfig(self.strategy, self.combine)


# Every served op (solvers/ops.py::SOLVER_OPS — the audit cross-checks
# the two lists and reddens on drift, so a new op cannot ship unpinned)
# across one combine per strategy family: the default gathers plus
# colwise's psum, whose non-empty census makes the op-SET gate bite
# (rowwise/blockwise gather lower their combine as GSPMD sharding
# constraints — empty census — so for them the while-count pin is the
# live tripwire).
_SOLVER_AUDIT_OPS = ("cg", "gmres", "power", "lanczos", "chebyshev")
SOLVER_AUDIT_CONFIGS: tuple[SolverAuditConfig, ...] = tuple(
    SolverAuditConfig(op, strategy, combine)
    for strategy, combine in (
        ("rowwise", "gather"),
        ("colwise", "psum"),
        ("blockwise", "gather"),
    )
    for op in _SOLVER_AUDIT_OPS
)


class FusedSolverAuditConfig(NamedTuple):
    """One audited FUSED-solver trace: a fixed-recurrence op compiled
    through the fused Pallas iteration tier
    (``solvers/ops.py::build_solver(kernel="pallas_fused")`` →
    ``ops/pallas_solver.py``) at one strategy × canonical combine ×
    resident storage. Audited at the JAXPR level, not StableHLO: the
    ``pallas_call`` boundary — the very thing the gate counts — is
    inlined away by lowering, but ``jax.make_jaxpr`` preserves it."""

    op: str
    strategy: str
    combine: str
    storage: str = "native"

    @property
    def key(self) -> str:
        return f"{self.op}|{self.strategy}|{self.combine}|{self.storage}"


# Both fused ops across the two supported strategy families (their
# canonical combines — the only spellings check_fused_solver admits),
# plus the int8c-resident colwise cell whose census proves the quantized
# solve never materializes a dequantized A (the PR's acceptance pin).
FUSED_SOLVER_AUDIT_CONFIGS: tuple[FusedSolverAuditConfig, ...] = tuple(
    FusedSolverAuditConfig(op, strategy, combine, storage)
    for op in ("cg", "chebyshev")
    for strategy, combine, storage in (
        ("rowwise", "gather", "native"),
        ("colwise", "psum", "native"),
        ("colwise", "psum", "int8c"),
    )
)


def _supported_configs(
    configs: Iterable[AuditConfig],
) -> tuple[AuditConfig, ...]:
    """Filter configs this backend build can lower (fp8 cells need the
    float8 dtype). The stale-key check uses the same filter so a golden
    blessed on an fp8-capable build does not read as stale elsewhere."""
    from ..ops.quantize import fp8_supported

    return tuple(
        cfg for cfg in configs
        if cfg.storage != "fp8" or fp8_supported()
    )


def _audit_mesh():
    import jax

    from ..parallel.mesh import make_mesh

    devices = jax.devices()
    if len(devices) < AUDIT_DEVICES:
        raise RuntimeError(
            f"the HLO audit needs {AUDIT_DEVICES} devices (an abstract CPU "
            f"mesh), got {len(devices)}; run under JAX_PLATFORMS=cpu with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{AUDIT_DEVICES} (the CLI and tests/conftest.py both set this)"
        )
    return make_mesh(AUDIT_DEVICES, devices=devices)


def audit_block(cfg: AuditConfig, mesh) -> int | None:
    """The quantization block the audit uses for one quantized config —
    the same derivation the engine's residency step makes
    (``ops.quantize.default_block`` against the strategy's contraction
    sharding). None for native storage."""
    if cfg.storage == "native":
        return None
    from ..models import get_strategy
    from ..ops.quantize import default_block

    strat = get_strategy(cfg.strategy)
    return default_block(AUDIT_K, strat.contraction_shards(mesh))


def lower_config(cfg: AuditConfig, mesh, kernel=None):
    """Build and lower one config against the audit operand (trace-only).
    ``kernel`` overrides the local kernel callable — the early-dequant
    gate's mutation tests inject the dequant-first anti-pattern here."""
    import jax
    import numpy as np

    from ..models import get_strategy

    kwargs: dict = {
        "combine": cfg.combine,
        "kernel": kernel if kernel is not None else cfg.kernel,
    }
    if cfg.stages is not None:
        kwargs["stages"] = cfg.stages
    dtype = np.dtype(AUDIT_DTYPE)
    if cfg.storage != "native":
        from ..ops.quantize import quantized_struct

        kwargs["dtype_storage"] = cfg.storage
        a = quantized_struct(
            AUDIT_M, AUDIT_K, cfg.storage, dtype, audit_block(cfg, mesh)
        )
    else:
        a = jax.ShapeDtypeStruct((AUDIT_M, AUDIT_K), dtype)
    fn = get_strategy(cfg.strategy).build(mesh, **kwargs)
    x = jax.ShapeDtypeStruct((AUDIT_K,), dtype)
    return fn.lower(a, x)


def _tensor_bytes(type_str: str) -> int:
    m = _TENSOR_RE.match(type_str)
    if not m:
        return 0
    dims, elem = m.groups()
    count = 1
    for d in (dims or "").split("x"):
        if d:
            count *= int(d)
    return count * _ITEMSIZE.get(_elem_name(elem), 0)


def collective_census(lowered) -> tuple[dict[str, int], dict[str, int]]:
    """Walk the lowered StableHLO module: per-kind op counts and per-kind
    payload bytes (sum of operand tensor bytes — the per-device bytes each
    op hands the interconnect)."""
    census: dict[str, int] = {}
    payload: dict[str, int] = {}

    def walk(op):
        for region in op.regions:
            for block in region.blocks:
                for child in block.operations:
                    name = child.operation.name
                    if name.startswith("stablehlo."):
                        kind = _KINDS.get(name.split(".", 1)[1])
                        if kind is not None:
                            census[kind] = census.get(kind, 0) + 1
                            payload[kind] = payload.get(kind, 0) + sum(
                                _tensor_bytes(str(o.type))
                                for o in child.operands
                            )
                    walk(child.operation)

    walk(lowered.compiler_ir(dialect="stablehlo").operation)
    return census, payload


def _func_name(op) -> str:
    """The sym_name of one ``func.func`` op, unquoted — the ONE
    predicate every artifact gate walks the module with."""
    return str(op.attributes["sym_name"]).strip('"')


def _main_func(module):
    """The module's ``@main`` entry function (None when absent)."""
    for op in module.body.operations:
        if op.operation.name == "func.func" and _func_name(op) == "main":
            return op
    return None


def a_operand_bytes(lowered) -> int:
    """Bytes of the lowered program's resident-A input parameters: every
    ``@main`` argument except the trailing ``x`` — for native storage the
    one (m, k) array, for quantized storage the payload + scale (+
    correction) leaves. Read off the ARTIFACT (the module's entry
    signature), not the builder's intent — that is the whole point of
    auditing."""
    main = _main_func(lowered.compiler_ir(dialect="stablehlo"))
    if main is None:
        raise RuntimeError("lowered module has no @main function to audit")
    types = [str(a.type) for a in main.regions[0].blocks[0].arguments]
    if not types:
        return 0
    return sum(_tensor_bytes(t) for t in types[:-1])


# ---------------------------------------------------------- memory audit
#
# The engine-recipe lowering: strategy build + sharded arg structs +
# donate_argnums, through the SAME accessor the AOT cache compiles
# (engine.executables.lower_artifact). The schedule census keeps its own
# plain-struct lowering above (its golden fingerprints predate this
# audit); the memory facts are read off the artifact the engine ships.


def engine_builder(cfg: AuditConfig, mesh, kernel=None,
                   donate: tuple[int, ...] | None = None):
    """A builder in the engine's ``ExecutableCache`` contract —
    ``() -> (fn, arg_structs, donate_argnums)`` — for one audited
    config, mirroring ``MatvecEngine._matvec_builder_for`` (sharded
    structs, quantized pytree template under quantized storage, the RHS
    donated)."""
    import jax
    import numpy as np

    from ..models import get_strategy

    strat = get_strategy(cfg.strategy)
    dtype = np.dtype(AUDIT_DTYPE)
    sh_a, sh_x = strat.shardings(mesh)
    if donate is None:
        # Resolved at call time so (a) the donation mutation test can
        # patch the module override, and (b) the default is literally
        # the engine's own constant, never a copy that could drift.
        donate = ENGINE_DONATE_ARGNUMS
        if donate is None:
            from ..engine.executables import DONATE_ARGNUMS

            donate = DONATE_ARGNUMS

    def builder():
        kwargs: dict = {
            "combine": cfg.combine,
            "kernel": kernel if kernel is not None else cfg.kernel,
        }
        if cfg.stages is not None:
            kwargs["stages"] = cfg.stages
        if cfg.storage != "native":
            from ..ops.quantize import quantized_like, quantized_struct

            kwargs["dtype_storage"] = cfg.storage
            a = quantized_like(
                quantized_struct(
                    AUDIT_M, AUDIT_K, cfg.storage, dtype,
                    audit_block(cfg, mesh),
                ),
                lambda leaf: jax.ShapeDtypeStruct(
                    leaf.shape, leaf.dtype, sharding=sh_a
                ),
            )
        else:
            a = jax.ShapeDtypeStruct((AUDIT_M, AUDIT_K), dtype, sharding=sh_a)
        fn = strat.build(mesh, **kwargs)
        x = jax.ShapeDtypeStruct((AUDIT_K,), dtype, sharding=sh_x)
        return fn, (a, x), donate

    return builder


def lower_engine_artifact(cfg: AuditConfig, mesh, kernel=None,
                          donate: tuple[int, ...] | None = None):
    """One audited config lowered EXACTLY as the engine's executable
    cache lowers it (``lower_artifact`` — the shared accessor, so the
    memory audit and ``ExecutableCache.fingerprint`` inspect the same
    artifact)."""
    from ..engine.executables import lower_artifact

    return lower_artifact(engine_builder(cfg, mesh, kernel, donate))


def donation_state(lowered) -> str:
    """How the RHS donation lowered: ``"aliased"`` (shape-matched
    input-output aliasing, ``tf.aliasing_output``), ``"donated"``
    (``jax.buffer_donor`` — the donation is recorded and the compiler
    picks the reuse), or ``"none"`` — the state the engine and the HBM
    accountant silently assume never happens. Read off the LAST ``@main``
    argument's attributes — the RHS by the engine's calling convention —
    not the whole module: a donation recorded on the wrong argument
    (donating the resident A, which XLA must never clobber) reads as
    ``"none"``, exactly as it should."""
    main = _main_func(lowered.compiler_ir(dialect="stablehlo"))
    if main is None:
        return "none"
    try:
        arg_attrs = list(main.attributes["arg_attrs"])
    except KeyError:
        return "none"  # no per-arg attributes at all
    if not arg_attrs:
        return "none"
    rhs = str(arg_attrs[-1])
    if "tf.aliasing_output" in rhs:
        return "aliased"
    if "jax.buffer_donor" in rhs:
        return "donated"
    return "none"


def _type_bytes(mlir_type) -> int:
    return _tensor_bytes(str(mlir_type))


def peak_buffer_bytes(lowered, devices: int = AUDIT_DEVICES) -> int:
    """Static PER-DEVICE peak-liveness estimate over the lowered
    StableHLO: walk the module in its printed (linear) schedule — block
    arguments live from entry to their last use, op results from
    creation to last use, ``func.call`` and nested regions (scan/while
    bodies) contributing their callee/body peak at the issuing op.

    Units are per-device HBM bytes: jit-level (global-shaped) tensors
    count ``1/devices`` of their bytes (the sharded view each device
    holds; small replicated operands are deliberately under-counted at
    the same rate), while everything inside a ``shmap_body`` manual
    region — where shapes are already per-shard — counts in full. One
    consistent unit is what lets a per-shard dequantized temporary
    register against the sharded payload instead of drowning under
    global-shaped bookkeeping. An ESTIMATE of the allocator high-water
    mark XLA's real (reordering, aliasing) schedule refines — pinned in
    the golden table as a drift detector and gated for the quantized
    configs (:data:`PEAK_LIVENESS_CEILING`)."""
    module = lowered.compiler_ir(dialect="stablehlo")
    funcs: dict[str, object] = {}
    for op in module.body.operations:
        if op.operation.name == "func.func":
            funcs[_func_name(op)] = op

    func_peaks: dict[tuple, float] = {}

    def func_peak(name: str, scale: float, stack: tuple = ()) -> float:
        key = (name, scale)
        if key in func_peaks:
            return func_peaks[key]
        if name not in funcs or name in stack:
            return 0.0  # unknown callee / recursion guard
        peak = max(
            (block_peak(blk, scale, stack + (name,))
             for blk in funcs[name].regions[0].blocks),
            default=0.0,
        )
        func_peaks[key] = peak
        return peak

    def block_peak(block, scale: float, stack: tuple) -> float:
        ops = list(block.operations)
        last_use: list[tuple] = []  # (value, op index) — linear map; see below

        def find(v):
            for j, (u, idx) in enumerate(last_use):
                if u == v:
                    return j
            return None

        for i, op in enumerate(ops):
            for v in op.operands:
                j = find(v)
                if j is None:
                    last_use.append((v, i))
                else:
                    last_use[j] = (v, i)
        alive: list[tuple] = []  # (value, bytes)
        current = 0.0
        for arg in block.arguments:
            b = _type_bytes(arg.type) * scale
            alive.append((arg, b))
            current += b
        peak = current
        for i, op in enumerate(ops):
            nested = 0.0
            name = op.operation.name
            if name == "func.call":
                callee = str(op.attributes["callee"]).lstrip("@").strip('"')
                # Entering a manual (shard_map body) region: shapes
                # below are per-shard already — full-unit accounting.
                callee_scale = (
                    1.0 if callee.startswith("shmap_body") else scale
                )
                nested = func_peak(callee, callee_scale, stack)
            else:
                for region in op.regions:
                    for blk in region.blocks:
                        nested = max(nested, block_peak(blk, scale, stack))
            created = [(r, _type_bytes(r.type) * scale) for r in op.results]
            alive.extend(created)
            current += sum(b for _, b in created)
            peak = max(peak, current + nested)
            # Release everything whose last use is behind us (results
            # with no use die immediately — transient, already peaked).
            survivors = []
            for v, b in alive:
                j = find(v)
                dead = (j is None) if v in [r for r, _ in created] else (
                    j is not None and last_use[j][1] <= i
                )
                if dead:
                    current -= b
                else:
                    survivors.append((v, b))
            alive = survivors
        return peak

    return int(round(func_peak("main", 1.0 / max(1, devices))))


def memory_entry(cfg: AuditConfig, mesh, kernel=None,
                 donate: tuple[int, ...] | None = None) -> dict:
    """The compiled-artifact memory facts for one config: donation state
    and the static peak-liveness estimate, off the engine-recipe
    lowering. ``peak_bytes_ratio`` normalizes by the native
    (m · k · itemsize) stream, like ``a_bytes_ratio``."""
    lowered = lower_engine_artifact(cfg, mesh, kernel, donate)
    peak = peak_buffer_bytes(lowered)
    # Per-device units throughout: the ratio normalizes by the native
    # resident-A stream's per-device share.
    native_bytes = AUDIT_M * AUDIT_K * _ITEMSIZE[AUDIT_DTYPE] / AUDIT_DEVICES
    return {
        "donation": donation_state(lowered),
        "peak_bytes": peak,
        "peak_bytes_ratio": round(peak / native_bytes, 6),
    }


def native_counterpart(cfg: AuditConfig) -> AuditConfig:
    """The same schedule under native storage — the baseline the
    quantized peak-liveness ceiling compares against."""
    return AuditConfig(cfg.strategy, cfg.combine, cfg.stages, cfg.kernel)


def memory_findings(cfg: AuditConfig, entry: dict,
                    native_peak: int | None) -> list[Finding]:
    """The memory audit's gates for one config's :func:`memory_entry`:
    donation must have lowered, and a quantized config's static peak
    must respect its storage ceiling against the native counterpart's
    peak (the liveness-level version of the ``a_bytes`` pin — a
    lowering that materializes a dequantized full-width temporary blows
    straight through it)."""
    findings: list[Finding] = []
    if entry["donation"] == "none":
        findings.append(Finding(
            f"<hlo:{cfg.key}>", 0, "hlo-donation",
            "the RHS argument of the compiled artifact carries no "
            "donation (neither tf.aliasing_output nor jax.buffer_donor): "
            "the engine dispatch path dropped donate_argnums, so every "
            "request churns a fresh padded-RHS allocation the HBM "
            "accountant assumes is reused (engine/executables.py)",
        ))
    ceiling = PEAK_LIVENESS_CEILING.get(cfg.storage)
    if ceiling is not None and native_peak:
        if entry["peak_bytes"] > ceiling * native_peak:
            findings.append(Finding(
                f"<hlo:{cfg.key}>", 0, "hlo-peak-liveness",
                f"static peak liveness {entry['peak_bytes']} bytes is "
                f"{entry['peak_bytes'] / native_peak:.3f}x the native "
                f"counterpart's {native_peak}, over the {cfg.storage} "
                f"ceiling of {ceiling}x — the lowering materializes "
                "full-width temporaries (early dequant?) and moves the "
                "bytes the storage format exists not to move",
            ))
    return findings


def _local_a_shape(cfg: AuditConfig, mesh) -> tuple[int, int]:
    """The per-device shard shape of A for one strategy on the audit mesh
    (the shape a full-shard dequantizing convert would produce)."""
    from ..models import get_strategy

    strat = get_strategy(cfg.strategy)
    spec_a = strat.specs(mesh)[0]

    def axis_devices(entry) -> int:
        if entry is None:
            return 1
        names = (entry,) if isinstance(entry, str) else tuple(entry)
        n = 1
        for name in names:
            n *= mesh.shape[name]
        return n

    return (
        AUDIT_M // axis_devices(spec_a[0]),
        AUDIT_K // axis_devices(spec_a[1] if len(spec_a) > 1 else None),
    )


def early_dequant_findings(
    cfg: AuditConfig, lowered, mesh
) -> list[Finding]:
    """The early-dequant census gate: a quantized config's lowering must
    not contain a ``stablehlo.convert`` whose low-bit operand is a
    full-width A — the global (m, k) or the per-device shard shape.
    The sanctioned kernel upcasts (m, block) tiles (block strictly
    smaller than the local width — ``ops.quantize.default_block``), so
    any full-shard convert means the program dequantized A before the
    contraction and moves full-width float bytes while claiming the
    payload's."""
    if cfg.storage == "native":
        return []
    local = _local_a_shape(cfg, mesh)
    full_shapes = {(AUDIT_M, AUDIT_K), local}
    findings: list[Finding] = []

    def walk(op):
        for region in op.regions:
            for block in region.blocks:
                for child in block.operations:
                    name = child.operation.name
                    if name == "stablehlo.convert":
                        operand = str(child.operands[0].type)
                        result = str(child.results[0].type)
                        om = _TENSOR_RE.match(operand)
                        rm = _TENSOR_RE.match(result)
                        if om and rm:
                            odims, oelem = om.groups()
                            _, relem = rm.groups()
                            lowbit = (
                                oelem in _LOWBIT_ELEMS
                                or oelem.startswith("f8")
                            )
                            shape = tuple(
                                int(d) for d in (odims or "").split("x") if d
                            )
                            if (
                                lowbit
                                and relem in _FLOAT_ELEMS
                                and shape in full_shapes
                            ):
                                findings.append(Finding(
                                    f"<hlo:{cfg.key}>", 0,
                                    "hlo-early-dequant",
                                    f"lowering converts a full-width "
                                    f"{operand} A shard to {result} before "
                                    "the contraction: the quantized config "
                                    "stores the payload's bytes but MOVES "
                                    "full-width float bytes (upcast per "
                                    "(m, block) tile instead — "
                                    "ops/quantize.py, docs/QUANTIZATION.md)",
                                ))
                    walk(child.operation)

    walk(lowered.compiler_ir(dialect="stablehlo").operation)
    return findings


def dtype_itemsize(dtype: str) -> int:
    """Bytes per element for the census dtype names (the same table the
    byte accounting uses) — shared with the cost model so both sides size
    payloads identically."""
    return _ITEMSIZE[dtype]


def storage_bytes_ratio(
    storage: str, itemsize: int, block: int = 128
) -> float:
    """Structural resident-A byte ratio of a storage format against the
    native ``itemsize``-per-element stream: one payload byte plus one fp32
    scale per ``block``-element group (docs/QUANTIZATION.md derives it),
    doubled for the compensated pair. This is the symbolic face of the
    audit's artifact-read ``a_bytes_ratio`` — the two agree on the
    committed golden table within rounding (pinned in
    tests/test_cost_model.py), and the analytic cost model
    (``tuning/cost_model.py``) sizes quantized residencies from it."""
    if storage == "native":
        return 1.0
    if storage not in ("int8", "int8c", "fp8"):
        raise KeyError(f"no storage byte formula for {storage!r}")
    per_elem = 1.0 + 4.0 / block
    if storage == "int8c":
        per_elem *= 2.0
    return per_elem / itemsize


def schedule_formula(
    strategy: str,
    combine: str,
    stages: int | None,
    *,
    m: int,
    p: int,
    r: int,
    itemsize: int,
) -> tuple[dict[str, int], dict[str, int]]:
    """The per-config collective census and per-device payload bytes as a
    SYMBOLIC function of the operand and mesh — ``(census, payload_bytes)``
    keyed by collective kind.

    This is the single source of truth for what each schedule issues:
    :func:`expected_schedule` evaluates it at the audit operand to pin the
    golden table, and the analytic cost model
    (``tuning/cost_model.py``) evaluates it over arbitrary (m, p, dtype)
    to predict combine crossovers — so a formula perturbation reddens both
    (the mutation test in tests/test_cost_model.py). Payloads are the
    operand bytes each op presents per device (the census's accounting);
    the wire factor — e.g. 2(p−1)/p for a ring all-reduce — is the cost
    model's to apply, not the schedule's. An ``overlap@S`` entry is by
    construction S chunked collectives at 1/S of the un-staged bytes
    (same total — the staging invariant the audit enforces).

    ``r`` is the blockwise grid's row count (``mesh_grid_shape``); the 1-D
    strategies ignore it. Raises ``KeyError`` for a (strategy, combine)
    pair no formula covers."""
    s = stages or 1

    def entry(**kinds: tuple[int, int]):
        # each kind: (op count, elements per op)
        census = {k: n for k, (n, _) in kinds.items()}
        payload = {k: n * e * itemsize for k, (n, e) in kinds.items()}
        return census, payload

    strat, comb = strategy, combine
    if strat in ("rowwise", "colwise"):
        if comb == "gather":
            # with_sharding_constraint: GSPMD's all-gather, invisible to
            # the StableHLO census (module docstring).
            return entry()
        if comb == "psum":
            return entry(**{"all-reduce": (1, m)})
        if comb == "psum_scatter":
            return entry(**{"reduce-scatter": (1, m)})
        if comb in ("ring", "ring_overlap"):
            # p−1 neighbor hops, each moving one m/p accumulator chunk.
            return entry(**{"collective-permute": (p - 1, m // p)})
        if comb == "a2a":
            return entry(**{"all-to-all": (1, m)})
        if comb == "overlap" and strat == "colwise":
            # S chunked reduce-scatters, m/S rows each.
            return entry(**{"reduce-scatter": (s, m // s)})
        if comb == "overlap" and strat == "rowwise":
            # S chunked ring all-gathers: (p−1) hops of m/(p·S) rows each.
            return entry(**{"collective-permute": (s * (p - 1), m // (p * s))})
        if comb == "overlap_ring":
            # S staged ring reduce-scatters: each stage's m/S-row partial
            # rides p−1 hops of m/(p·S)-row accumulator chunks.
            return entry(**{"collective-permute": (s * (p - 1), m // (p * s))})
    if strat == "blockwise":
        if comb == "gather":
            # The in-body reduce-over-grid-columns; the final gather over
            # 'rows' is GSPMD's (as above).
            return entry(**{"all-reduce": (1, m // r)})
        if comb == "ring":
            return entry(**{
                "all-reduce": (1, m // r),
                "collective-permute": (r - 1, m // r),
            })
        if comb == "overlap":
            # Per stage: one chunked psum over grid cols + (r−1) chunked
            # ring-gather hops over grid rows, m/(r·S) rows each.
            return entry(**{
                "all-reduce": (s, m // (r * s)),
                "collective-permute": (s * (r - 1), m // (r * s)),
            })
    staged = f"@{stages}" if stages is not None else ""
    raise KeyError(
        f"no schedule formula for {strategy}|{combine}{staged}"
    )


def expected_schedule(
    cfg: AuditConfig, mesh
) -> tuple[dict[str, int], dict[str, int]]:
    """The structural formula evaluated at the audit operand: what each
    audited config must issue, derived from the mesh (p devices, (r, c)
    grid) — the second, golden-independent pin on the census. Thin
    adapter over :func:`schedule_formula` (the symbolic single source of
    truth the cost model shares)."""
    from ..parallel.mesh import mesh_grid_shape

    p = int(mesh.devices.size)
    r, _c = mesh_grid_shape(mesh)
    return schedule_formula(
        cfg.strategy, cfg.combine, cfg.stages,
        m=AUDIT_M, p=p, r=r, itemsize=_ITEMSIZE[AUDIT_DTYPE],
    )


# ---------------------------------------------------------- reshard audit
#
# The online-resharding layer (parallel/reshard.py; docs/RESHARDING.md):
# migrating a resident A between two strategies must lower to the MINIMAL
# collective program — all_to_all over the right axis (plus the grid
# transpose ppermute for the colwise↔blockwise pair), every device moving
# exactly its 1/p local shard per step. The structural formula below is
# the single symbolic source of truth the cost model's predict_reshard
# shares (the same late-import seam as schedule_formula), so a formula
# perturbation reddens the audit and the migration trigger together. A
# gather/reduce kind in the lowering is the on-device signature of a
# host-round-trip migration (the full operand materialized somewhere);
# any count or payload drift from the formula is a redundant — or
# missing — collective. Both turn hlo-reshard-schedule red
# (mutation-tested via parallel.reshard._MUTATION).


class ReshardAuditConfig(NamedTuple):
    """One audited migration: a (src, dst) strategy pair."""

    src: str
    dst: str

    @property
    def key(self) -> str:
        return f"reshard|{self.src}|{self.dst}"


RESHARD_AUDIT_CONFIGS = tuple(
    ReshardAuditConfig(src, dst)
    for src in ("rowwise", "colwise", "blockwise")
    for dst in ("rowwise", "colwise", "blockwise")
    if src != dst
)


def reshard_formula(
    src: str, dst: str, *, m: int, k: int, p: int, r: int, c: int,
    itemsize: int,
) -> tuple[dict[str, int], dict[str, int]]:
    """The (src, dst) migration's collective census and per-device
    payload bytes as a SYMBOLIC function of the operand and mesh —
    ``(census, payload_bytes)`` keyed by collective kind. Every step of
    every program presents exactly the device's 1/p local shard (the
    constant-footprint invariant), so payload = count × (m·k·itemsize)/p
    per kind. Evaluated by :func:`expected_reshard` at the audit operand
    and by ``tuning.cost_model.CostModel.predict_reshard`` over arbitrary
    shapes (the wire factor — (g−1)/g per all_to_all group — is the cost
    model's to apply, not the schedule's)."""
    from ..parallel.reshard import reshard_program

    shard_bytes = (m * k * itemsize) // p
    census: dict[str, int] = {}
    for step in reshard_program(src, dst, r, c):
        kind = "all-to-all" if step[0] == "a2a" else "collective-permute"
        census[kind] = census.get(kind, 0) + 1
    payload = {kind: n * shard_bytes for kind, n in census.items()}
    return census, payload


def expected_reshard(
    rcfg: ReshardAuditConfig, mesh
) -> tuple[dict[str, int], dict[str, int]]:
    """The structural formula evaluated at the audit operand — the
    golden-independent pin on each migration's census."""
    from ..parallel.mesh import mesh_grid_shape

    p = int(mesh.devices.size)
    r, c = mesh_grid_shape(mesh)
    return reshard_formula(
        rcfg.src, rcfg.dst, m=AUDIT_M, k=AUDIT_K, p=p, r=r, c=c,
        itemsize=_ITEMSIZE[AUDIT_DTYPE],
    )


def lower_reshard_config(rcfg: ReshardAuditConfig, mesh):
    """Lower one (src, dst) migration against the src-sharded audit
    operand (trace-only — exactly the program ``MatvecEngine.reshard``
    dispatches for the payload leaves)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding

    from ..parallel.reshard import build_reshard, payload_spec

    struct = jax.ShapeDtypeStruct(
        (AUDIT_M, AUDIT_K), np.dtype(AUDIT_DTYPE),
        sharding=NamedSharding(mesh, payload_spec(rcfg.src)),
    )
    return build_reshard(mesh, rcfg.src, rcfg.dst).lower(struct)


def reshard_audit_entry(
    rcfg: ReshardAuditConfig, mesh, lowered=None
) -> dict:
    """Package one migration's observed schedule."""
    if lowered is None:
        lowered = lower_reshard_config(rcfg, mesh)
    census, payload = collective_census(lowered)
    return {
        "census": dict(sorted(census.items())),
        "payload_bytes": dict(sorted(payload.items())),
        "payload_total_bytes": sum(payload.values()),
    }


def reshard_findings(
    rcfg: ReshardAuditConfig, entry: dict, mesh
) -> list[Finding]:
    """The structural gates for one migration entry: no gather/reduce
    kind anywhere (a host-transfer-shaped lowering), and census + payload
    exactly the formula's minimal program (an extra OR missing collective
    is drift either way)."""
    findings: list[Finding] = []
    exp_census, exp_payload = expected_reshard(rcfg, mesh)
    census = entry["census"]
    gatherish = sorted(
        set(census) - {"all-to-all", "collective-permute"}
    )
    if gatherish:
        findings.append(Finding(
            f"<hlo:{rcfg.key}>", 0, "hlo-reshard-schedule",
            f"migration lowers {gatherish} — a gather/reduce kind "
            "materializes more than the 1/p local shard somewhere, the "
            "on-device signature of a host-round-trip migration; the "
            f"{rcfg.src}->{rcfg.dst} move must be the minimal "
            "all_to_all/ppermute program",
        ))
    elif census != dict(sorted(exp_census.items())):
        findings.append(Finding(
            f"<hlo:{rcfg.key}>", 0, "hlo-reshard-schedule",
            f"collective census {census} != structural expectation "
            f"{dict(sorted(exp_census.items()))} — a redundant (or "
            "missing) collective in the migration program",
        ))
    elif entry["payload_bytes"] != dict(sorted(exp_payload.items())):
        findings.append(Finding(
            f"<hlo:{rcfg.key}>", 0, "hlo-reshard-schedule",
            f"collective payload {entry['payload_bytes']} != structural "
            f"expectation {dict(sorted(exp_payload.items()))} — each "
            "migration step must move exactly the device's 1/p local "
            "shard",
        ))
    return findings


def lowering_fingerprint(lowered) -> str:
    return hashlib.sha256(lowered.as_text().encode()).hexdigest()


def exec_key(cfg: AuditConfig):
    """The engine-cache identity this config dispatches under — the
    fingerprint gate's subject (engine/executables.py records the same
    hash at compile time)."""
    from ..engine.executables import ExecKey

    combine = cfg.combine + (
        f"@{cfg.stages}" if cfg.stages is not None else ""
    )
    return ExecKey(
        op="matvec", strategy=cfg.strategy, kernel=cfg.kernel,
        combine=combine, bucket=1, dtype=AUDIT_DTYPE, storage=cfg.storage,
    )


def audit_entry(cfg: AuditConfig, mesh, lowered=None) -> dict:
    """Package one config's observed schedule (lowering it unless the
    caller already has the lowered artifact in hand). ``a_bytes`` is the
    resident-A parameter footprint read off the module's entry signature;
    ``a_bytes_ratio`` normalizes it by the native (m · k · itemsize)
    stream the format replaces."""
    if lowered is None:
        lowered = lower_config(cfg, mesh)
    census, payload = collective_census(lowered)
    a_bytes = a_operand_bytes(lowered)
    native_bytes = AUDIT_M * AUDIT_K * _ITEMSIZE[AUDIT_DTYPE]
    return {
        "census": dict(sorted(census.items())),
        "payload_bytes": dict(sorted(payload.items())),
        "payload_total_bytes": sum(payload.values()),
        "a_bytes": a_bytes,
        "a_bytes_ratio": round(a_bytes / native_bytes, 6),
    }


# ---------------------------------------------------------- solver audit
#
# The served-solver layer: each solvers/ops.py program is one compiled
# lax.while_loop/scan around the strategy matvec, so its WHOLE-PROGRAM
# collective census must use exactly the matvec counterpart's collective
# kinds (x0 = 0 means no pre-loop matvec; the verified-exit refreshes and
# the final true-residual check re-issue the same combine, so COUNTS can
# exceed the matvec's 1 — the golden pins them exactly, the structural
# gate checks the SET). A host-synced residual check would tear the loop
# out of the program (no stablehlo.while left — the while-count gate);
# an un-staged all-gather smuggled into the loop changes the kind set
# (the op-set gate); any count drift trips the golden pin.


def while_op_count(lowered) -> int:
    """Count of ``stablehlo.while`` ops in the lowered module — ≥ 1 is
    the solver audit's the-loop-stayed-on-device gate (``lax.scan`` and
    ``fori_loop`` lower to while too, so every solver op qualifies)."""
    count = 0

    def walk(op):
        nonlocal count
        for region in op.regions:
            for block in region.blocks:
                for child in block.operations:
                    if child.operation.name == "stablehlo.while":
                        count += 1
                    walk(child.operation)

    walk(lowered.compiler_ir(dialect="stablehlo").operation)
    return count


def lower_solver_config(scfg: SolverAuditConfig, mesh):
    """Build and lower one served solver against the square audit operand
    (trace-only), with the engine's uniform signature
    ``fn(a, b, rtol, maxiter, p0, p1)`` — dynamic knobs as scalar
    operands, exactly what ``MatvecEngine._solver_builder_for``
    compiles."""
    import jax
    import numpy as np

    from ..models import get_strategy
    from ..solvers import build_solver

    dtype = np.dtype(AUDIT_DTYPE)
    fn = build_solver(
        scfg.op, get_strategy(scfg.strategy), mesh,
        dtype=dtype, combine=scfg.combine,
    )
    n = SOLVER_AUDIT_N
    a = jax.ShapeDtypeStruct((n, n), dtype)
    b = jax.ShapeDtypeStruct((n,), dtype)
    f32 = jax.ShapeDtypeStruct((), np.float32)
    i32 = jax.ShapeDtypeStruct((), np.int32)
    return jax.jit(fn).lower(a, b, f32, i32, f32, f32)


def solver_audit_entry(scfg: SolverAuditConfig, mesh, lowered=None) -> dict:
    """One solver config's observed schedule: the whole-program collective
    census + payload bytes (at the SOLVER operand — not comparable to the
    matvec entries' bytes) and the ``stablehlo.while`` count."""
    if lowered is None:
        lowered = lower_solver_config(scfg, mesh)
    census, payload = collective_census(lowered)
    return {
        "census": dict(sorted(census.items())),
        "payload_bytes": dict(sorted(payload.items())),
        "while_ops": while_op_count(lowered),
    }


def solver_findings(
    scfg: SolverAuditConfig, entry: dict, mesh
) -> list[Finding]:
    """The structural (golden-independent) gates for one solver entry:
    collective-kind SET equality with the matvec counterpart, and at
    least one on-device loop."""
    findings: list[Finding] = []
    exp_census, _ = expected_schedule(scfg.matvec, mesh)
    if set(entry["census"]) != set(exp_census):
        findings.append(Finding(
            f"<hlo:{scfg.key}>", 0, "hlo-solver-schedule",
            f"solver program's collective kinds "
            f"{sorted(entry['census'])} != the "
            f"{scfg.matvec.key.rsplit('|', 1)[0]} matvec counterpart's "
            f"{sorted(exp_census)} — the loop body issues collectives "
            "the audited matvec schedule does not (an un-staged gather "
            "or a stray reduction inside the iteration)",
        ))
    if entry["while_ops"] < 1:
        findings.append(Finding(
            f"<hlo:{scfg.key}>", 0, "hlo-solver-loop",
            "solver program lowered with no stablehlo.while: the "
            "iteration left the device (a host-driven loop re-dispatching "
            "matvecs — k host round-trips per solve, and the "
            "compiles_steady == 0 / deadline story no longer covers the "
            "solve; solvers/ops.py compiles the loop)",
        ))
    return findings


# -------------------------------------------------- fused-solver audit
#
# The fused Pallas iteration tier (ops/pallas_solver.py; the tentpole of
# docs/SOLVERS.md "Fused iteration tier"): the whole CG/Chebyshev while
# body — local GEMV tile loop, combine, vector updates, residual
# reduction — must lower to exactly ONE pallas_call plus the strategy's
# S collective hops (S = 1 for the canonical gather/psum combines), and
# an int8c-resident fused solve must upcast per (bm, block) tile INSIDE
# the kernel, never a full shard outside it. StableHLO inlines the
# pallas_call boundary, so this layer audits the traced jaxpr instead —
# the representation where the kernel boundary is a first-class eqn.

# Jaxpr primitive names of the collective kinds a fused body could issue
# (the jaxpr-level spelling, distinct from the StableHLO _KINDS above).
_FUSED_COLLECTIVE_PRIMS = (
    "psum", "all_gather", "ppermute", "all_to_all", "psum_scatter",
    "reduce_scatter",
)

# What each canonical fused combine's while body must issue: one hop.
_FUSED_EXPECTED_CENSUS = {
    "gather": {"all_gather": 1},
    "psum": {"psum": 1},
}


def _sub_eqns(jaxpr, *, skip_pallas: bool = False):
    """Every eqn in ``jaxpr``, recursing into sub-jaxpr params (while and
    scan bodies, shard_map, cond branches — and pallas_call kernels,
    unless ``skip_pallas`` excludes the sanctioned kernel interior for
    the early-dequant walk)."""
    import jax.core as jcore

    def sub(v):
        if isinstance(v, jcore.ClosedJaxpr):
            yield from _sub_eqns(v.jaxpr, skip_pallas=skip_pallas)
        elif hasattr(v, "eqns"):
            yield from _sub_eqns(v, skip_pallas=skip_pallas)
        elif isinstance(v, (tuple, list)):
            for item in v:
                yield from sub(item)

    for eqn in jaxpr.eqns:
        if skip_pallas and eqn.primitive.name == "pallas_call":
            continue
        yield eqn
        for v in eqn.params.values():
            yield from sub(v)


def trace_fused_solver(fcfg: FusedSolverAuditConfig, mesh):
    """The closed jaxpr of one fused solve at the fused audit operand
    (trace-only — quantized cells trace against a
    ``quantized_struct`` layout, no data is quantized)."""
    import jax
    import numpy as np

    from ..models import get_strategy
    from ..solvers import build_solver

    n = FUSED_SOLVER_AUDIT_N
    dtype = np.dtype(AUDIT_DTYPE)
    strat = get_strategy(fcfg.strategy)
    if fcfg.storage == "native":
        a = jax.ShapeDtypeStruct((n, n), dtype)
        dtype_storage = None
    else:
        from ..ops.quantize import default_block, quantized_struct

        a = quantized_struct(
            n, n, fcfg.storage, dtype,
            default_block(n, strat.contraction_shards(mesh)),
        )
        dtype_storage = fcfg.storage
    fn = build_solver(
        fcfg.op, strat, mesh, dtype=dtype, kernel="pallas_fused",
        combine=fcfg.combine, dtype_storage=dtype_storage,
    )
    b = jax.ShapeDtypeStruct((n,), dtype)
    f32 = jax.ShapeDtypeStruct((), np.float32)
    i32 = jax.ShapeDtypeStruct((), np.int32)
    return jax.make_jaxpr(fn)(a, b, f32, i32, f32, f32)


def _lowbit_shard_converts(jaxpr, n: int, p: int) -> int:
    """Count of converts OUTSIDE any pallas_call that upcast a low-bit
    tensor of full-A width — global (n, n) or either 1-D strategy's
    local shard — to float: each one is a dequantized-A materialization
    the fused tier exists to make impossible. The sanctioned upcasts are
    (·, block)-tile-shaped (inside the kernel, or in the scan fallback's
    ``matvec_quantized``) and don't match."""
    full_shapes = {(n, n), (n // p, n), (n, n // p)}
    count = 0
    for eqn in _sub_eqns(jaxpr.jaxpr, skip_pallas=True):
        if eqn.primitive.name != "convert_element_type":
            continue
        iv = eqn.invars[0].aval
        ov = eqn.outvars[0].aval
        src = str(getattr(iv, "dtype", ""))
        dst = str(getattr(ov, "dtype", ""))
        lowbit = src.startswith(("int8", "uint8", "float8"))
        if lowbit and dst.startswith(("float", "bfloat"))                 and tuple(getattr(iv, "shape", ())) in full_shapes:
            count += 1
    return count


def fused_solver_audit_entry(
    fcfg: FusedSolverAuditConfig, mesh, jaxpr=None
) -> dict:
    """One fused config's observed iteration structure: the while count,
    the per-body pallas_call count, the per-body collective census, and
    the whole-program full-shard low-bit convert count (0 is the pin)."""
    if jaxpr is None:
        jaxpr = trace_fused_solver(fcfg, mesh)
    whiles = [
        e for e in _sub_eqns(jaxpr.jaxpr) if e.primitive.name == "while"
    ]
    body_prims: list[str] = []
    for w in whiles:
        body_prims.extend(
            e.primitive.name
            for e in _sub_eqns(w.params["body_jaxpr"].jaxpr)
        )
    census = {
        k: body_prims.count(k)
        for k in _FUSED_COLLECTIVE_PRIMS if k in body_prims
    }
    p = int(mesh.devices.size)
    return {
        "while_ops": len(whiles),
        "pallas_calls": body_prims.count("pallas_call"),
        "census": dict(sorted(census.items())),
        "lowbit_shard_converts": _lowbit_shard_converts(
            jaxpr, FUSED_SOLVER_AUDIT_N, p
        ),
    }


def fused_solver_findings(
    fcfg: FusedSolverAuditConfig, entry: dict
) -> list[Finding]:
    """The structural (golden-independent) gates for one fused entry."""
    findings: list[Finding] = []
    if entry["while_ops"] != 1:
        findings.append(Finding(
            f"<hlo:fused:{fcfg.key}>", 0, "hlo-fused-solver",
            f"fused solve traced {entry['while_ops']} while loops, "
            "expected exactly 1: the iteration either left the device or "
            "was unrolled/nested (ops/pallas_solver.py compiles ONE "
            "rotated while loop)",
        ))
    if entry["pallas_calls"] != 1:
        findings.append(Finding(
            f"<hlo:fused:{fcfg.key}>", 0, "hlo-fused-solver",
            f"fused iteration body contains {entry['pallas_calls']} "
            "pallas_call eqns, expected exactly 1 — the tier's whole "
            "claim is the entire recurrence (GEMV tiles + vector updates "
            "+ residual reduction) in ONE kernel so p/x/r never "
            "round-trip through HBM; an unfused body pays the XLA tier's "
            "per-iteration launches while reporting the fused ExecKey",
        ))
    expected = _FUSED_EXPECTED_CENSUS[fcfg.combine]
    if entry["census"] != expected:
        findings.append(Finding(
            f"<hlo:fused:{fcfg.key}>", 0, "hlo-fused-solver",
            f"fused iteration body's collective census {entry['census']} "
            f"!= the canonical {fcfg.combine} combine's {expected} — a "
            "stray collective inside the loop multiplies per-iteration "
            "latency by its launch cost",
        ))
    if fcfg.storage != "native" and entry["lowbit_shard_converts"]:
        findings.append(Finding(
            f"<hlo:fused:{fcfg.key}>", 0, "hlo-early-dequant",
            f"quantized fused solve materializes "
            f"{entry['lowbit_shard_converts']} full-shard dequantized A "
            "tensor(s) outside the kernel: the int8c-resident tier must "
            "upcast per (bm, block) tile inside the pallas_call "
            "(ops/pallas_solver.py; docs/QUANTIZATION.md)",
        ))
    return findings


# ----------------------------------------------------- speculative audit
#
# The speculative-dispatch layer (ops/speculative.py; the engine's
# submit(rtol=...) tier): the fused candidate + acceptance-check program
# must lower to the int8c counterpart's collective schedule plus AT MOST
# one extra reduction whose payload is the probe vector (s scalars) —
# never a full-width collective (which would spend the bandwidth the
# speculation exists to save) — and the accept/escalate decision must be
# a device predicate in the artifact's outputs, not a host round-trip
# inside the program (hlo-spec-host-sync). Rowwise contracts locally, so
# its check adds NO collective at all; the golden pins each cell exactly.


class SpecAuditConfig(NamedTuple):
    """One audited speculative lowering: the fused int8c candidate +
    acceptance check compiled for one strategy × combine
    (``ops.speculative.build_speculative`` — the program the engine's
    ``submit(rtol=...)`` path dispatches)."""

    strategy: str
    combine: str

    @property
    def key(self) -> str:
        return f"speculate|{self.strategy}|{self.combine}"

    @property
    def counterpart(self) -> AuditConfig:
        """The int8c matvec cell whose collective schedule the fused
        program must contain (storage is census-orthogonal, so the
        counterpart's EXPECTED schedule is the strategy × combine
        formula; the int8c framing matters for the byte story, not the
        census)."""
        return AuditConfig(self.strategy, self.combine, storage="int8c")


# One cell per strategy family, same combines as the solver audit:
# colwise's psum makes the one-extra-reduction gate bite (its counterpart
# census is non-empty), rowwise/blockwise gather pin the
# zero-extra-collective (rowwise) and sharded-contraction (blockwise)
# faces.
SPEC_AUDIT_CONFIGS: tuple[SpecAuditConfig, ...] = (
    SpecAuditConfig("rowwise", "gather"),
    SpecAuditConfig("colwise", "psum"),
    SpecAuditConfig("blockwise", "gather"),
)


def _audit_probes() -> int:
    """The probe count the engine arms with (its resident P/U are sized
    at the eligibility floor — engine/core.py's constructor makes the
    same call)."""
    from ..ops.speculative import SPEC_RTOL_FLOOR, probe_count

    return probe_count(SPEC_RTOL_FLOOR)


def lower_spec_config(scfg: SpecAuditConfig, mesh):
    """Build and lower one fused speculative program against the audit
    operand (trace-only), with the engine's operand signature
    ``fn(aq, p, u, x, rtol)`` — the quantized pytree, the precomputed
    projection/probe matrices, the request, and the DYNAMIC tolerance
    scalar (exactly what ``MatvecEngine._spec_builder_for_locked`` compiles)."""
    import jax
    import numpy as np

    from ..models import get_strategy
    from ..ops.quantize import quantized_struct
    from ..ops.speculative import build_speculative

    dtype = np.dtype(AUDIT_DTYPE)
    s = _audit_probes()
    fn = build_speculative(
        get_strategy(scfg.strategy), mesh, probes=s,
        combine=scfg.combine, storage="int8c",
    )
    aq = quantized_struct(
        AUDIT_M, AUDIT_K, "int8c", dtype, audit_block(scfg.counterpart, mesh)
    )
    p_struct = jax.ShapeDtypeStruct((s, AUDIT_K), dtype)
    u_struct = jax.ShapeDtypeStruct((s, AUDIT_M), dtype)
    x = jax.ShapeDtypeStruct((AUDIT_K,), dtype)
    rtol = jax.ShapeDtypeStruct((), np.float32)
    return jax.jit(fn).lower(aq, p_struct, u_struct, x, rtol)


def pred_output_count(lowered) -> int:
    """How many ``i1`` tensors the module's ``@main`` RETURNS — the
    hlo-spec-host-sync gate's subject. The accept predicate must leave
    the program as a device output (the engine reads it once, at
    ``MatvecFuture.result()`` — its contractual sync point); a lowering
    with no boolean result means the decision was resolved inside the
    trace, i.e. a host round-trip per request."""
    main = _main_func(lowered.compiler_ir(dialect="stablehlo"))
    if main is None:
        return 0
    ftype = str(main.attributes["function_type"])
    results = ftype.rsplit("->", 1)[-1]
    return results.count("tensor<i1>")


def spec_audit_entry(scfg: SpecAuditConfig, mesh, lowered=None) -> dict:
    """One speculative config's observed artifact: the whole-program
    collective census + payload bytes, the probe count it was built at,
    and the device-predicate output count."""
    if lowered is None:
        lowered = lower_spec_config(scfg, mesh)
    census, payload = collective_census(lowered)
    return {
        "census": dict(sorted(census.items())),
        "payload_bytes": dict(sorted(payload.items())),
        "probes": _audit_probes(),
        "pred_outputs": pred_output_count(lowered),
    }


def spec_findings(
    scfg: SpecAuditConfig, entry: dict, mesh
) -> list[Finding]:
    """The structural (golden-independent) gates for one speculative
    entry: the counterpart's schedule must survive intact, the check may
    add at most ONE reduction of probe-vector payload (never a
    full-width collective), and the escalate decision must be a device
    predicate output."""
    findings: list[Finding] = []
    exp_census, exp_payload = expected_schedule(scfg.counterpart, mesh)
    census = entry["census"]
    payload = entry["payload_bytes"]
    missing = {
        kind: n for kind, n in exp_census.items()
        if census.get(kind, 0) < n
    }
    extra = {
        kind: census[kind] - exp_census.get(kind, 0)
        for kind in census
        if census[kind] > exp_census.get(kind, 0)
    }
    if missing:
        findings.append(Finding(
            f"<hlo:{scfg.key}>", 0, "hlo-spec-schedule",
            f"fused speculative program lost collectives {missing} from "
            f"its {scfg.counterpart.key} counterpart's schedule "
            f"{dict(sorted(exp_census.items()))} — the candidate matvec "
            "no longer lowers the audited combine",
        ))
    if set(extra) - {"all-reduce"} or sum(extra.values()) > 1:
        findings.append(Finding(
            f"<hlo:{scfg.key}>", 0, "hlo-spec-schedule",
            f"acceptance check added {extra} beyond the "
            f"{scfg.counterpart.key} counterpart's schedule — the check "
            "must cost at most ONE extra reduction (the psum of s probe "
            "scalars; rowwise adds none)",
        ))
    # The one allowed extra reduction must move the probe vector, not a
    # full-width operand: s scalars at the serving itemsize.
    check_ceiling = entry["probes"] * _ITEMSIZE[AUDIT_DTYPE]
    extra_ar_bytes = (
        payload.get("all-reduce", 0) - exp_payload.get("all-reduce", 0)
    )
    if extra.get("all-reduce") and extra_ar_bytes > check_ceiling:
        findings.append(Finding(
            f"<hlo:{scfg.key}>", 0, "hlo-spec-schedule",
            f"the check's extra all-reduce moves {extra_ar_bytes} bytes, "
            f"over the {check_ceiling}-byte probe-vector ceiling "
            f"({entry['probes']} probes × {_ITEMSIZE[AUDIT_DTYPE]} B) — a "
            "full-width collective smuggled into the acceptance check "
            "spends the bandwidth the speculation exists to save",
        ))
    if entry["pred_outputs"] < 1:
        findings.append(Finding(
            f"<hlo:{scfg.key}>", 0, "hlo-spec-host-sync",
            "fused speculative program returns no i1 predicate: the "
            "accept/escalate decision was resolved inside the trace — a "
            "host round-trip per request — instead of riding to "
            "MatvecFuture.result() as a device output",
        ))
    return findings


def build_schedule_table(
    configs: Iterable[AuditConfig] | None = None,
    solver_configs: Iterable[SolverAuditConfig] | None = None,
    spec_configs: Iterable[SpecAuditConfig] | None = None,
    fused_solver_configs: Iterable[FusedSolverAuditConfig] | None = None,
    reshard_configs: Iterable[ReshardAuditConfig] | None = None,
) -> dict:
    """The full golden-table payload for the current tree: the schedule
    census (plain-struct lowering) merged with the compiled-artifact
    memory audit (engine-recipe lowering) per config, plus the served
    solver loops' census/while pins per strategy × op, plus the fused
    speculative programs' census/predicate pins per strategy family,
    plus the fused solver tier's jaxpr census pins per op × strategy ×
    storage, plus the online-reshard migration programs' census/payload
    pins per (src, dst) strategy pair (schema 7)."""
    import jax

    mesh = _audit_mesh()
    entries = {
        cfg.key: {**audit_entry(cfg, mesh), **memory_entry(cfg, mesh)}
        for cfg in _supported_configs(configs or AUDIT_CONFIGS)
    }
    solver_entries = {
        scfg.key: solver_audit_entry(scfg, mesh)
        for scfg in (
            SOLVER_AUDIT_CONFIGS if solver_configs is None
            else tuple(solver_configs)
        )
    }
    spec_entries = {
        scfg.key: spec_audit_entry(scfg, mesh)
        for scfg in (
            SPEC_AUDIT_CONFIGS if spec_configs is None
            else tuple(spec_configs)
        )
    }
    fused_entries = {
        fcfg.key: fused_solver_audit_entry(fcfg, mesh)
        for fcfg in (
            FUSED_SOLVER_AUDIT_CONFIGS if fused_solver_configs is None
            else tuple(fused_solver_configs)
        )
    }
    reshard_entries = {
        rcfg.key: reshard_audit_entry(rcfg, mesh)
        for rcfg in (
            RESHARD_AUDIT_CONFIGS if reshard_configs is None
            else tuple(reshard_configs)
        )
    }
    return {
        "schema": GOLDEN_SCHEMA,
        "mesh": {
            "devices": AUDIT_DEVICES,
            "grid": list(mesh.devices.shape),
        },
        "operand": {"m": AUDIT_M, "k": AUDIT_K, "dtype": AUDIT_DTYPE},
        "solver_operand": {"n": SOLVER_AUDIT_N, "dtype": AUDIT_DTYPE},
        "fused_solver_operand": {
            "n": FUSED_SOLVER_AUDIT_N, "dtype": AUDIT_DTYPE,
        },
        "jax_version_at_capture": jax.__version__,
        "configs": entries,
        "solvers": solver_entries,
        "speculative": spec_entries,
        "fused_solvers": fused_entries,
        "reshards": reshard_entries,
    }


def write_golden(root: Path | None = None, path: Path | None = None) -> Path:
    """Regenerate the committed golden schedule table — the bless step
    after a deliberate schedule change (docs/STATIC_ANALYSIS.md)."""
    root = Path(root) if root is not None else repo_root()
    path = Path(path) if path is not None else root / GOLDEN_REL
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(build_schedule_table(), indent=2) + "\n")
    return path


def run_hlo_audit(
    root: Path | None = None,
    golden_path: Path | None = None,
    configs: Iterable[AuditConfig] | None = None,
    check_fingerprints: bool = True,
    schedule: bool = True,
    memory: bool = True,
    solvers: bool | None = None,
    solver_configs: Iterable[SolverAuditConfig] | None = None,
    speculative: bool | None = None,
    spec_configs: Iterable[SpecAuditConfig] | None = None,
    fused_solvers: bool | None = None,
    fused_solver_configs: Iterable[FusedSolverAuditConfig] | None = None,
    reshards: bool | None = None,
    reshard_configs: Iterable[ReshardAuditConfig] | None = None,
) -> list[Finding]:
    """The full lowered-artifact audit: the collective-schedule layer
    (census + bytes vs formula and golden, the overlap chunking gate,
    fingerprint stability — ``schedule=True``), the compiled-artifact
    memory layer (donation → aliasing, peak liveness vs the quantized
    ceilings — ``memory=True``; the CLI's ``--memory-audit`` runs it
    alone), the served-solver layer (whole-program collective-kind
    set vs the matvec counterpart, the on-device while pin, golden count
    pins — ``solvers=True``), and the speculative-dispatch layer (fused
    check census vs the int8c counterpart + one probe-vector reduction,
    the hlo-spec-host-sync device-predicate pin — ``speculative=True``),
    and the fused solver tier's jaxpr census (exactly one pallas_call +
    S collective hops per while body, no full-shard dequant outside the
    kernel — ``fused_solvers=True``; gate hlo-fused-solver).
    All compare against the golden table over whichever fields they
    computed. Returns findings; empty means every config lowers as
    pinned."""
    root = Path(root) if root is not None else repo_root()
    golden_path = (
        Path(golden_path) if golden_path is not None else root / GOLDEN_REL
    )
    if solvers is None:
        # A narrowed matvec-config run (tests auditing one cell) should
        # not pay for 15 solver lowerings; full audits always include
        # them, as does an explicit solver_configs narrowing.
        solvers = configs is None or solver_configs is not None
    if speculative is None:
        # Same narrowing rule as the solver layer.
        speculative = configs is None or spec_configs is not None
    if fused_solvers is None:
        # Same narrowing rule again.
        fused_solvers = configs is None or fused_solver_configs is not None
    if reshards is None:
        # Same narrowing rule again (gate hlo-reshard-schedule).
        reshards = configs is None or reshard_configs is not None
    configs = _supported_configs(configs or AUDIT_CONFIGS)
    findings: list[Finding] = []

    golden_cfgs: dict = {}
    have_golden = golden_path.is_file()
    if have_golden:
        golden = json.loads(golden_path.read_text())
        if golden.get("schema") != GOLDEN_SCHEMA:
            findings.append(Finding(
                GOLDEN_REL, 0, "hlo-golden",
                f"golden schema {golden.get('schema')!r} != "
                f"{GOLDEN_SCHEMA}; regenerate with --write-golden",
            ))
        golden_cfgs = golden.get("configs", {})
    else:
        findings.append(Finding(
            GOLDEN_REL, 0, "hlo-golden",
            "golden collective-schedule table missing; generate it with "
            "`python -m matvec_mpi_multiplier_tpu.staticcheck "
            "--write-golden`",
        ))

    mesh = _audit_mesh()
    native_peaks: dict[str, int] = {}

    def native_peak_for(cfg: AuditConfig) -> int:
        base = native_counterpart(cfg)
        peak = native_peaks.get(base.key)
        if peak is None:
            peak = peak_buffer_bytes(lower_engine_artifact(base, mesh))
            native_peaks[base.key] = peak
        return peak

    for cfg in configs:
        observed: dict = {}
        overlap_hint = ""
        if cfg.stages is not None:
            overlap_hint = (
                f" — a staged overlap body must lower to S={cfg.stages} "
                "chunked collectives (1/S of the un-staged bytes each), "
                "never a full-width one"
            )
        if schedule:
            lowered = lower_config(cfg, mesh)
            observed.update(audit_entry(cfg, mesh, lowered))
            exp_census, exp_payload = expected_schedule(cfg, mesh)

            if observed["census"] != dict(sorted(exp_census.items())):
                findings.append(Finding(
                    f"<hlo:{cfg.key}>", 0, "hlo-schedule",
                    f"collective census {observed['census']} != structural "
                    f"expectation {dict(sorted(exp_census.items()))}"
                    f"{overlap_hint}",
                ))
            elif observed["payload_bytes"] != dict(sorted(exp_payload.items())):
                findings.append(Finding(
                    f"<hlo:{cfg.key}>", 0, "hlo-schedule",
                    f"collective payload {observed['payload_bytes']} != "
                    f"structural expectation "
                    f"{dict(sorted(exp_payload.items()))}{overlap_hint}",
                ))

            ceiling = STORAGE_BYTE_CEILING.get(cfg.storage)
            if ceiling is not None and observed["a_bytes_ratio"] > ceiling:
                findings.append(Finding(
                    f"<hlo:{cfg.key}>", 0, "hlo-storage-bytes",
                    f"resident-A parameter bytes are "
                    f"{observed['a_bytes_ratio']:.3f}x the native stream, "
                    f"over the {cfg.storage} ceiling of {ceiling}x — the "
                    "storage format is not actually shrinking the bytes it "
                    "exists to shrink",
                ))
            findings.extend(early_dequant_findings(cfg, lowered, mesh))

            if check_fingerprints:
                # The census pass's lowering doubles as the first sample;
                # one fresh rebuild probes determinism.
                fp_a = lowering_fingerprint(lowered)
                fp_b = lowering_fingerprint(lower_config(cfg, mesh))
                if fp_a != fp_b:
                    findings.append(Finding(
                        f"<hlo:{cfg.key}>", 0, "hlo-fingerprint",
                        f"two lowerings of ExecKey {exec_key(cfg)} hash "
                        f"differently ({fp_a[:12]} vs {fp_b[:12]}): the "
                        "engine's AOT cache would silently recompile (or "
                        "serve divergent programs) across restarts",
                    ))

        if memory:
            mem = memory_entry(cfg, mesh)
            observed.update(mem)
            if cfg.storage == "native":
                # The audited natives ARE the quantized cells' baselines
                # (the table orders natives first) — recording the peak
                # here saves native_peak_for a redundant lowering.
                native_peaks.setdefault(cfg.key, mem["peak_bytes"])
                native_peak = None
            else:
                native_peak = native_peak_for(cfg)
            findings.extend(memory_findings(cfg, mem, native_peak))

        if have_golden:
            # Empty/absent "configs" must read as every pin missing, not
            # as a clean audit — a truncated golden would otherwise turn
            # the whole pin layer off silently.
            pinned = golden_cfgs.get(cfg.key)
            if pinned is None:
                findings.append(Finding(
                    GOLDEN_REL, 0, "hlo-golden",
                    f"config {cfg.key} missing from the golden table; "
                    "bless it with --write-golden",
                ))
            else:
                # A full run (both layers) compares whole entries, so a
                # stale/extra golden field is drift; a partial run
                # (--memory-audit) compares only the fields it computed,
                # without re-lowering the other layer's.
                pinned_view = (
                    pinned if (schedule and memory)
                    else {k: pinned.get(k) for k in observed}
                )
                if pinned_view != observed:
                    findings.append(Finding(
                        GOLDEN_REL, 0, "hlo-census",
                        f"{cfg.key}: lowered artifact {observed} != golden "
                        f"{pinned_view}{overlap_hint}; if the change is "
                        "deliberate, bless it with --write-golden",
                    ))

    if solvers:
        golden_solvers = golden.get("solvers", {}) if have_golden else {}
        if solver_configs is None:
            # Coverage cross-check (default set only — a subset run is a
            # deliberate narrowing): every served op must be audited, so
            # a new SOLVER_OPS entry cannot ship unpinned.
            from ..solvers import SOLVER_OPS

            missing_ops = sorted(set(SOLVER_OPS) - set(_SOLVER_AUDIT_OPS))
            if missing_ops:
                findings.append(Finding(
                    "<hlo:solvers>", 0, "hlo-solver-coverage",
                    f"served solver ops {missing_ops} have no audit "
                    "configs; extend SOLVER_AUDIT_CONFIGS and re-bless "
                    "the golden table",
                ))
        for scfg in (
            SOLVER_AUDIT_CONFIGS if solver_configs is None
            else tuple(solver_configs)
        ):
            entry = solver_audit_entry(scfg, mesh)
            findings.extend(solver_findings(scfg, entry, mesh))
            if have_golden:
                pinned = golden_solvers.get(scfg.key)
                if pinned is None:
                    findings.append(Finding(
                        GOLDEN_REL, 0, "hlo-golden",
                        f"solver config {scfg.key} missing from the "
                        "golden table; bless it with --write-golden",
                    ))
                elif pinned != entry:
                    findings.append(Finding(
                        GOLDEN_REL, 0, "hlo-census",
                        f"{scfg.key}: lowered solver program {entry} != "
                        f"golden {pinned}; a collective-count or loop "
                        "change inside a served solver — if deliberate, "
                        "bless it with --write-golden",
                    ))
        if have_golden and solver_configs is None:
            audited_solvers = {scfg.key for scfg in SOLVER_AUDIT_CONFIGS}
            for stale in sorted(set(golden_solvers) - audited_solvers):
                findings.append(Finding(
                    GOLDEN_REL, 0, "hlo-golden",
                    f"golden table pins unknown solver config {stale}; "
                    "regenerate with --write-golden",
                ))

    if speculative:
        golden_spec = golden.get("speculative", {}) if have_golden else {}
        for scfg in (
            SPEC_AUDIT_CONFIGS if spec_configs is None
            else tuple(spec_configs)
        ):
            entry = spec_audit_entry(scfg, mesh)
            findings.extend(spec_findings(scfg, entry, mesh))
            if have_golden:
                pinned = golden_spec.get(scfg.key)
                if pinned is None:
                    findings.append(Finding(
                        GOLDEN_REL, 0, "hlo-golden",
                        f"speculative config {scfg.key} missing from the "
                        "golden table; bless it with --write-golden",
                    ))
                elif pinned != entry:
                    findings.append(Finding(
                        GOLDEN_REL, 0, "hlo-census",
                        f"{scfg.key}: lowered speculative program {entry} "
                        f"!= golden {pinned}; a census, probe-count or "
                        "predicate change inside the fused check — if "
                        "deliberate, bless it with --write-golden",
                    ))
        if have_golden and spec_configs is None:
            audited_spec = {scfg.key for scfg in SPEC_AUDIT_CONFIGS}
            for stale in sorted(set(golden_spec) - audited_spec):
                findings.append(Finding(
                    GOLDEN_REL, 0, "hlo-golden",
                    f"golden table pins unknown speculative config "
                    f"{stale}; regenerate with --write-golden",
                ))

    if fused_solvers:
        golden_fused = golden.get("fused_solvers", {}) if have_golden else {}
        for fcfg in (
            FUSED_SOLVER_AUDIT_CONFIGS if fused_solver_configs is None
            else tuple(fused_solver_configs)
        ):
            entry = fused_solver_audit_entry(fcfg, mesh)
            findings.extend(fused_solver_findings(fcfg, entry))
            if have_golden:
                pinned = golden_fused.get(fcfg.key)
                if pinned is None:
                    findings.append(Finding(
                        GOLDEN_REL, 0, "hlo-golden",
                        f"fused solver config {fcfg.key} missing from "
                        "the golden table; bless it with --write-golden",
                    ))
                elif pinned != entry:
                    findings.append(Finding(
                        GOLDEN_REL, 0, "hlo-census",
                        f"{fcfg.key}: traced fused solve {entry} != "
                        f"golden {pinned}; a kernel-count, collective or "
                        "dequant change inside the fused iteration — if "
                        "deliberate, bless it with --write-golden",
                    ))
        if have_golden and fused_solver_configs is None:
            audited_fused = {f.key for f in FUSED_SOLVER_AUDIT_CONFIGS}
            for stale in sorted(set(golden_fused) - audited_fused):
                findings.append(Finding(
                    GOLDEN_REL, 0, "hlo-golden",
                    f"golden table pins unknown fused solver config "
                    f"{stale}; regenerate with --write-golden",
                ))

    if reshards:
        golden_reshards = golden.get("reshards", {}) if have_golden else {}
        for rcfg in (
            RESHARD_AUDIT_CONFIGS if reshard_configs is None
            else tuple(reshard_configs)
        ):
            entry = reshard_audit_entry(rcfg, mesh)
            findings.extend(reshard_findings(rcfg, entry, mesh))
            if have_golden:
                pinned = golden_reshards.get(rcfg.key)
                if pinned is None:
                    findings.append(Finding(
                        GOLDEN_REL, 0, "hlo-golden",
                        f"reshard config {rcfg.key} missing from the "
                        "golden table; bless it with --write-golden",
                    ))
                elif pinned != entry:
                    findings.append(Finding(
                        GOLDEN_REL, 0, "hlo-census",
                        f"{rcfg.key}: lowered migration program {entry} "
                        f"!= golden {pinned}; a collective or payload "
                        "change in an online-reshard lowering — if "
                        "deliberate, bless it with --write-golden",
                    ))
        if have_golden and reshard_configs is None:
            audited_reshards = {r.key for r in RESHARD_AUDIT_CONFIGS}
            for stale in sorted(set(golden_reshards) - audited_reshards):
                findings.append(Finding(
                    GOLDEN_REL, 0, "hlo-golden",
                    f"golden table pins unknown reshard config {stale}; "
                    "regenerate with --write-golden",
                ))

    if have_golden:
        audited = {cfg.key for cfg in AUDIT_CONFIGS}
        for stale in sorted(set(golden_cfgs) - audited):
            findings.append(Finding(
                GOLDEN_REL, 0, "hlo-golden",
                f"golden table pins unknown config {stale}; regenerate "
                "with --write-golden",
            ))
    return dedup(findings)

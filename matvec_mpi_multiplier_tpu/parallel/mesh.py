"""Device-mesh construction: the TPU-native process-grid layer.

Reference analog: ``get_2_most_closest_multipliers`` (``src/utils.c:26-37``)
factors the MPI process count into the most-square 2-D grid ``(r, c)`` with
``r <= c`` by scanning down from ``floor(sqrt(n))``; the blockwise executable
then places rank ``k`` at grid cell ``(k / c, k % c)``
(``src/multiplier_blockwise.c:299-303``). Verified mapping: 1→1×1, 2→1×2,
4→2×2, 6→2×3, 8→2×4, 12→3×4, 24→4×6.

Here the same factorization builds a ``jax.sharding.Mesh`` whose axes carry the
named shardings for the three strategies. Subset meshes (fewer devices than
are physically present) support the reference's scaling sweeps
(``test.sh:5`` runs p ∈ {1,2,6,12,24} on a fixed machine).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..utils.constants import MESH_AXIS_COLS, MESH_AXIS_ROWS
from ..utils.errors import ConfigError


def most_square_factors(n: int) -> tuple[int, int]:
    """Factor ``n`` into ``(r, c)`` with ``r <= c`` and ``r*c == n``, maximally square.

    Exact semantics of ``get_2_most_closest_multipliers`` (``src/utils.c:26-37``):
    scan ``r`` downward from ``floor(sqrt(n))`` until ``n % r == 0``.
    """
    if n <= 0:
        raise ConfigError(f"device count must be positive, got {n}")
    r = int(math.isqrt(n))
    while n % r != 0:
        r -= 1
    return r, n // r


def make_mesh(
    n_devices: int | None = None,
    *,
    shape: tuple[int, int] | None = None,
    axis_names: Sequence[str] = (MESH_AXIS_ROWS, MESH_AXIS_COLS),
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a 2-D device mesh over the first ``n_devices`` devices.

    * ``shape=(r, c)`` pins the grid explicitly; otherwise the most-square
      factorization of ``n_devices`` is used (reference ``src/utils.c:26-37``).
    * 1-D strategies (rowwise/colwise) use the same 2-D mesh with one axis of
      size 1 collapsed away by the strategy's PartitionSpec, so a single mesh
      serves all three strategies.
    * ``devices`` overrides the device list (used for subset meshes in scaling
      sweeps, the analog of ``mpiexec -n p`` with varying p, ``test.sh:11``).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = math.prod(shape) if shape is not None else len(devices)
    if n_devices > len(devices):
        raise ConfigError(
            f"requested {n_devices} devices but only {len(devices)} available"
        )
    if shape is None:
        shape = most_square_factors(n_devices)
    r, c = shape
    if r * c != n_devices:
        raise ConfigError(f"mesh shape {shape} does not cover {n_devices} devices")
    device_grid = np.asarray(devices[:n_devices]).reshape(r, c)
    return Mesh(device_grid, axis_names=tuple(axis_names))


def make_1d_mesh(
    n_devices: int | None = None,
    *,
    axis_name: str = MESH_AXIS_ROWS,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """A flat 1-D mesh, the analog of the reference's flat MPI_COMM_WORLD
    used by rowwise/colwise (``src/multiplier_rowwise.c:68-69``)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices > len(devices):
        raise ConfigError(
            f"requested {n_devices} devices but only {len(devices)} available"
        )
    return Mesh(np.asarray(devices[:n_devices]), axis_names=(axis_name,))


def mesh_grid_shape(mesh: Mesh) -> tuple[int, int]:
    """Return the (rows, cols) grid shape of a 1-D or 2-D mesh."""
    if len(mesh.axis_names) == 1:
        return 1, mesh.devices.size
    shape = mesh.devices.shape
    return shape[0], shape[1]

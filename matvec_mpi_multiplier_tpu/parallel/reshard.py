"""On-device resharding: migrate a resident operand between strategies.

GSPMD's view of resharding is a collective program: a layout change is a
redistribution of the same bytes across the same devices, so the minimal
migration between two of our partitionings is a short ``all_to_all`` /
``ppermute`` sequence — never a host gather. Each device holds exactly
1/p of ``A`` before, during, and after every step (the constant-footprint
invariant the ``hlo-reshard-schedule`` audit pins), so migrating an
``m x k`` resident moves at most a handful of local-shard-sized payloads
over the interconnect instead of streaming the whole matrix through the
host and recompiling from scratch.

The per-pair programs, on an ``(r, c)`` mesh grid with ``p = r * c``
devices and the flat device order ``d = i * c + j``:

==========  ==========  ==================================================
src         dst         program
==========  ==========  ==================================================
rowwise     colwise     all_to_all over the flat axis (split 1, concat 0)
colwise     rowwise     all_to_all over the flat axis (split 0, concat 1)
rowwise     blockwise   all_to_all over 'cols' (split 1, concat 0)
blockwise   rowwise     all_to_all over 'cols' (split 0, concat 1)
colwise     blockwise   grid-transpose ppermute, then all_to_all over
                        'rows' (split 0, concat 1)
blockwise   colwise     all_to_all over 'rows' (split 1, concat 0), then
                        inverse grid-transpose ppermute
==========  ==========  ==================================================

:func:`reshard_program` is the single symbolic source of truth for these
step sequences: :func:`build_reshard` executes it, the staticcheck
audit's ``reshard_formula`` prices it (census + payload bytes), and the
cost model's ``predict_reshard`` consumes that same formula — so a
perturbation here reddens the audit and the migration trigger together.

The built callable maps an arbitrary pytree of identically-sharded
arrays, so a quantized resident's ``(q, scales)`` leaves ride the same
program as a native ``A`` — per-block scales migrate bitwise whenever
the block size (a pure function of ``k`` and the contraction shard
count) agrees between the two layouts, which the engine checks before
choosing device migration over host requantization.
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import PartitionSpec

from ..utils.compat import shard_map
from ..utils.constants import MESH_AXIS_COLS, MESH_AXIS_ROWS
from ..utils.errors import ConfigError
from .mesh import mesh_grid_shape

__all__ = [
    "RESHARD_STRATEGIES",
    "payload_spec",
    "reshard_program",
    "build_reshard",
    "validate_reshard",
]

#: The strategies the on-device migration covers, in canonical order.
RESHARD_STRATEGIES = ("rowwise", "colwise", "blockwise")

_FLAT = (MESH_AXIS_ROWS, MESH_AXIS_COLS)

# Audit mutation seam (tests/test_staticcheck.py): None runs the real
# program; "host" swaps in a gather-everything-then-slice lowering (the
# on-device stand-in for a host round-trip — a literal host transfer
# cannot appear in a lowered module, but the full-``A`` all-gather it
# would imply can, and that is what the audit catches); "redundant"
# appends a rotate/unrotate ppermute pair (correct result, two extra
# collective-permutes in the census). Either must turn
# ``hlo-reshard-schedule`` red.
_MUTATION: str | None = None


def payload_spec(strategy: str) -> PartitionSpec:
    """The ``PartitionSpec`` a strategy's resident ``A`` payload lives
    under — also the pytree-prefix spec every leaf of a quantized
    resident shares (q and scales shard identically along both axes)."""
    if strategy == "rowwise":
        return PartitionSpec(_FLAT, None)
    if strategy == "colwise":
        return PartitionSpec(None, _FLAT)
    if strategy == "blockwise":
        return PartitionSpec(MESH_AXIS_ROWS, MESH_AXIS_COLS)
    raise ConfigError(
        f"reshard covers {RESHARD_STRATEGIES}, got {strategy!r}"
    )


def _transpose_perm(r: int, c: int) -> list[tuple[int, int]]:
    # Flat-order grid transpose: device (i, j) sends to device (j, i) of
    # the transposed grid, i.e. flat d = i*c + j -> (d % r) * c + d // r
    # on the (r, c) grid read column-major.
    p = r * c
    return [(d, (d % r) * c + d // r) for d in range(p)]


def _transpose_inv_perm(r: int, c: int) -> list[tuple[int, int]]:
    p = r * c
    return [(e, (e % c) * r + e // c) for e in range(p)]


def reshard_program(
    src: str, dst: str, r: int, c: int
) -> tuple[tuple, ...]:
    """The effective step sequence migrating ``src`` -> ``dst`` on an
    ``(r, c)`` grid: ``("a2a", axis, split, concat)`` and
    ``("perm", which)`` tuples, with degenerate steps (size-1 collective
    groups, fixed-point permutes) already elided so the census formula
    and the built program agree on every mesh shape."""
    for name in (src, dst):
        if name not in RESHARD_STRATEGIES:
            raise ConfigError(
                f"reshard covers {RESHARD_STRATEGIES}, got {name!r}"
            )
    if src == dst:
        return ()
    programs = {
        ("rowwise", "colwise"): (("a2a", "flat", 1, 0),),
        ("colwise", "rowwise"): (("a2a", "flat", 0, 1),),
        ("rowwise", "blockwise"): (("a2a", "cols", 1, 0),),
        ("blockwise", "rowwise"): (("a2a", "cols", 0, 1),),
        ("colwise", "blockwise"): (("perm", "t"), ("a2a", "rows", 0, 1)),
        ("blockwise", "colwise"): (("a2a", "rows", 1, 0), ("perm", "t_inv")),
    }
    sizes = {"flat": r * c, "rows": r, "cols": c}
    steps = []
    for step in programs[(src, dst)]:
        if step[0] == "a2a" and sizes[step[1]] == 1:
            continue  # size-1 group: the all_to_all is an identity
        if step[0] == "perm":
            perm = (
                _transpose_perm(r, c)
                if step[1] == "t"
                else _transpose_inv_perm(r, c)
            )
            if all(a == b for a, b in perm):
                continue  # degenerate grid: the transpose is a no-op
        steps.append(step)
    return tuple(steps)


def validate_reshard(shape, mesh, *, what: str = "A") -> None:
    """Conservative divisibility gate: every migration step splits a
    local shard by a collective-group size, so requiring both global
    dims divisible by ``p`` is sufficient for every (src, dst) pair
    (the strategies' own constructors already enforce their per-layout
    constraints). Raises :class:`ConfigError` naming the offending
    operand so the engine can fall back to a host requantization for a
    scale leaf instead of tripping a cryptic XLA shape error."""
    p = int(mesh.devices.size)
    m, k = int(shape[0]), int(shape[1])
    if m % p or k % p:
        raise ConfigError(
            f"reshard needs both dims of {what} divisible by the device "
            f"count: shape=({m}, {k}), p={p}"
        )


def build_reshard(mesh, src: str, dst: str):
    """Build the jitted migration ``src`` -> ``dst`` on ``mesh``.

    Returns a compiled callable mapping a pytree of ``src``-sharded
    arrays (a bare ``A`` or a quantized resident's leaves — every leaf
    sharded by :func:`payload_spec`) to the same values ``dst``-sharded,
    as pure device collectives. ``src == dst`` builds an identity (the
    engine short-circuits earlier; this keeps the primitive total)."""
    r, c = mesh_grid_shape(mesh)
    steps = reshard_program(src, dst, r, c)
    axes = {
        "flat": _FLAT,
        "rows": MESH_AXIS_ROWS,
        "cols": MESH_AXIS_COLS,
    }
    mutation = _MUTATION

    def migrate_leaf(x):
        if mutation == "host":
            return _gather_and_slice(x, src, dst, r, c)
        for step in steps:
            if step[0] == "a2a":
                x = lax.all_to_all(
                    x,
                    axes[step[1]],
                    split_axis=step[2],
                    concat_axis=step[3],
                    tiled=True,
                )
            else:
                perm = (
                    _transpose_perm(r, c)
                    if step[1] == "t"
                    else _transpose_inv_perm(r, c)
                )
                x = lax.ppermute(x, _FLAT, perm)
        if mutation == "redundant":
            p = r * c
            x = lax.ppermute(x, _FLAT, [(d, (d + 1) % p) for d in range(p)])
            x = lax.ppermute(x, _FLAT, [(d, (d - 1) % p) for d in range(p)])
        return x

    def body(tree):
        return jax.tree_util.tree_map(migrate_leaf, tree)

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=payload_spec(src),
            out_specs=payload_spec(dst),
        )
    )


def _gather_and_slice(x, src: str, dst: str, r: int, c: int):
    # The seeded "host" mutation: materialize the full operand on every
    # device, then slice out this device's destination shard. Bitwise
    # the same result, but the census shows a full-``A`` all-gather —
    # exactly the payload signature a host round-trip would imply.
    if src == "rowwise":
        full = lax.all_gather(x, _FLAT, axis=0, tiled=True)
    elif src == "colwise":
        full = lax.all_gather(x, _FLAT, axis=1, tiled=True)
    else:
        full = lax.all_gather(x, MESH_AXIS_ROWS, axis=0, tiled=True)
        full = lax.all_gather(full, MESH_AXIS_COLS, axis=1, tiled=True)
    p = r * c
    i = lax.axis_index(MESH_AXIS_ROWS)
    j = lax.axis_index(MESH_AXIS_COLS)
    flat = i * c + j
    m, k = full.shape
    if dst == "rowwise":
        return lax.dynamic_slice_in_dim(full, flat * (m // p), m // p, axis=0)
    if dst == "colwise":
        return lax.dynamic_slice_in_dim(full, flat * (k // p), k // p, axis=1)
    return lax.dynamic_slice(
        full, (i * (m // r), j * (k // c)), (m // r, k // c)
    )

"""Ring collectives over mesh axes: explicit neighbor-ring reduce-scatter.

The reference's colwise strategy reduces full-length partial vectors through
the root in one blocking ``MPI_Reduce(MPI_SUM)`` (``src/multiplier_colwise.c:124``)
— a root-serialized combine. The TPU-idiomatic default is ``lax.psum_scatter``
(XLA schedules it over ICI). This module adds the *explicit* ring formulation
— the building block of ring attention / long-context sequence parallelism
(SURVEY.md §5.7): p−1 ``ppermute`` hops around the mesh-axis ring, each hop
moving one accumulated chunk to the right neighbor while the local chunk is
added — so each step's transfer rides a single ICI neighbor link and compute
(the add) overlaps the permute under XLA's async collective scheduling.

Semantics match ``lax.psum_scatter(..., tiled=True)`` exactly (tested against
it); the value is pedagogical + a scheduling alternative XLA sometimes can't
pick on its own.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array


def _ring_perm(p: int) -> list[tuple[int, int]]:
    """Right-neighbor ring permutation on a size-p axis."""
    return [(i, (i + 1) % p) for i in range(p)]


def ring_psum_scatter(x: Array, axis_name: str) -> Array:
    """Ring reduce-scatter of a length-n array over ``axis_name``.

    Must be called inside shard_map. Each device contributes a full-length
    partial ``x``; device ``i`` returns chunk ``i`` of the elementwise sum
    (length ``n // p``) — identical to
    ``lax.psum_scatter(x, axis_name, tiled=True)``.

    Requires ``n % p == 0`` (same constraint psum_scatter imposes tiled).
    """
    p = jax.lax.axis_size(axis_name)
    if p == 1:
        return x
    n = x.shape[0]
    if n % p != 0:
        raise ValueError(f"ring_psum_scatter: length {n} not divisible by {p}")
    chunks = x.reshape(p, n // p)
    idx = jax.lax.axis_index(axis_name)
    perm = _ring_perm(p)

    def chunk(i):
        return jnp.take(chunks, jnp.mod(i, p), axis=0)

    # Start with own chunk (idx-1); after step s the accumulator holds the
    # partial sum for chunk (idx-1-s), so after p-1 hops device idx ends with
    # chunk idx summed across all devices.
    acc = chunk(idx - 1)
    for s in range(1, p):
        acc = jax.lax.ppermute(acc, axis_name, perm)
        acc = acc + chunk(idx - 1 - s)
    return acc


def ring_all_gather(x: Array, axis_name: str) -> Array:
    """Ring all-gather: each device's chunk circulates p−1 hops; the result
    is the axis-ordered concatenation, identical to
    ``lax.all_gather(x, axis_name, tiled=True)``.

    The rowwise strategy's final gather (``MPI_Gather``,
    ``src/multiplier_rowwise.c:141``) expressed as neighbor traffic.

    Note: the result is replicated in *value*, but shard_map's vma checker
    cannot prove it (ppermute outputs stay marked axis-varying), so callers
    returning it through ``out_specs=P()`` must build their shard_map with
    ``check_vma=False``.
    """
    p = jax.lax.axis_size(axis_name)
    if p == 1:
        return x
    idx = jax.lax.axis_index(axis_name)
    perm = _ring_perm(p)
    n = x.shape[0]
    out = jnp.zeros((p, n), x.dtype)
    piece = x
    # After s hops, `piece` is the chunk originally owned by (idx - s).
    out = out.at[jnp.mod(idx, p)].set(piece)
    for s in range(1, p):
        piece = jax.lax.ppermute(piece, axis_name, perm)
        out = out.at[jnp.mod(idx - s, p)].set(piece)
    return out.reshape(p * n)

"""Ring collectives over mesh axes: explicit neighbor-ring reduce-scatter.

The reference's colwise strategy reduces full-length partial vectors through
the root in one blocking ``MPI_Reduce(MPI_SUM)`` (``src/multiplier_colwise.c:124``)
— a root-serialized combine. The TPU-idiomatic default is ``lax.psum_scatter``
(XLA schedules it over ICI). This module adds the *explicit* ring formulation
— the building block of ring attention / long-context sequence parallelism
(SURVEY.md §5.7): p−1 ``ppermute`` hops around the mesh-axis ring, each hop
moving one accumulated chunk to the right neighbor while the local chunk is
added — so each step's transfer rides a single ICI neighbor link and compute
(the add) overlaps the permute under XLA's async collective scheduling.

Semantics match ``lax.psum_scatter(..., tiled=True)`` exactly (tested against
it); the value is pedagogical + a scheduling alternative XLA sometimes can't
pick on its own.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array

from ..obs.annotations import named_span
from ..utils.compat import axis_size


def _ring_perm(p: int) -> list[tuple[int, int]]:
    """Right-neighbor ring permutation on a size-p axis."""
    return [(i, (i + 1) % p) for i in range(p)]


def _ring_reduce(chunk_fn, axis_name: str):
    """The shared ring-reduce walk: after step ``s`` the accumulator holds
    the partial sum for chunk ``idx - 1 - s``, so after ``p - 1`` hops device
    ``idx`` ends holding chunk ``idx`` summed across the whole ring.

    ``chunk_fn(i)`` produces this device's contribution to logical chunk
    ``i`` (``i`` is a traced, possibly negative index — implementations
    wrap with ``jnp.mod``). Callers handle ``p == 1`` themselves.
    """
    p = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = _ring_perm(p)
    acc = chunk_fn(idx - 1)
    for s in range(1, p):
        acc = jax.lax.ppermute(acc, axis_name, perm)
        acc = acc + chunk_fn(idx - 1 - s)
    return acc


def ring_psum_scatter(x: Array, axis_name: str) -> Array:
    """Ring reduce-scatter over ``axis_name``, chunking along axis 0.

    Must be called inside shard_map. Each device contributes a full partial
    ``x`` (any rank — a length-n vector for matvec, an (m, n) partial C for
    GEMM); device ``i`` returns chunk ``i`` of the elementwise sum (leading
    dim ``x.shape[0] // p``) — identical to
    ``lax.psum_scatter(x, axis_name, tiled=True)``.

    Requires ``x.shape[0] % p == 0`` (same constraint psum_scatter imposes
    tiled).
    """
    p = axis_size(axis_name)
    if p == 1:
        return x
    n = x.shape[0]
    if n % p != 0:
        raise ValueError(f"ring_psum_scatter: length {n} not divisible by {p}")
    chunks = x.reshape(p, n // p, *x.shape[1:])
    return _ring_reduce(
        lambda i: jnp.take(chunks, jnp.mod(i, p), axis=0), axis_name
    )


def ring_matvec(a_panel: Array, x_seg: Array, axis_name: str, kernel) -> Array:
    """Overlapped ring matvec: compute rides the ring with the accumulator.

    The ring-attention-style schedule (SURVEY.md §5.7): where
    :func:`ring_psum_scatter` first materializes the full-length local partial
    and then reduces it around the ring, this version never forms it — at
    each of the p steps the device computes only the ``(m/p, k/p)`` tile of
    its column panel that contributes to the chunk currently held by the
    accumulator, so each step's GEMV tile overlaps the previous step's
    single-neighbor ``ppermute`` hop under XLA's async collective scheduling.
    Per-step working set drops from O(m) to O(m/p).

    Must be called inside shard_map. ``a_panel`` is the device's ``(m, k/p)``
    column panel, ``x_seg`` its ``(k/p,)`` x segment; returns chunk ``i`` of
    ``y`` (length ``m/p``, the kernel's accumulator dtype) on device ``i`` —
    the same contract as
    ``ring_psum_scatter(kernel(a_panel, x_seg), axis_name)``.

    Requires ``m % p == 0``.
    """
    p = axis_size(axis_name)
    if p == 1:
        return kernel(a_panel, x_seg)
    m = a_panel.shape[0]
    if m % p != 0:
        raise ValueError(f"ring_matvec: {m} rows not divisible by {p}")
    chunk_rows = m // p

    def tile_gemv(i):
        # Rows of this panel contributing to output chunk i (traced index).
        start = jnp.mod(i, p) * chunk_rows
        tile = jax.lax.dynamic_slice_in_dim(a_panel, start, chunk_rows, axis=0)
        return kernel(tile, x_seg)

    return _ring_reduce(tile_gemv, axis_name)


def ring_matmul(a_panel: Array, b_seg: Array, axis_name: str, kernel) -> Array:
    """Overlapped ring matmul: :func:`ring_matvec` with a rank-2 RHS.

    The walk is rank-agnostic — at each step the device computes the
    ``(m/p, k/p) @ (k/p, n)`` tile feeding the C-row chunk currently held by
    the accumulator, so per-step MXU work overlaps the previous hop's
    ``ppermute``. Device ``i`` returns rows ``i`` of C (``(m/p, n)``,
    accumulator dtype) — the same contract as
    ``ring_psum_scatter(kernel(a_panel, b_seg), axis_name)``. This is the
    ring-SUMMA schedule, the GEMM face of the long-context primitive.
    """
    return ring_matvec(a_panel, b_seg, axis_name, kernel)


def a2a_psum_scatter(x: Array, axis_name: str) -> Array:
    """Reduce-scatter as ONE balanced all-to-all + local reduce — the
    Ulysses-style schedule, the third member of the combine family beside
    ``lax.psum_scatter`` (XLA-scheduled) and :func:`ring_psum_scatter`
    (p−1 neighbor hops). Each device splits its full partial into p leading
    chunks, ``lax.all_to_all`` delivers chunk j to device j across every
    link at once, and the local sum over the p received contributions
    yields this device's chunk. Rank-agnostic (vector partials for matvec,
    (m, n) partials for GEMM); same contract and constraint
    (``x.shape[0] % p == 0``) as :func:`ring_psum_scatter`.
    """
    p = axis_size(axis_name)
    if p == 1:
        return x
    n = x.shape[0]
    if n % p != 0:
        raise ValueError(f"a2a_psum_scatter: length {n} not divisible by {p}")
    chunks = x.reshape(p, n // p, *x.shape[1:])
    # After the exchange, leading index i holds device i's contribution to
    # THIS device's chunk; the local sum completes the reduce-scatter.
    recv = jax.lax.all_to_all(
        chunks, axis_name, split_axis=0, concat_axis=0, tiled=True
    )
    return recv.sum(axis=0)


def ring_all_gather(x: Array, axis_name: str) -> Array:
    """Ring all-gather: each device's chunk circulates p−1 hops; the result
    is the axis-ordered concatenation, identical to
    ``lax.all_gather(x, axis_name, tiled=True)``. Rank-agnostic: a length-n
    vector gathers to ``(p·n,)``, an ``(n, b)`` block to ``(p·n, b)`` —
    the batched bodies ride the same walk.

    The rowwise strategy's final gather (``MPI_Gather``,
    ``src/multiplier_rowwise.c:141``) expressed as neighbor traffic.
    Reachable from every sharded-output strategy via
    ``build(gather_output="ring")`` (``models/base.py``), which wraps it in
    its own gather-stage shard_map.

    Note: the result is replicated in *value*, but shard_map's vma checker
    cannot prove it (ppermute outputs stay marked axis-varying), so callers
    returning it through ``out_specs=P()`` must build their shard_map with
    ``check_vma=False`` — ``build`` scopes that to the gather stage only.
    """
    p = axis_size(axis_name)
    if p == 1:
        return x
    idx = jax.lax.axis_index(axis_name)
    perm = _ring_perm(p)
    n = x.shape[0]
    out = jnp.zeros((p,) + x.shape, x.dtype)
    piece = x
    # After s hops, `piece` is the chunk originally owned by (idx - s).
    out = out.at[jnp.mod(idx, p)].set(piece)
    for s in range(1, p):
        piece = jax.lax.ppermute(piece, axis_name, perm)
        out = out.at[jnp.mod(idx - s, p)].set(piece)
    return out.reshape((p * n,) + x.shape[1:])


# --------------------------------------------------------------- overlap
#
# The staged `overlap` schedule family: split the contraction into S stages
# and software-pipeline them, so stage s's partial-combine (a chunked
# psum_scatter or a double-buffered neighbor-ring walk) is already in
# flight while stage s+1's local partial GEMV computes. On a TPU this is
# the latency-hiding shape of large-scale linear algebra (arXiv:2112.09017):
# the ICI carries stage s while the MXU runs stage s+1, instead of the
# whole interconnect idling until the full local GEMV finishes. On the CPU
# test mesh the schedules are sequential but bit-equivalent in structure,
# so correctness is provable off-hardware.
#
# Stage layout: the device's output chunk (m/p rows for the scatter family,
# m_loc local rows for the gather family) is divided into S contiguous
# sub-chunks, and stage s covers sub-chunk s of EVERY device — so each
# stage's combine moves 1/S of the bytes the un-staged combine would, and
# concatenating the S per-stage results reassembles the contiguous chunk.
#
# Lint contract (scripts/tier1.sh, tests/test_lint.py): overlap schedule
# bodies in this module and ops/pallas_collective.py must never issue an
# un-chunked full-width collective — every collective here handles one
# stage's sub-chunk. Deliberate exceptions carry an `# overlap-ok: <reason>` marker. — stale-ok: syntax documentation, not an exemption


def stage_ladder(m: int, p: int, ladder=(8, 4, 2, 1)) -> list[int]:
    """Stage counts from ``ladder`` that evenly divide the per-device chunk
    ``m // p`` (largest first; ``1`` — the un-pipelined degenerate schedule
    — always qualifies when ``m % p == 0``). The autotuner measures exactly
    these; dispatch clamps a requested S down to the first valid entry."""
    if m % p != 0:
        return []
    chunk = m // p
    return [s for s in sorted(set(ladder), reverse=True) if chunk % s == 0]


def _pipeline_stages(compute, combine, stages: int) -> list:
    """The software pipeline shared by the staged schedules: issue stage
    s's combine BEFORE tracing stage s+1's compute, so in program order
    every collective sits between two independent compute steps — the
    window XLA's async collective scheduling overlaps on TPU. Returns the
    S combined pieces in stage order.

    Each stage's two halves carry named device-trace annotations
    (``stage{s}/compute`` / ``stage{s}/combine``, ``obs/annotations``):
    with ``--annotate`` a Perfetto capture shows the pipeline's interleaved
    structure by name instead of as an anonymous op soup — the only way a
    staged schedule's overlap is verifiable in a device trace."""

    def _compute(s):
        with named_span(f"stage{s}/compute"):
            return compute(s)

    def _combine(s, v):
        with named_span(f"stage{s}/combine"):
            return combine(v)

    pieces = []
    prev = _compute(0)
    for s in range(1, stages):
        in_flight = _combine(s - 1, prev)  # stage s-1's combine, issued...
        prev = _compute(s)                 # ...while stage s's GEMV computes
        pieces.append(in_flight)
    pieces.append(_combine(stages - 1, prev))
    return pieces


def staged_overlap_scatter(
    a_panel: Array,
    x_seg: Array,
    axis_name,
    kernel,
    stages: int,
    step: str = "psum_scatter",
) -> Array:
    """Pipelined colwise combine: S-stage local GEMV with each stage's
    chunked reduce-scatter overlapping the next stage's compute.

    Must be called inside shard_map. ``a_panel`` is the device's
    ``(m, k/p)`` column panel, ``x_seg`` its x segment (rank-1 vector or
    rank-2 ``(k/p, b)`` block — the walk is rank-agnostic); device ``i``
    returns sub-chunk ``i`` of the combined result (leading dim ``m/p``,
    the kernel's accumulator dtype) — the same contract as
    ``ring_psum_scatter(kernel(a_panel, x_seg), axis_name)``.

    ``step`` picks the per-stage combine primitive:

    * ``"psum_scatter"`` — one chunked ``lax.psum_scatter`` per stage
      (1/S of the full-width scatter's bytes), XLA-scheduled;
    * ``"ring"`` — the double-buffered neighbor-ring walk
      (:func:`ring_psum_scatter`): stage s's accumulator rides its p−1
      ``ppermute`` hops while stage s+1's GEMV computes — two live
      buffers, the explicit-schedule face.

    Stage s computes rows ``{i·(m/p) + s·(m/(p·S)) ...}`` for every device
    chunk i (the interleaved selection that makes the S per-stage scatter
    results concatenate into the device's contiguous ``m/p`` rows).
    Requires ``m % (p·S) == 0``.
    """
    p = axis_size(axis_name)
    if stages < 1:
        raise ValueError(f"staged_overlap_scatter: stages must be >= 1, got {stages}")
    if step not in ("psum_scatter", "ring"):
        raise ValueError(
            f"staged_overlap_scatter: unknown step {step!r} "
            "(expected 'psum_scatter' or 'ring')"
        )
    m = a_panel.shape[0]
    if p == 1:
        # Degenerate ring: no combine exists; stage the compute anyway so
        # S>1 traces the same staged program shape it does on p>1.
        if m % stages != 0:
            raise ValueError(
                f"staged_overlap_scatter: {m} rows not divisible by "
                f"stages={stages}"
            )
        slabs = a_panel.reshape(stages, m // stages, *a_panel.shape[1:])
        pieces = _pipeline_stages(
            lambda s: kernel(slabs[s], x_seg), lambda v: v, stages
        )
        return jnp.concatenate(pieces, axis=0)
    if m % (p * stages) != 0:
        raise ValueError(
            f"staged_overlap_scatter: {m} rows not divisible by "
            f"p*stages={p}*{stages}"
        )
    sub = m // (p * stages)  # rows per (device chunk, stage) cell
    # (p, S, sub, k_loc): axis 0 walks device chunks, axis 1 stages.
    cells = a_panel.reshape(p, stages, sub, *a_panel.shape[1:])

    def compute(s):
        # Stage s's slab: sub-chunk s of every device chunk, device-major —
        # a (p·sub, k_loc) GEMV, 1/S of the local panel's rows.
        slab = cells[:, s].reshape(p * sub, *a_panel.shape[1:])
        return kernel(slab, x_seg)

    if step == "ring":
        combine = lambda v: ring_psum_scatter(v, axis_name)
    else:
        combine = lambda v: jax.lax.psum_scatter(v, axis_name, tiled=True)
    return jnp.concatenate(_pipeline_stages(compute, combine, stages), axis=0)


def staged_overlap_gather(
    a_blk: Array,
    x_loc: Array,
    gather_axes,
    kernel,
    stages: int,
    reduce_axes=None,
) -> Array:
    """Pipelined output gather for the sharded-output strategies: S-stage
    local GEMV with each stage's chunked ring all-gather (and, for
    blockwise, its chunked psum over the grid columns) overlapping the
    next stage's compute.

    Must be called inside shard_map. ``a_blk`` is the device's local row
    block (``(m_loc, k_loc)``), ``x_loc`` its local RHS (vector or block);
    returns the FULL replicated result (``(m,)`` / ``(m, b)``, accumulator
    dtype) — the same value as gathering ``kernel(a_blk, x_loc)`` over
    ``gather_axes``, i.e. the ``combine="gather"`` baseline.

    ``reduce_axes`` names mesh axes to psum each stage's partial over
    before gathering (blockwise's reduce-over-grid-columns); each such
    psum is chunked — it carries ``m_loc/S`` rows, not ``m_loc``.

    Like :func:`ring_all_gather`, the result is replicated in value but
    not provably so through ppermute: callers returning it through
    ``out_specs=P()`` must build with ``check_vma=False`` (``models/base``
    scopes that to this overlap program only). Requires
    ``m_loc % S == 0``.
    """
    if stages < 1:
        raise ValueError(f"staged_overlap_gather: stages must be >= 1, got {stages}")
    m_loc = a_blk.shape[0]
    if m_loc % stages != 0:
        raise ValueError(
            f"staged_overlap_gather: {m_loc} local rows not divisible by "
            f"stages={stages}"
        )
    sub = m_loc // stages
    p = axis_size(gather_axes)

    def compute(s):
        part = kernel(
            jax.lax.dynamic_slice_in_dim(a_blk, s * sub, sub, axis=0), x_loc
        )
        if reduce_axes is not None:
            # Chunked reduce-over-grid-columns: sub = m_loc/S rows per psum,
            # pipelined against the next stage's GEMV like the gather hops.
            part = jax.lax.psum(part, reduce_axes)  # overlap-ok: chunked (m_loc/S rows per stage)
        return part

    pieces = _pipeline_stages(
        compute, lambda v: ring_all_gather(v, gather_axes), stages
    )
    if stages == 1:
        return pieces[0]
    # Each gathered piece is (p·sub, ...) device-major for ONE stage;
    # stage-major stack -> (S, p, sub, ...) -> device-major reassembly.
    stacked = jnp.stack(pieces, axis=0).reshape(
        (stages, p, sub) + pieces[0].shape[1:]
    )
    moved = jnp.moveaxis(stacked, 0, 1)  # (p, S, sub, ...)
    return moved.reshape((p * stages * sub,) + pieces[0].shape[1:])

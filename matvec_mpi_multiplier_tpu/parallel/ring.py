"""Ring collectives over mesh axes: explicit neighbor-ring reduce-scatter.

The reference's colwise strategy reduces full-length partial vectors through
the root in one blocking ``MPI_Reduce(MPI_SUM)`` (``src/multiplier_colwise.c:124``)
— a root-serialized combine. The TPU-idiomatic default is ``lax.psum_scatter``
(XLA schedules it over ICI). This module adds the *explicit* ring formulation
— the building block of ring attention / long-context sequence parallelism
(SURVEY.md §5.7): p−1 ``ppermute`` hops around the mesh-axis ring, each hop
moving one accumulated chunk to the right neighbor while the local chunk is
added — so each step's transfer rides a single ICI neighbor link and compute
(the add) overlaps the permute under XLA's async collective scheduling.

Semantics match ``lax.psum_scatter(..., tiled=True)`` exactly (tested against
it); the value is pedagogical + a scheduling alternative XLA sometimes can't
pick on its own.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array

from ..utils.compat import axis_size


def _ring_perm(p: int) -> list[tuple[int, int]]:
    """Right-neighbor ring permutation on a size-p axis."""
    return [(i, (i + 1) % p) for i in range(p)]


def _ring_reduce(chunk_fn, axis_name: str):
    """The shared ring-reduce walk: after step ``s`` the accumulator holds
    the partial sum for chunk ``idx - 1 - s``, so after ``p - 1`` hops device
    ``idx`` ends holding chunk ``idx`` summed across the whole ring.

    ``chunk_fn(i)`` produces this device's contribution to logical chunk
    ``i`` (``i`` is a traced, possibly negative index — implementations
    wrap with ``jnp.mod``). Callers handle ``p == 1`` themselves.
    """
    p = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = _ring_perm(p)
    acc = chunk_fn(idx - 1)
    for s in range(1, p):
        acc = jax.lax.ppermute(acc, axis_name, perm)
        acc = acc + chunk_fn(idx - 1 - s)
    return acc


def ring_psum_scatter(x: Array, axis_name: str) -> Array:
    """Ring reduce-scatter over ``axis_name``, chunking along axis 0.

    Must be called inside shard_map. Each device contributes a full partial
    ``x`` (any rank — a length-n vector for matvec, an (m, n) partial C for
    GEMM); device ``i`` returns chunk ``i`` of the elementwise sum (leading
    dim ``x.shape[0] // p``) — identical to
    ``lax.psum_scatter(x, axis_name, tiled=True)``.

    Requires ``x.shape[0] % p == 0`` (same constraint psum_scatter imposes
    tiled).
    """
    p = axis_size(axis_name)
    if p == 1:
        return x
    n = x.shape[0]
    if n % p != 0:
        raise ValueError(f"ring_psum_scatter: length {n} not divisible by {p}")
    chunks = x.reshape(p, n // p, *x.shape[1:])
    return _ring_reduce(
        lambda i: jnp.take(chunks, jnp.mod(i, p), axis=0), axis_name
    )


def ring_matvec(a_panel: Array, x_seg: Array, axis_name: str, kernel) -> Array:
    """Overlapped ring matvec: compute rides the ring with the accumulator.

    The ring-attention-style schedule (SURVEY.md §5.7): where
    :func:`ring_psum_scatter` first materializes the full-length local partial
    and then reduces it around the ring, this version never forms it — at
    each of the p steps the device computes only the ``(m/p, k/p)`` tile of
    its column panel that contributes to the chunk currently held by the
    accumulator, so each step's GEMV tile overlaps the previous step's
    single-neighbor ``ppermute`` hop under XLA's async collective scheduling.
    Per-step working set drops from O(m) to O(m/p).

    Must be called inside shard_map. ``a_panel`` is the device's ``(m, k/p)``
    column panel, ``x_seg`` its ``(k/p,)`` x segment; returns chunk ``i`` of
    ``y`` (length ``m/p``, the kernel's accumulator dtype) on device ``i`` —
    the same contract as
    ``ring_psum_scatter(kernel(a_panel, x_seg), axis_name)``.

    Requires ``m % p == 0``.
    """
    p = axis_size(axis_name)
    if p == 1:
        return kernel(a_panel, x_seg)
    m = a_panel.shape[0]
    if m % p != 0:
        raise ValueError(f"ring_matvec: {m} rows not divisible by {p}")
    chunk_rows = m // p

    def tile_gemv(i):
        # Rows of this panel contributing to output chunk i (traced index).
        start = jnp.mod(i, p) * chunk_rows
        tile = jax.lax.dynamic_slice_in_dim(a_panel, start, chunk_rows, axis=0)
        return kernel(tile, x_seg)

    return _ring_reduce(tile_gemv, axis_name)


def ring_matmul(a_panel: Array, b_seg: Array, axis_name: str, kernel) -> Array:
    """Overlapped ring matmul: :func:`ring_matvec` with a rank-2 RHS.

    The walk is rank-agnostic — at each step the device computes the
    ``(m/p, k/p) @ (k/p, n)`` tile feeding the C-row chunk currently held by
    the accumulator, so per-step MXU work overlaps the previous hop's
    ``ppermute``. Device ``i`` returns rows ``i`` of C (``(m/p, n)``,
    accumulator dtype) — the same contract as
    ``ring_psum_scatter(kernel(a_panel, b_seg), axis_name)``. This is the
    ring-SUMMA schedule, the GEMM face of the long-context primitive.
    """
    return ring_matvec(a_panel, b_seg, axis_name, kernel)


def a2a_psum_scatter(x: Array, axis_name: str) -> Array:
    """Reduce-scatter as ONE balanced all-to-all + local reduce — the
    Ulysses-style schedule, the third member of the combine family beside
    ``lax.psum_scatter`` (XLA-scheduled) and :func:`ring_psum_scatter`
    (p−1 neighbor hops). Each device splits its full partial into p leading
    chunks, ``lax.all_to_all`` delivers chunk j to device j across every
    link at once, and the local sum over the p received contributions
    yields this device's chunk. Rank-agnostic (vector partials for matvec,
    (m, n) partials for GEMM); same contract and constraint
    (``x.shape[0] % p == 0``) as :func:`ring_psum_scatter`.
    """
    p = axis_size(axis_name)
    if p == 1:
        return x
    n = x.shape[0]
    if n % p != 0:
        raise ValueError(f"a2a_psum_scatter: length {n} not divisible by {p}")
    chunks = x.reshape(p, n // p, *x.shape[1:])
    # After the exchange, leading index i holds device i's contribution to
    # THIS device's chunk; the local sum completes the reduce-scatter.
    recv = jax.lax.all_to_all(
        chunks, axis_name, split_axis=0, concat_axis=0, tiled=True
    )
    return recv.sum(axis=0)


def ring_all_gather(x: Array, axis_name: str) -> Array:
    """Ring all-gather: each device's chunk circulates p−1 hops; the result
    is the axis-ordered concatenation, identical to
    ``lax.all_gather(x, axis_name, tiled=True)``.

    The rowwise strategy's final gather (``MPI_Gather``,
    ``src/multiplier_rowwise.c:141``) expressed as neighbor traffic.
    Reachable from every sharded-output strategy via
    ``build(gather_output="ring")`` (``models/base.py``), which wraps it in
    its own gather-stage shard_map.

    Note: the result is replicated in *value*, but shard_map's vma checker
    cannot prove it (ppermute outputs stay marked axis-varying), so callers
    returning it through ``out_specs=P()`` must build their shard_map with
    ``check_vma=False`` — ``build`` scopes that to the gather stage only.
    """
    p = axis_size(axis_name)
    if p == 1:
        return x
    idx = jax.lax.axis_index(axis_name)
    perm = _ring_perm(p)
    n = x.shape[0]
    out = jnp.zeros((p, n), x.dtype)
    piece = x
    # After s hops, `piece` is the chunk originally owned by (idx - s).
    out = out.at[jnp.mod(idx, p)].set(piece)
    for s in range(1, p):
        piece = jax.lax.ppermute(piece, axis_name, perm)
        out = out.at[jnp.mod(idx - s, p)].set(piece)
    return out.reshape(p * n)

"""Ring attention: sequence-parallel exact attention over ppermute hops.

``parallel/ring.py`` builds the combine-side ring primitives (reduce-
scatter, overlapped ring matvec — the schedule skeleton of ring
attention); this module is the full long-context operator itself
(SURVEY.md §5.7: "ring attention / sequence parallelism" is the modern
workload the reference's colwise contraction-sharding foreshadows).

Layout: ``Q, K, V`` are ``(s, d)`` with the SEQUENCE axis sharded over
the mesh's flat device axis — each device owns an ``(s/p, d)`` block of
all three. The KV pair circulates the ring: at step ``t`` device ``i``
holds the KV block originally owned by device ``(i - t) mod p``, computes
its local ``Q_i K_j^T`` tile, and folds it into an ONLINE-SOFTMAX
accumulator (the flash-attention recurrence: running row-max ``m``,
normalizer ``l``, and value accumulator — numerically stable, never
materializing the full ``s × s`` score matrix). After ``p − 1``
single-neighbor hops every Q block has seen every KV block and holds its
exact attention output, still sequence-sharded. Per-device memory is
``O(s/p · d)`` and each hop's ``ppermute`` rides one ICI link while the
current tile's MXU work overlaps it under XLA's async collectives —
the property that makes million-token contexts feasible.

Causal masking uses global positions reconstructed from the ring step
(device ``i`` processing step ``t`` knows block ``j = i − t`` starts at
``j · s/p``), so the mask needs no materialized position arrays beyond
one iota per block.

Accumulation runs in fp32 regardless of storage dtype (bf16 Q/K/V is the
TPU-native input; softmax statistics in bf16 would destroy long-context
tails) — the same accumulator contract as the kernel registry. The WIRE is
the exception by design: KV blocks (and their backward cotangents)
traverse the collectives at storage width, so bf16 inputs pay half the
ICI bytes of fp32; forward numerics are unchanged (the per-tile upcast is
exact), while KV gradients accept per-hop bf16 rounding — pass fp32
inputs where fp32-precise gradients matter more than wire bytes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import Array
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pallas_attention import flash_block_partial, merge_partials
from ..utils.compat import axis_size, shard_map
from .ring import _ring_perm

# Local-block attention tiers, mirroring the GEMV/GEMM kernel registries:
# "xla" materializes the (h, bq, bk) score tile between two XLA matmuls;
# "flash" fuses scores + online softmax + weighted-V in one Pallas VMEM
# pipeline (ops/pallas_attention.py), the tile never reaching HBM.
ATTENTION_KERNELS = ("xla", "flash")


def _check_kernel(kernel: str) -> None:
    if kernel not in ATTENTION_KERNELS:
        raise ValueError(
            f"unknown attention kernel {kernel!r}; "
            f"options: {', '.join(ATTENTION_KERNELS)}"
        )


def _online_update(m, l, acc, scores, v_blk):
    """Fold one score tile into the flash-attention running state.

    ``scores``: (h, q_blk, k_blk) fp32 logits (already masked); ``v_blk``:
    (k_blk, h, d). ``m, l``: (h, q_blk); ``acc``: (h, q_blk, d). Rows with
    no unmasked entries contribute -inf maxima and zero weight — handled
    because ``l`` only accumulates finite terms.
    """
    tile_max = jnp.max(scores, axis=-1)  # (h, q_blk)
    new_m = jnp.maximum(m, tile_max)
    # Guard -inf - -inf (fully masked row against fully masked history).
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    correction = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    p_tile = jnp.exp(scores - safe_m[..., None])  # exp(-inf) = 0 for masked
    l = l * correction + jnp.sum(p_tile, axis=-1)
    acc = acc * correction[..., None] + jnp.einsum(
        "hqk,khd->hqd", p_tile, v_blk
    )
    return new_m, l, acc


def ring_attention(
    q: Array, k: Array, v: Array, axis_name, *, causal: bool = False,
    kernel: str = "xla",
) -> Array:
    """Exact attention with the sequence axis sharded over ``axis_name``.

    Must be called inside shard_map. ``q, k, v``: local ``(blk, d)``
    single-head or ``(blk, h, d_head)`` multi-head sequence blocks (same
    ``blk`` on every device; heads batch through the same ring walk).
    Returns the local block of ``softmax(Q Kᵀ / sqrt(d)) V`` (fp32, input
    rank preserved), exactly — the ring changes the schedule, not the
    math. ``kernel`` picks the per-hop tile implementation
    (:data:`ATTENTION_KERNELS`); both fold the same online-softmax state,
    so they agree to fp32 rounding.
    """
    _check_kernel(kernel)
    p = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    single_head = q.ndim == 2
    if single_head:
        q, k, v = q[:, None, :], k[:, None, :], v[:, None, :]
    blk, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32) * scale
    # KV circulates at its STORAGE dtype: bf16 blocks ride the ring at
    # half the ICI bytes of fp32 (TPU collectives carry bf16 natively),
    # and the per-tile upcast is exact, so the FORWARD numbers are
    # bit-identical to upcasting before the hops. The backward follows
    # the same wire: per-hop dK/dV cotangents round to bf16 and sum
    # across the reversed ring in bf16 — the standard bf16
    # gradient-communication trade (p-1 roundings instead of the one a
    # pre-loop upcast would give). Callers needing fp32-precise KV
    # gradients pass fp32 K/V and pay the 2x wire. The CPU test backend
    # legalizes bf16 collectives to f32 (its collective runtime is
    # f32-only), so HLO inspected there shows f32 permutes; that is the
    # emulation, not this schedule. Q is local (never on the wire), so
    # pre-scaling it in fp32 costs nothing.
    kv = (k, v)

    m = jnp.full((h, blk), -jnp.inf, jnp.float32)
    l = jnp.zeros((h, blk), jnp.float32)
    acc = jnp.zeros((h, blk, d), jnp.float32)
    perm = _ring_perm(p)
    rows = jax.lax.iota(jnp.int32, blk)
    if kernel == "flash":
        # The kernel wants head-major operands: transpose Q once and
        # circulate the KV pair ALREADY head-major, rather than paying two
        # (blk, h, d) transposes per hop on the path the fused tier exists
        # to speed up.
        q_heads = jnp.transpose(qf, (1, 0, 2))  # (h, blk, d)
        kv = tuple(jnp.transpose(x, (1, 0, 2)) for x in kv)

    for t in range(p):
        if t > 0:
            kv = jax.lax.ppermute(kv, axis_name, perm)
        k_blk, v_blk = kv
        # Global positions: this device's Q rows start at idx*blk; the
        # KV block in hand at step t came from device (idx - t) mod p.
        src = jnp.mod(idx - t, p)
        if kernel == "flash":
            part = flash_block_partial(
                q_heads, k_blk, v_blk,
                idx * blk + rows, src * blk + rows, causal=causal,
            )
            acc, m, l = merge_partials((acc, m, l), part)
            continue
        scores = jnp.einsum(
            "qhd,khd->hqk", qf, k_blk.astype(jnp.float32)
        )  # (h, blk, blk)
        if causal:
            q_pos = idx * blk + rows[:, None]
            k_pos = src * blk + rows[None, :]
            scores = jnp.where(
                (k_pos <= q_pos)[None, :, :], scores, -jnp.inf
            )
        m, l, acc = _online_update(
            m, l, acc, scores, v_blk.astype(jnp.float32)
        )

    # Fully-masked rows (can't happen causally: position t attends itself)
    # would have l == 0; guard the division anyway.
    o = acc / jnp.maximum(l, 1e-30)[..., None]  # (h, blk, d)
    if single_head:
        return o[0]  # the lone head, already (blk, d)
    return jnp.transpose(o, (1, 0, 2))  # back to (blk, h, d)


def _dense_block_attention(q, k, v, *, causal: bool) -> Array:
    """Plain fp32 attention over full local arrays (per-head local step of
    the Ulysses schedule; (s, d) in, (s, d) out)."""
    d = q.shape[-1]
    scores = (q @ k.T) * (1.0 / (d ** 0.5))
    if causal:
        s = q.shape[0]
        rows = jax.lax.iota(jnp.int32, s)
        scores = jnp.where(rows[None, :] <= rows[:, None], scores, -jnp.inf)
    m = jnp.max(scores, axis=1, keepdims=True)
    w = jnp.exp(scores - m)
    return (w @ v) / jnp.sum(w, axis=1, keepdims=True)


def _local_heads_attention(q, k, v, *, causal: bool, kernel: str) -> Array:
    """Full local attention over (s, h, d_head) arrays — the per-head
    step both Ulysses branches share, in the requested kernel tier.
    Accepts storage dtype (the exchanges deliver it un-upcast) and runs
    the math in fp32 per the accumulator contract."""
    q = q.astype(jnp.float32)
    k = k.astype(jnp.float32)
    v = v.astype(jnp.float32)
    if kernel == "flash":
        s, _, dh = q.shape
        pos = jax.lax.iota(jnp.int32, s)
        o_u, _, l = flash_block_partial(
            jnp.transpose(q, (1, 0, 2)) * (1.0 / (dh ** 0.5)),
            jnp.transpose(k, (1, 0, 2)),
            jnp.transpose(v, (1, 0, 2)),
            pos, pos, causal=causal,
        )
        o = o_u / jnp.maximum(l, 1e-30)[..., None]
        return jnp.transpose(o, (1, 0, 2))
    return jax.vmap(
        partial(_dense_block_attention, causal=causal),
        in_axes=1, out_axes=1,
    )(q, k, v)


def ulysses_attention(
    q: Array, k: Array, v: Array, axis_name, *, causal: bool = False,
    kernel: str = "xla",
) -> Array:
    """Exact multi-head attention, sequence-parallel via ONE all-to-all
    each way — the Ulysses schedule, the balanced-exchange counterpart of
    :func:`ring_attention` (SURVEY.md §5.7's second long-context family).

    Must be called inside shard_map. ``q, k, v``: local
    ``(s/p, h, d_head)`` blocks (sequence-sharded). One ``all_to_all``
    reshards to head-parallel ``(s, h/p, d_head)`` — full sequence, a
    slice of heads — where attention is a plain per-head dense step using
    every link at once instead of p−1 neighbor hops; a second
    ``all_to_all`` reshards back. Requires ``h % p == 0``. Trade-off vs
    the ring: one balanced exchange (lower latency on all-to-all-capable
    fabrics) against O(s²) per-head local scores (the ring never
    materializes them) — which is why both live in the toolkit.
    Returns the local ``(s/p, h, d_head)`` output block (fp32).
    """
    _check_kernel(kernel)
    p = axis_size(axis_name)
    blk, h, dh = q.shape
    if p == 1:
        return _local_heads_attention(q, k, v, causal=causal, kernel=kernel)
    if h % p != 0:
        raise ValueError(f"ulysses_attention: {h} heads not divisible by {p}")

    def to_heads(x):
        # (s/p, h, dh) -> (s, h/p, dh): split heads across devices, gather
        # the sequence — one balanced exchange, in STORAGE dtype (bf16
        # rides the fabric at half the fp32 bytes; the local step upcasts
        # after, which is exact).
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=0, tiled=True
        )

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    oh = _local_heads_attention(qh, kh, vh, causal=causal, kernel=kernel)
    # (s, h/p, dh) -> (s/p, h, dh): the inverse exchange.
    return jax.lax.all_to_all(
        oh, axis_name, split_axis=0, concat_axis=1, tiled=True
    )


def build_ring_attention(
    mesh: Mesh, *, causal: bool = False, gather_output: bool = False,
    kernel: str = "xla",
):
    """Return jitted ``attn(q, k, v) -> o`` over ``mesh``'s flat axis.

    Inputs are global ``(s, d)`` single-head or ``(s, h, d_head)``
    multi-head arrays, sequence-sharded by the returned function's
    sharding constraints; ``s`` must divide the device count.
    ``gather_output=True`` replicates the result (for small-scale
    verification; the honest long-context mode keeps o sequence-sharded).
    ``kernel``: per-hop tile tier (:data:`ATTENTION_KERNELS`).
    """
    _check_kernel(kernel)
    axes = tuple(mesh.axis_names)
    spec = P(axes)

    mapped = shard_map(
        partial(ring_attention, axis_name=axes, causal=causal, kernel=kernel),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # Interpret-mode pallas mixes unvarying internals into the body in
        # ways the vma checker cannot track (same relaxation models/base.py
        # applies for the pallas GEMV tier); the xla tier keeps the check.
        check_vma=(kernel != "flash"),
    )

    @jax.jit
    def attn(q: Array, k: Array, v: Array) -> Array:
        s = q.shape[0]
        p = int(mesh.devices.size)
        if s % p != 0:
            raise ValueError(
                f"sequence length {s} not divisible by {p} devices"
            )
        o = mapped(q, k, v)
        if gather_output:
            o = jax.lax.with_sharding_constraint(o, NamedSharding(mesh, P()))
        return o

    return attn


def build_ulysses_attention(
    mesh: Mesh, *, causal: bool = False, gather_output: bool = False,
    kernel: str = "xla",
):
    """Return jitted ``attn(q, k, v) -> o`` for the all-to-all schedule.

    Inputs are global ``(s, h, d_head)`` arrays, sequence-sharded on the
    flat axis; ``s`` must divide the device count and ``h`` must divide
    it too (the head-parallel intermediate layout).
    ``kernel``: local per-head tile tier (:data:`ATTENTION_KERNELS`).
    """
    _check_kernel(kernel)
    axes = tuple(mesh.axis_names)
    spec = P(axes)

    mapped = shard_map(
        partial(ulysses_attention, axis_name=axes, causal=causal,
                kernel=kernel),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # Same vma relaxation as build_ring_attention's flash tier.
        check_vma=(kernel != "flash"),
    )

    @jax.jit
    def attn(q: Array, k: Array, v: Array) -> Array:
        s, h = q.shape[0], q.shape[1]
        p = int(mesh.devices.size)
        if s % p != 0:
            raise ValueError(
                f"sequence length {s} not divisible by {p} devices"
            )
        if h % p != 0:
            raise ValueError(f"{h} heads not divisible by {p} devices")
        o = mapped(q, k, v)
        if gather_output:
            o = jax.lax.with_sharding_constraint(o, NamedSharding(mesh, P()))
        return o

    return attn

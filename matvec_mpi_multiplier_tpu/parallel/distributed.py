"""Multi-host runtime: the MPI_Init/MPI_Finalize analog.

Reference analog: L0 runtime bring-up — ``MPI_Init``/``MPI_Finalize``
(``src/multiplier_rowwise.c:66,157``) and the SPMD identity calls
``MPI_Comm_size``/``MPI_Comm_rank`` (``:68-69``). The reference launches p
single-threaded ranks with ``mpiexec -n p`` on one machine (``test.sh:11``);
the TPU equivalent is one JAX process per host, each owning its local
devices, joined by ``jax.distributed.initialize`` — after which
``jax.devices()`` spans every chip in the slice/pod and the mesh layer
(parallel/mesh.py) shards over ICI within a slice and DCN across slices.

On a single host nothing needs initializing — every helper degrades to the
trivial one-process answers, so the same benchmark scripts run unmodified on
a laptop CPU, one TPU VM, or a multi-host pod (driven by e.g.
``gcloud ... tpu-vm ssh --worker=all --command="python bench.py"``).
"""

from __future__ import annotations

import jax

from ..utils.constants import MAIN_PROCESS


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the multi-host runtime (no-op if already initialized or if all
    arguments are None on a TPU pod, where JAX autodetects from metadata).

    Mirrors ``MPI_Init`` (``src/multiplier_rowwise.c:66``): call once at
    program start, before any device computation.
    """
    if jax.process_count() > 1:
        return  # already initialized
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if kwargs or _on_multihost_platform():
        jax.distributed.initialize(**kwargs)


def _on_multihost_platform() -> bool:
    """True when running under a launcher that provides coordination env
    (TPU pod metadata / SLURM / OMPI) — the cases jax.distributed.initialize
    can autodetect."""
    import os

    return any(
        v in os.environ
        for v in ("COORDINATOR_ADDRESS", "SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE")
    )


def process_index() -> int:
    """This process's rank (``MPI_Comm_rank``, ``src/multiplier_rowwise.c:69``)."""
    return jax.process_index()


def process_count() -> int:
    """World size in processes (``MPI_Comm_size``, ``src/multiplier_rowwise.c:68``)."""
    return jax.process_count()


def is_main_process() -> bool:
    """The coordinator-role check (``rank == MAIN_PROCESS``,
    ``src/constants.h:5``): the process that loads data files and writes CSV
    metrics, exactly as the reference's root rank does."""
    return jax.process_index() == MAIN_PROCESS


def device_count() -> int:
    """Global device count across all processes (the 'p' in speedup curves)."""
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()

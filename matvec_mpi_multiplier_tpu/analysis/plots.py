"""Time / SpeedUp / Efficiency figures.

Reinstates the reference's missing ``stats_visualization.ipynb`` (C13): per
strategy, three curves over process/device count for each matrix size, plus a
cross-strategy comparison at a fixed size — the figures the reference README
embeds as (dead) image links (``README.md:59-68``).

Matplotlib is imported lazily so the core framework has no hard plotting
dependency.
"""

from __future__ import annotations

import os
from collections import defaultdict
from pathlib import Path

from .stats import ScalingPoint


def _mpl():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def _series(points: list[ScalingPoint]):
    """Group points into {(n_rows, n_cols): sorted [(p, point)]}."""
    by_size = defaultdict(list)
    for p in points:
        by_size[(p.n_rows, p.n_cols)].append(p)
    return {
        size: sorted(ps, key=lambda q: q.n_processes)
        for size, ps in sorted(by_size.items())
    }


def plot_strategy(
    points: list[ScalingPoint], out_path: str | os.PathLike, title: str = ""
) -> Path:
    """One figure per strategy: Time, SpeedUp, Efficiency vs device count,
    one line per matrix size (the README's per-algorithm figure set)."""
    plt = _mpl()
    fig, axes = plt.subplots(1, 3, figsize=(15, 4))
    panels = [
        ("time_s", "Time (s)", lambda q: q.time_s),
        ("speedup", "SpeedUp  S = T1/Tp", lambda q: q.speedup),
        ("efficiency", "Efficiency  E = S/p", lambda q: q.efficiency),
    ]
    for ax, (_, ylabel, get) in zip(axes, panels):
        for (m, n), ps in _series(points).items():
            xs = [q.n_processes for q in ps if get(q) is not None]
            ys = [get(q) for q in ps if get(q) is not None]
            if xs:
                ax.plot(xs, ys, marker="o", label=f"{m}×{n}")
        ax.set_xlabel("devices")
        ax.set_ylabel(ylabel)
        ax.grid(True, alpha=0.3)
    axes[0].set_yscale("log")
    axes[1].legend(fontsize=7, ncol=2)
    fig.suptitle(title or (points[0].strategy if points else ""))
    fig.tight_layout()
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_overlay(
    runs: dict[str, dict[str, list[ScalingPoint]]],
    n_rows: int,
    n_cols: int,
    out_path: str | os.PathLike,
) -> Path:
    """Overlay Time/SpeedUp/Efficiency curves from multiple result sets.

    ``runs`` maps a run label (e.g. "reference (MPI)", "this work (CPU
    mesh)") to its per-strategy points. This is the BASELINE.json north-star
    figure: TPU/virtual-device curves drawn directly over the reference's
    MPI process-count curves at one matrix size, one linestyle per run, one
    color per strategy. With a single run under an empty label this renders
    the plain single-run comparison (see :func:`plot_comparison`).
    """
    plt = _mpl()
    fig, axes = plt.subplots(1, 3, figsize=(15, 4))
    panels = [
        ("Time (s)", lambda q: q.time_s),
        ("SpeedUp", lambda q: q.speedup),
        ("Efficiency", lambda q: q.efficiency),
    ]
    linestyles = ["-", "--", ":", "-."]
    colors: dict[str, object] = {}
    for run_i, (run_label, by_strategy) in enumerate(runs.items()):
        ls = linestyles[run_i % len(linestyles)]
        for name, points in sorted(by_strategy.items()):
            ps = sorted(
                (q for q in points if (q.n_rows, q.n_cols) == (n_rows, n_cols)),
                key=lambda q: q.n_processes,
            )
            if not ps:
                continue
            if name not in colors:
                colors[name] = f"C{len(colors)}"
            curve_label = f"{name} [{run_label}]" if run_label else name
            for ax, (ylabel, get) in zip(axes, panels):
                xs = [q.n_processes for q in ps if get(q) is not None]
                ys = [get(q) for q in ps if get(q) is not None]
                if xs:
                    ax.plot(
                        xs, ys, marker="o", linestyle=ls, color=colors[name],
                        label=curve_label,
                    )
    for ax, (ylabel, _) in zip(axes, panels):
        ax.set_xlabel("processes / devices")
        ax.set_ylabel(ylabel)
        ax.grid(True, alpha=0.3)
    axes[0].set_yscale("log")
    axes[0].legend(fontsize=6 if len(runs) > 1 else 8)
    title = f"{n_rows}×{n_cols}"
    if len(runs) > 1:
        title += ": overlaid runs"
    fig.suptitle(title)
    fig.tight_layout()
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_comparison(
    by_strategy: dict[str, list[ScalingPoint]],
    n_rows: int,
    n_cols: int,
    out_path: str | os.PathLike,
) -> Path:
    """Cross-strategy Time/SpeedUp/Efficiency at one size (the README's
    comparison figures at the largest sweep size) — the single-run special
    case of :func:`plot_overlay`."""
    return plot_overlay({"": by_strategy}, n_rows, n_cols, out_path)

"""Time / SpeedUp / Efficiency figures.

Reinstates the reference's missing ``stats_visualization.ipynb`` (C13): per
strategy, three curves over process/device count for each matrix size, plus a
cross-strategy comparison at a fixed size — the figures the reference README
embeds as (dead) image links (``README.md:59-68``).

Matplotlib is imported lazily so the core framework has no hard plotting
dependency.
"""

from __future__ import annotations

import os
from collections import defaultdict
from pathlib import Path

from .stats import ScalingPoint


def _mpl():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def _series(points: list[ScalingPoint]):
    """Group points into {(n_rows, n_cols): sorted [(p, point)]}."""
    by_size = defaultdict(list)
    for p in points:
        by_size[(p.n_rows, p.n_cols)].append(p)
    return {
        size: sorted(ps, key=lambda q: q.n_processes)
        for size, ps in sorted(by_size.items())
    }


def plot_strategy(
    points: list[ScalingPoint], out_path: str | os.PathLike, title: str = ""
) -> Path:
    """One figure per strategy: Time, SpeedUp, Efficiency vs device count,
    one line per matrix size (the README's per-algorithm figure set)."""
    plt = _mpl()
    fig, axes = plt.subplots(1, 3, figsize=(15, 4))
    panels = [
        ("time_s", "Time (s)", lambda q: q.time_s),
        ("speedup", "SpeedUp  S = T1/Tp", lambda q: q.speedup),
        ("efficiency", "Efficiency  E = S/p", lambda q: q.efficiency),
    ]
    for ax, (_, ylabel, get) in zip(axes, panels):
        for (m, n), ps in _series(points).items():
            xs = [q.n_processes for q in ps if get(q) is not None]
            ys = [get(q) for q in ps if get(q) is not None]
            if xs:
                ax.plot(xs, ys, marker="o", label=f"{m}×{n}")
        ax.set_xlabel("devices")
        ax.set_ylabel(ylabel)
        ax.grid(True, alpha=0.3)
    axes[0].set_yscale("log")
    axes[1].legend(fontsize=7, ncol=2)
    fig.suptitle(title or (points[0].strategy if points else ""))
    fig.tight_layout()
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_overlay(
    runs: dict[str, dict[str, list[ScalingPoint]]],
    n_rows: int,
    n_cols: int,
    out_path: str | os.PathLike,
) -> Path:
    """Overlay Time/SpeedUp/Efficiency curves from multiple result sets.

    ``runs`` maps a run label (e.g. "reference (MPI)", "this work (CPU
    mesh)") to its per-strategy points. This is the BASELINE.json north-star
    figure: TPU/virtual-device curves drawn directly over the reference's
    MPI process-count curves at one matrix size, one linestyle per run, one
    color per strategy. With a single run under an empty label this renders
    the plain single-run comparison (see :func:`plot_comparison`).
    """
    plt = _mpl()
    fig, axes = plt.subplots(1, 3, figsize=(15, 4))
    panels = [
        ("Time (s)", lambda q: q.time_s),
        ("SpeedUp", lambda q: q.speedup),
        ("Efficiency", lambda q: q.efficiency),
    ]
    linestyles = ["-", "--", ":", "-."]
    colors: dict[str, object] = {}
    for run_i, (run_label, by_strategy) in enumerate(runs.items()):
        ls = linestyles[run_i % len(linestyles)]
        for name, points in sorted(by_strategy.items()):
            ps = sorted(
                (q for q in points if (q.n_rows, q.n_cols) == (n_rows, n_cols)),
                key=lambda q: q.n_processes,
            )
            if not ps:
                continue
            if name not in colors:
                colors[name] = f"C{len(colors)}"
            curve_label = f"{name} [{run_label}]" if run_label else name
            for ax, (ylabel, get) in zip(axes, panels):
                xs = [q.n_processes for q in ps if get(q) is not None]
                ys = [get(q) for q in ps if get(q) is not None]
                if xs:
                    ax.plot(
                        xs, ys, marker="o", linestyle=ls, color=colors[name],
                        label=curve_label,
                    )
    for ax, (ylabel, _) in zip(axes, panels):
        ax.set_xlabel("processes / devices")
        ax.set_ylabel(ylabel)
        ax.grid(True, alpha=0.3)
    axes[0].set_yscale("log")
    axes[0].legend(fontsize=6 if len(runs) > 1 else 8)
    title = f"{n_rows}×{n_cols}"
    if len(runs) > 1:
        title += ": overlaid runs"
    fig.suptitle(title)
    fig.tight_layout()
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_comparison(
    by_strategy: dict[str, list[ScalingPoint]],
    n_rows: int,
    n_cols: int,
    out_path: str | os.PathLike,
) -> Path:
    """Cross-strategy Time/SpeedUp/Efficiency at one size (the README's
    comparison figures at the largest sweep size) — the single-run special
    case of :func:`plot_overlay`."""
    return plot_overlay({"": by_strategy}, n_rows, n_cols, out_path)


def plot_roofline(
    by_strategy: dict[str, list[ScalingPoint]],
    out_path: str | os.PathLike,
    *,
    itemsize: int = 4,
    hbm_peak_gbps: float,
    vmem_bytes: int | None = None,
    n_processes: int = 1,
) -> Path | None:
    """Effective bandwidth vs per-chip operand bytes, against the HBM roof.

    The memory-side counterpart of the Time/SpeedUp panels: one line per
    strategy (matvec rows at ``n_processes`` devices), x = per-chip matrix
    bytes (log), y = effective GB/s, a horizontal line at the per-chip HBM
    peak, and a vertical band boundary at VMEM capacity — sizes left of it
    may legitimately sit above the HBM roof via on-chip residency (see
    ``stats.format_table``'s (VMEM) marker). Returns None when no matvec
    rows match ``n_processes`` (e.g. an empty or GEMM-only dataset).
    """
    from .stats import VMEM_BYTES

    vmem = VMEM_BYTES if vmem_bytes is None else vmem_bytes
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(7, 4.5))
    drew = False
    for name, points in sorted(by_strategy.items()):
        rows = sorted(
            (p for p in points
             if p.n_rhs == 1 and p.n_processes == n_processes),
            key=lambda p: p.n_rows * p.n_cols,
        )
        xs = [(p.itemsize or itemsize) * p.n_rows * p.n_cols / n_processes
              for p in rows]
        ys = [p.gbps(itemsize) for p in rows]
        if xs:
            ax.plot(xs, ys, marker="o", ms=3, label=name)
            drew = True
    if not drew:
        plt.close(fig)
        return None
    # gbps() is AGGREGATE bandwidth (total bytes / max-across-process time),
    # so the roof scales with device count — same convention as
    # stats.format_table's %-of-peak column.
    roof = hbm_peak_gbps * n_processes
    ax.axhline(roof, color="k", ls="--", lw=1,
               label=f"HBM peak ({roof:.0f} GB/s aggregate, p={n_processes})")
    ax.axvline(vmem, color="gray", ls=":", lw=1,
               label=f"VMEM capacity ({vmem // (1024 * 1024)} MiB)")
    ax.set_xscale("log")
    ax.set_xlabel(f"per-chip matrix bytes (p={n_processes})")
    ax.set_ylabel("effective GB/s")
    ax.grid(True, alpha=0.3)
    ax.legend(fontsize=7)
    ax.set_title(
        "Bandwidth roofline (left of VMEM line: on-chip residency)",
        fontsize=10,
    )
    fig.tight_layout()
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_crossover_roofline(
    points: list[tuple[int, float, float]],
    out_path: str | os.PathLike,
    *,
    hbm_peak_gbps: float,
    mxu_peak_gflops: float,
) -> Path | None:
    """The classic roofline diagram for the GEMV→GEMM crossover study.

    ``points`` are ``(n_rhs, intensity FLOP/byte, achieved GFLOP/s)`` from
    one n_rhs sweep at a fixed matrix (scripts/crossover_study.py). Axes
    are log-log: the bandwidth roof is the slope ``hbm · I``, the compute
    roof the flat ``mxu`` line, their intersection the ridge. Measured
    points hug the slope while HBM-bound and peel onto the flat roof past
    the knee — the figure form of the study's t/t_bw column. Returns None
    on no points (every row unmeasurable).
    """
    if not points:
        return None
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(6.5, 4.5))
    pts = sorted(points)
    xs = [i for _, i, _ in pts]
    ys = [g for _, _, g in pts]
    lo, hi = min(xs) / 2, max(xs) * 2
    grid = [lo * (hi / lo) ** (k / 200) for k in range(201)]
    ax.plot(grid, [min(hbm_peak_gbps * i, mxu_peak_gflops) for i in grid],
            color="k", ls="--", lw=1,
            label=f"roofline (HBM {hbm_peak_gbps:.0f} GB/s, "
                  f"MXU {mxu_peak_gflops / 1e3:.0f} TFLOP/s)")
    ridge = mxu_peak_gflops / hbm_peak_gbps
    ax.axvline(ridge, color="gray", ls=":", lw=1,
               label=f"ridge ({ridge:.0f} FLOP/byte)")
    ax.plot(xs, ys, marker="o", ms=4, color="C0", label="measured")
    for (r, i, g) in pts:
        ax.annotate(f"r={r}", (i, g), textcoords="offset points",
                    xytext=(4, -9), fontsize=7)
    ax.set_xscale("log")
    ax.set_yscale("log")
    ax.set_xlabel("arithmetic intensity (FLOP/byte)")
    ax.set_ylabel("achieved GFLOP/s")
    ax.grid(True, alpha=0.3, which="both")
    ax.legend(fontsize=7, loc="lower right")
    ax.set_title("GEMV→GEMM crossover on the roofline (r = n_rhs)",
                 fontsize=10)
    fig.tight_layout()
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path

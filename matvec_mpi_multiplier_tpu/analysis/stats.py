"""SpeedUp / Efficiency analysis.

Reference analog: components C13/C14 — the *missing* plotting notebook
``stats_visualization.ipynb`` (listed in ``.MISSING_LARGE_BLOBS:1``) that
consumed ``data/out/*.csv`` and produced the README's Time / SpeedUp /
Efficiency figures (``README.md:59-68``). Formulas (``README.md:47-50``):

* SpeedUp   ``S_p = T_1 / T_p``  (baseline = same strategy, same size, p=1)
* Efficiency ``E_p = S_p / p``

plus the derived throughput columns BASELINE.md defines:
``GFLOP/s = 2·m·n / T / 1e9`` and ``GB/s = itemsize·(m·n + m + n) / T / 1e9``.

Works on both this framework's CSVs and the reference's committed ones (the
parser in bench.metrics tolerates both header variants, quirk Q10), so
TPU-device-count curves can be overlaid directly on the reference's
MPI-process-count curves — the BASELINE.json north star.
"""

from __future__ import annotations

import dataclasses
import os
from collections import defaultdict
from pathlib import Path
from typing import Iterable

from ..bench.metrics import read_csv


@dataclasses.dataclass(frozen=True)
class ScalingPoint:
    n_rows: int
    n_cols: int
    n_processes: int
    time_s: float
    speedup: float | None  # None when no p=1 baseline exists for this size
    efficiency: float | None
    strategy: str = ""
    # Right-hand-side width: 1 = matvec (the reference's scope); >1 = GEMM
    # rows (gemm_<strategy>.csv) — the throughput formulas depend on it.
    n_rhs: int = 1
    # Bytes per element when known for THIS row (from the extended CSV's
    # dtype column); None → the caller-supplied table default. Without it a
    # mixed-dtype dataset (fp32 matvec + bf16 GEMM) would misstate GB/s for
    # whichever rows the single global itemsize doesn't match.
    itemsize: int | None = None

    def gflops(self) -> float:
        return (
            2.0 * self.n_rows * self.n_cols * self.n_rhs / self.time_s / 1e9
        )

    def gbps(self, itemsize: int = 8) -> float:
        elems = (
            self.n_rows * self.n_cols
            + (self.n_rows + self.n_cols) * self.n_rhs
        )
        return (self.itemsize or itemsize) * elems / self.time_s / 1e9


def _mean_times(rows: Iterable[dict]) -> dict[tuple[int, int, int], float]:
    """Average duplicate rows (append-only CSVs accumulate re-runs)."""
    acc: dict[tuple[int, int, int], list[float]] = defaultdict(list)
    for r in rows:
        key = (int(r["n_rows"]), int(r["n_cols"]), int(r["n_processes"]))
        acc[key].append(float(r["time"]))
    return {k: sum(v) / len(v) for k, v in acc.items()}


def scaling_table(
    rows: Iterable[dict],
    strategy: str = "",
    n_rhs_lookup: dict[tuple[int, int, int], int] | None = None,
    itemsize_lookup: dict[tuple[int, int, int], int] | None = None,
) -> list[ScalingPoint]:
    """Compute S and E for every (size, p) against the p=1 row of the same
    size (README.md:47-50).

    ``n_rhs_lookup`` maps (n_rows, n_cols, p) → RHS width for GEMM rows and
    ``itemsize_lookup`` the same key → operand bytes-per-element (the
    reference CSV schema cannot carry either; the extended CSV can —
    scripts/stats_visualization.py builds both lookups from it).
    """
    means = _mean_times(rows)
    points = []
    for (m, n, p), t in sorted(means.items()):
        t1 = means.get((m, n, 1))
        s = t1 / t if t1 is not None else None
        points.append(
            ScalingPoint(
                n_rows=m, n_cols=n, n_processes=p, time_s=t,
                speedup=s, efficiency=(s / p if s is not None else None),
                strategy=strategy,
                n_rhs=(n_rhs_lookup or {}).get((m, n, p), 1),
                itemsize=(itemsize_lookup or {}).get((m, n, p)),
            )
        )
    return points


def load_strategy_csv(
    path: str | os.PathLike,
    strategy: str = "",
    n_rhs_lookup: dict[tuple[int, int, int], int] | None = None,
    itemsize_lookup: dict[tuple[int, int, int], int] | None = None,
) -> list[ScalingPoint]:
    path = Path(path)
    if not strategy:
        strategy = path.stem.replace("asymmetric_", "")
    return scaling_table(
        read_csv(path), strategy=strategy, n_rhs_lookup=n_rhs_lookup,
        itemsize_lookup=itemsize_lookup,
    )


def best_point(points: list[ScalingPoint], n_rows: int, n_cols: int) -> ScalingPoint:
    """Fastest configuration for a given size (the README's 'best wall time'
    comparison, README.md:71-75)."""
    cands = [p for p in points if p.n_rows == n_rows and p.n_cols == n_cols]
    if not cands:
        raise ValueError(f"no rows for size {n_rows}x{n_cols}")
    return min(cands, key=lambda p: p.time_s)


# Per-chip VMEM capacity (TPU v5e: 128 MiB). An operand set at or under this
# can be served from on-chip memory across a device-side rep loop, so its
# effective GB/s is not an HBM fraction — the roofline column flags it.
VMEM_BYTES = 128 * 1024 * 1024


def format_table(
    points: list[ScalingPoint],
    itemsize: int = 8,
    hbm_peak_gbps: float | None = None,
    mxu_peak_tflops: float | None = None,
    vmem_bytes: int = VMEM_BYTES,
) -> str:
    """Markdown table in the BASELINE.md column layout.

    ``hbm_peak_gbps`` adds the roofline column (%-of-HBM-peak, the
    BASELINE.json north-star metric): aggregate peak = per-chip peak × p,
    e.g. 819 for TPU v5e, 1229 for v4. Rows whose matrix fits in per-chip
    VMEM (``vmem_bytes``) are marked ``(VMEM)``: on-chip residency across
    the rep loop can legitimately push effective bandwidth past the HBM
    roofline, so their percentage is not an HBM fraction.

    ``mxu_peak_tflops`` adds the MFU column (%-of-MXU-peak — the
    compute-roofline analog for GEMM rows, where the MXU, not HBM, is the
    ceiling): aggregate peak = per-chip peak × p, e.g. 197 bf16 TFLOP/s for
    TPU v5e. Matvec rows get an MFU too, but for them HBM is the binding
    roof (arithmetic intensity ≈ 1 FLOP/byte).
    """
    roofline = hbm_peak_gbps is not None
    mfu = mxu_peak_tflops is not None
    lines = [
        "| Strategy | Matrix | p | Time (s) | SpeedUp | Efficiency | GFLOP/s | GB/s |"
        + (" % HBM peak |" if roofline else "")
        + (" MFU % |" if mfu else ""),
        "|---|---|---|---|---|---|---|---|"
        + ("---|" if roofline else "")
        + ("---|" if mfu else ""),
    ]
    for p in points:
        s = f"{p.speedup:.2f}" if p.speedup is not None else "—"
        e = f"{p.efficiency:.3f}" if p.efficiency is not None else "—"
        row = (
            f"| {p.strategy} | {p.n_rows}×{p.n_cols} | {p.n_processes} "
            f"| {p.time_s:.6f} | {s} | {e} | {p.gflops():.2f} "
            f"| {p.gbps(itemsize):.2f} |"
        )
        if roofline:
            pct = 100.0 * p.gbps(itemsize) / (hbm_peak_gbps * p.n_processes)
            # Same per-point itemsize override the gbps above honors, so a
            # bf16 row in an fp32-default table is classified by its real
            # footprint.
            per_chip_bytes = (
                (p.itemsize or itemsize) * p.n_rows * p.n_cols
                / max(1, p.n_processes)
            )
            mark = " (VMEM)" if per_chip_bytes <= vmem_bytes else ""
            row += f" {pct:.1f}{mark} |"
        if mfu:
            pct = 100.0 * p.gflops() / (mxu_peak_tflops * 1e3 * p.n_processes)
            row += f" {pct:.1f} |"
        lines.append(row)
    return "\n".join(lines)

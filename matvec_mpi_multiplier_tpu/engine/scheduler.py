"""Arrival-window batching scheduler: continuous batching for the engine.

``MatvecEngine.submit`` dispatches each request alone; under heavy
single-RHS traffic every dispatch re-reads all of ``A`` for one column of
output, so the stream is HBM-bandwidth-bound at 1× amortization. This
module coalesces *concurrent* requests against the same resident ``A``
into one column-stacked multi-RHS dispatch through the engine's existing
bucket ladder — ``b`` requests per dispatch amortize the dominant memory
traffic ``b``-fold (the physics of the distributed GEMM in "Large Scale
Distributed Linear Algebra With TPUs" and of GSPMD's sharded-batch
execution model, PAPERS.md).

Mechanics:

* **arrival window** — the first pending request opens a window; requests
  arriving inside it column-stack into one batch. The window is adaptive:
  sized from an obs :class:`~..obs.registry.RateEstimator` so it stays
  near zero at low arrival rate (latency flat — a lone request dispatches
  immediately) and widens under load up to ``max_window_ms``
  (``window = cap · λ/(1+λ)`` with ``λ`` = expected arrivals per cap
  window — saturating, never past the cap).
* **tuner-aware flush** — three flush triggers, earliest wins. (1) The
  window expires: whatever is pending dispatches. (2) The accumulated
  width reaches the engine's widest bucket: flush immediately — past the
  largest warm bucket a batch only splits into a second dispatch, so
  waiting buys latency, not amortization. (3) The width reaches the
  tuned GEMV→GEMM promotion point ``b*`` (``tuning.lookup_promotion``,
  the measured width where one block GEMM beats sequential dispatch;
  static :data:`~.core.DEFAULT_PROMOTE_B` when the cache is cold) AND
  arrivals pause for ``settle_ms``: once the tuner has declared the
  batch a win, the scheduler stops *insisting* on the window and
  flushes at the first lull — a closed-loop stampede of N clients
  coalesces into width-N batches without ever waiting out the window,
  while a continuing arrival stream keeps filling toward the bucket
  cap.
* **deadline- and priority-aware admission** — each request carries a QoS
  tier (:data:`QOS_TIERS`): ``interactive`` flushes the open window
  immediately (coalesces with whatever is already waiting, adds zero
  wait), ``standard`` rides the adaptive window, ``bulk`` is content to
  wait the full cap. A request whose ``deadline_ms`` cannot survive the
  current window **bypasses coalescing** and dispatches alone through the
  engine (with its deadline intact); one that expires while its window is
  open fails via :class:`DeadlineExceededError` *before* dispatch and is
  sliced out of the batch — the rest of the batch dispatches unpoisoned.
* **per-request masked unpad** — one flush is ONE engine request; each
  :class:`CoalescedFuture` resolves to its own columns of the shared
  result (materialized once, sliced per request), so callers see exactly
  the ``MatvecFuture`` contract. Exactness: each output column is a
  contraction over its own input column only, and within one bucket
  executable the result is position- and pad-independent
  (``tests/test_scheduler.py`` pins coalesced columns bitwise against the
  same request dispatched alone through the same bucket).
* **backpressure on whole batches** — a flush is one ``engine.submit``,
  so the engine's ``max_in_flight`` gate counts and drains whole
  coalesced batches oldest-first; the scheduler never re-implements the
  gate.
* **blast-radius isolation (batch bisection)** — coalescing multiplies
  the cost of one bad request: a flush whose dispatch raises used to
  fail every waiter in the batch. Now a failed flush is **bisected**:
  the scheduler splits the live requests in half and re-dispatches each
  half (recursively, log-depth), so only the requests that fail *alone*
  fail their callers — everyone else still gets a correct result. The
  re-dispatches preserve PR 6's bitwise-exactness doctrine: each half is
  zero-padded back to the ORIGINAL flush's bucket, so a surviving
  request rides the same executable with the same padded width and its
  columns are bitwise what the unfaulted batch would have produced
  (pad-content independence within one bucket). The one exception is a
  flush wider than ``max_bucket`` (already a multi-dispatch split), whose
  halves re-enter at natural width. Counted in
  ``sched_bisect_splits_total`` / ``sched_isolated_failures_total``.
  Bisection targets *request-caused* failures; when several dispatches
  of one flush's tree fail with zero successes and the error carries no
  payload scope (``resilience.is_payload_fault``), the failure is
  declared **systemic** — the rest of the batch fails at once
  (``sched_batch_failures_total``) instead of re-dispatching every
  request O(log n) times against a dead backend.
  When the engine's NaN/Inf integrity gate is on, the scheduler applies
  it **per request slice** (the engine-level whole-block check is
  suppressed for coalesced dispatches), so one corrupt column fails one
  caller, not the batch.

Threading/locking discipline (lint-enforced:
``staticcheck`` rule ``scheduler-lock-across-dispatch``): all pending
state lives under one condition variable; a flush *swaps the batch out*
under the lock and dispatches after releasing it — the engine dispatch
(which may block in the backpressure drain) must never hold the lock
against new arrivals. The flusher thread exists only for window expiry;
width-threshold and interactive flushes dispatch on the submitting
caller's thread, so backpressure lands on the thread that caused it.
The host-sync and blocking-I/O lints cover this module like the rest of
``engine/`` (host staging is marked, no file I/O).

Multi-tenant note (``registry.py``): a scheduler wraps ONE engine, so
under the matrix registry coalescing is per-tenant by construction
(batches never mix tenants' matrices). A flush racing that tenant's
eviction is safe: a registry-managed engine re-places its retained host
payload transparently inside the dispatch (``MatvecEngine._a_for_locked``),
accounted through the residency listener — the flusher thread needs no
registry coordination. CROSS-tenant coalescing — tenants sharing an
exec signature AND payload bytes contributing columns to one flush,
counted in ``sched_cross_tenant_coalesced_total`` — lives in the global
scheduler (``global_scheduler.py``; docs/SCHEDULING.md), which knows
tenant identity; this class stays one-engine by design.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

import numpy as np

from ..obs.timeline import bind_request, next_request_id
from ..resilience.faults import is_payload_fault, refuse_nonfinite
from ..utils.errors import ConfigError, DeadlineExceededError
from .buckets import bucket_for, split_widths
from .core import DEFAULT_PROMOTE_B, MatvecEngine, MatvecFuture

# QoS tiers, most to least latency-sensitive. interactive: flush the open
# window now; standard: adaptive window; bulk: full window cap.
QOS_TIERS = ("interactive", "standard", "bulk")

# Widest coalescing window the adaptive sizing may reach (and the fixed
# window bulk requests wait). Milliseconds of added latency are traded for
# batch width only when the rate estimator says partners will arrive.
DEFAULT_MAX_WINDOW_MS = 2.0

# Batch-width histogram buckets (requests-per-flush, not milliseconds).
WIDTH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

# Bisection's systemic-failure escape hatch: once this many dispatches of
# one flush's bisection tree have failed with ZERO successes — the
# offered flush plus both halves, three independent programs — and the
# error is not payload-scoped, the failure is the backend's, not a
# request's. Bisecting further would re-dispatch every request O(n log n)
# times against a dead backend; fail the rest of the batch at once.
SYSTEMIC_FAILURE_THRESHOLD = 3


class _SharedResult:
    """One flush's materialization, shared by every request in the batch.

    The first ``value()`` caller materializes the engine future (host
    fetch of the whole stacked block); siblings wait on the same lock and
    read the cached host array. This lock guards *materialization* —
    caller-side, after dispatch — not the scheduler's pending state.
    """

    __slots__ = ("_future", "_lock", "_value", "_error", "_done")

    def __init__(self, future: MatvecFuture):
        self._future = future
        self._lock = threading.Lock()
        self._value: np.ndarray | None = None
        self._error: Exception | None = None
        self._done = False

    def done(self) -> bool:
        return self._future.done()

    def value(self) -> np.ndarray:
        with self._lock:
            if not self._done:
                try:
                    self._value = self._future.result()  # callback-ok: materialize-once latch BY DESIGN — _future is an engine MatvecFuture (result() fetches host bytes, fires no scheduler/registry callback); siblings deliberately wait here for the one shared host fetch
                except Exception as e:  # device error surfaces to every waiter
                    self._error = e
                self._done = True
            if self._error is not None:
                raise self._error
            return self._value


class CoalescedFuture:
    """Async handle to one scheduled request's result.

    Mirrors the :class:`~.core.MatvecFuture` face (``result`` /
    ``done`` / ``exception``) and resolves one of three ways: sliced out
    of a coalesced batch's shared result, adopted from a bypass dispatch's
    own engine future, or failed (deadline expired before dispatch).

    Batch-placement metadata (``offset``, ``width``, ``batch_width``,
    ``coalesced``) is exposed for introspection and the exactness tests —
    ``None``/``False`` until resolution, and for adopted futures.
    """

    def __init__(self, vector: bool, width: int, integrity_counter=None):
        self._vector = vector
        self.width = width
        self._event = threading.Event()
        self._shared: _SharedResult | None = None
        self._inner: MatvecFuture | None = None
        self._error: Exception | None = None
        self.offset: int | None = None
        self.batch_width: int | None = None
        self.coalesced = False
        # Non-None: apply the NaN/Inf integrity gate to THIS request's
        # slice of the shared result (per-request blast radius — the
        # engine-level whole-block gate is suppressed for coalesced
        # dispatches). Adopted (bypass) futures gate inside the engine.
        self._integrity_counter = integrity_counter

    # ---- resolution (scheduler-internal) ----

    def _adopt(self, inner: MatvecFuture) -> None:
        self._inner = inner
        self._event.set()

    def _resolve(
        self, shared: _SharedResult, offset: int, batch_width: int,
        n_requests: int,
    ) -> None:
        self._shared = shared
        self.offset = offset
        self.batch_width = batch_width
        self.coalesced = n_requests > 1
        self._event.set()

    def _fail(self, error: Exception) -> None:
        self._error = error
        self._event.set()

    # ---- the MatvecFuture face ----

    def done(self) -> bool:
        """True when the result is ready to materialize without blocking
        on the device (a failed future is done by definition). False
        while the request is still waiting in an open window."""
        if not self._event.is_set():
            return False
        if self._error is not None:
            return True
        if self._inner is not None:
            return self._inner.done()
        return self._shared.done()

    def exception(self) -> Exception | None:
        """The failure this future carries (``DeadlineExceededError``),
        or None — including while still pending in a window."""
        if self._error is not None:
            return self._error
        if self._inner is not None:
            return self._inner.exception()
        return None

    def result(self, timeout: float | None = None) -> np.ndarray:
        """Materialize this request's columns: ``(m,)`` for a vector
        request, ``(m, b)`` for a block. Blocks until the window flushes
        (``timeout`` bounds only that wait — ``None`` waits forever) and
        the shared batch result materializes; a failed future raises its
        error instead."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                "request still pending in the coalescing window after "
                f"{timeout} s (is the scheduler's flusher running?)"
            )
        if self._error is not None:
            raise self._error
        if self._inner is not None:
            return self._inner.result()
        block = self._shared.value()
        if self._vector:
            out = block[:, self.offset]
        else:
            out = block[:, self.offset:self.offset + self.width]
        if self._integrity_counter is not None:
            # Per-request integrity gate: this caller's columns are
            # corrupt; batchmates with finite slices still succeed. The
            # refusal is cached like any other failure — a second
            # result() raises it again without re-counting.
            err = refuse_nonfinite(
                out, self._integrity_counter,
                "this request's slice of the coalesced result",
            )
            if err is not None:
                self._error = err
                raise err
        return out


class _BisectState:
    """Shared across ONE flush's bisection tree: dispatch outcomes so
    far, and the systemic short-circuit (an error every sub-batch is
    failed with once bisection concludes the backend, not a payload, is
    at fault)."""

    __slots__ = ("failures", "successes", "systemic")

    def __init__(self):
        self.failures = 0
        self.successes = 0
        self.systemic: Exception | None = None


class _Pending:
    """One request waiting in the window: its normalized host block, its
    absolute deadline (scheduler-clock seconds, None = none), its
    process-unique correlation id (``obs/timeline.py``), and the future
    its batch placement will resolve."""

    __slots__ = ("block", "width", "deadline", "qos", "future", "rid")

    def __init__(self, block, width, deadline, qos, future, rid):
        self.block = block
        self.width = width
        self.deadline = deadline
        self.qos = qos
        self.future = future
        self.rid = rid


class SchedulerStats:
    """Point-in-time view over the scheduler's registry counters (same
    one-source-of-truth doctrine as :class:`~.core.EngineStats`)."""

    def __init__(
        self, requests: int, batches: int, coalesced_requests: int,
        bypass: int, deadline_failures: int, mean_batch_width: float,
    ):
        self.requests = requests
        self.batches = batches
        self.coalesced_requests = coalesced_requests
        self.bypass = bypass
        self.deadline_failures = deadline_failures
        self.mean_batch_width = mean_batch_width

    @property
    def coalesce_ratio(self) -> float:
        """Fraction of scheduled requests that shared a dispatch with at
        least one other (NaN before any request)."""
        if self.requests == 0:
            return float("nan")
        return self.coalesced_requests / self.requests


class ArrivalWindowScheduler:
    """Coalesce concurrent requests into batched engine dispatches.

    Parameters
    ----------
    engine : the :class:`~.core.MatvecEngine` to dispatch through. The
        scheduler counts into ``engine.metrics`` (one snapshot holds both
        vocabularies) and inherits the engine's dtype/shape validation.
    window_ms : ``"auto"`` (adaptive from the arrival-rate estimator, the
        default) or a fixed window in milliseconds (0 = flush every
        request immediately unless a partner is already waiting).
    max_window_ms : adaptive-window cap, and the fixed window ``bulk``
        requests wait.
    flush_width : accumulated batch width past which the scheduler stops
        insisting on the window (flush at the first ``settle_ms`` lull):
        ``"auto"`` (the tuned promotion point ``b*`` via
        ``tuning.lookup_promotion``, static default on a cold cache,
        engine ``max_bucket`` when the tuner measured promotion never
        winning) or an explicit int. Always clamped to
        ``engine.max_bucket``; width reaching ``max_bucket`` itself
        flushes immediately (a wider batch only splits).
    settle_ms : the arrival lull that flushes a batch already at/above
        ``flush_width`` — long enough that a thread stampede lands
        whole, short next to any real window.
    bypass_margin_ms : slack added to the current window when deciding
        whether a request's deadline can survive coalescing; a deadline
        inside ``window + margin`` bypasses the window and dispatches
        alone, carrying its deadline into the engine's own gate.
    rate_tau_s : time constant of the arrival-rate EWMA.
    auto_flush : start the window-expiry flusher thread (default). Tests
        that drive a fake clock disable it and flush explicitly —
        width-threshold and interactive flushes still happen inline on
        the submitting thread either way.
    clock : injectable monotonic clock (seconds).
    """

    def __init__(
        self,
        engine: MatvecEngine,
        *,
        window_ms: str | float = "auto",
        max_window_ms: float = DEFAULT_MAX_WINDOW_MS,
        flush_width: str | int = "auto",
        settle_ms: float = 0.2,
        bypass_margin_ms: float = 0.2,
        rate_tau_s: float = 0.25,
        auto_flush: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.engine = engine
        if window_ms != "auto":
            window_ms = float(window_ms)
            if window_ms < 0:
                raise ConfigError(
                    f"window_ms must be >= 0, got {window_ms}"
                )
        if max_window_ms < 0:
            raise ConfigError(
                f"max_window_ms must be >= 0, got {max_window_ms}"
            )
        if settle_ms < 0:
            raise ConfigError(f"settle_ms must be >= 0, got {settle_ms}")
        self._window_ms = window_ms
        self.max_window_ms = float(max_window_ms)
        self.settle_ms = float(settle_ms)
        self.bypass_margin_ms = float(bypass_margin_ms)
        self.flush_width = self._resolve_flush_width(flush_width)
        self._clock = clock
        # All pending state lives under this condition variable; dispatch
        # NEVER happens while it is held (scheduler-lock-across-dispatch).
        self._cond = threading.Condition()
        self._pending: list[_Pending] = []
        self._pending_width = 0
        self._flush_at: float | None = None
        self._last_arrival = 0.0
        self._closed = False

        metrics = engine.metrics
        self._rate = metrics.rate_estimator(
            "sched_arrival_req_per_s",
            "EWMA request arrival rate at the scheduler",
            tau_s=rate_tau_s, clock=clock,
        )
        self._c_requests = metrics.counter(
            "sched_requests_total", "scheduler submit() calls"
        )
        self._c_batches = metrics.counter(
            "sched_batches_total", "coalesced batches dispatched"
        )
        self._c_coalesced = metrics.counter(
            "sched_coalesced_requests_total",
            "requests that shared a dispatch with >= 1 other",
        )
        self._c_bypass = metrics.counter(
            "sched_bypass_total",
            "deadline-tight requests dispatched outside the window",
        )
        self._c_deadline_failures = metrics.counter(
            "sched_deadline_failures_total",
            "requests that expired inside an open window (failed before "
            "dispatch)",
        )
        self._c_amortized_bytes = metrics.counter(
            "sched_amortized_bytes_total",
            "bytes of A re-read traffic coalescing avoided vs per-request "
            "dispatch",
        )
        self._c_bisects = metrics.counter(
            "sched_bisect_splits_total",
            "failed coalesced dispatches split in half for re-dispatch "
            "(blast-radius isolation)",
        )
        self._c_isolated = metrics.counter(
            "sched_isolated_failures_total",
            "requests bisection isolated as genuinely failing (failed "
            "alone after log-depth splits)",
        )
        self._c_batch_failed = metrics.counter(
            "sched_batch_failures_total",
            "requests failed with their whole (sub-)batch when bisection "
            "declared the failure systemic (repeated non-payload dispatch "
            "failures with zero successes)",
        )
        # Per-request integrity gating (see CoalescedFuture): same counter
        # name as the engine's gate — one number for "results refused".
        self._integrity_counter = (
            metrics.counter(
                "engine_integrity_failures_total",
                "materializations the NaN/Inf integrity gate refused",
            )
            if engine.integrity_gate else None
        )
        self._h_batch_width = metrics.histogram(
            "sched_batch_width", "columns per coalesced flush",
            buckets=WIDTH_BUCKETS,
        )
        self._g_window = metrics.gauge(
            "sched_coalesce_window_ms",
            "coalescing window at the last admission decision",
        )
        # Bytes of A one dispatch re-reads — the amortization unit.
        self._a_bytes = engine.m * engine.k * engine.dtype.itemsize
        # The engine's correlated event hub: scheduler decisions (bypass,
        # coalesce, bisection, deadline expiry) emit alongside the
        # engine's dispatch events, correlated by the per-request ids
        # allocated at admission (obs/timeline.py).
        self._timeline = engine._timeline

        self._flusher: threading.Thread | None = None
        if auto_flush:
            self._flusher = threading.Thread(
                target=self._flusher_loop,
                name="matvec-sched-flusher", daemon=True,
            )
            self._flusher.start()

    # ---- construction-time resolution ----

    def _resolve_flush_width(self, flush_width: str | int) -> int:
        """Pin the early-flush threshold at construction.

        ``"auto"`` routes through the tuned promotion decision
        (``tune_promotion``'s ``b*`` — the measured width where one block
        GEMM beats sequential dispatch): a cold cache falls back to the
        static :data:`~.core.DEFAULT_PROMOTE_B`, and a measured
        "promotion never won" accumulates to the widest bucket instead
        (coalescing still saves per-request dispatch overhead even when
        the GEMM itself does not win). Always clamped to the engine's
        ``max_bucket``.
        """
        engine = self.engine
        if flush_width == "auto":
            from ..models.base import mesh_size
            from ..tuning import lookup_promotion

            decision = lookup_promotion(
                strategy=engine.strategy.name, m=engine.m, k=engine.k,
                p=mesh_size(engine.mesh), dtype=str(engine.dtype),
            )
            if decision is None:  # cold cache: static default
                b_star = DEFAULT_PROMOTE_B
            else:
                b_star = decision.get("b_star")
                if b_star is None:  # measured: promotion never won
                    b_star = engine.max_bucket
            return max(1, min(int(b_star), engine.max_bucket))
        flush_width = int(flush_width)
        if flush_width < 1:
            raise ConfigError(
                f"flush_width must be >= 1, got {flush_width}"
            )
        return min(flush_width, engine.max_bucket)

    # ---- window sizing ----

    def current_window_ms(self, now: float | None = None) -> float:
        """The coalescing window a standard request arriving now would
        wait: the fixed override, or the adaptive size — ``cap · λ/(1+λ)``
        with ``λ = rate · cap``, the expected number of arrivals during a
        cap-wide window. Near zero when arrivals are rare (a lone request
        dispatches immediately; latency stays flat), saturating toward
        the cap as the estimated rate grows."""
        if self._window_ms != "auto":
            return self._window_ms
        if now is None:
            now = self._clock()
        lam = self._rate.rate_per_s(now=now) * (self.max_window_ms / 1e3)
        return self.max_window_ms * lam / (1.0 + lam)

    # ---- admission ----

    def submit(
        self,
        x,
        *,
        deadline_ms: float | None = None,
        qos: str = "standard",
    ) -> CoalescedFuture:
        """Admit one request — a ``(k,)`` vector or ``(k, b)`` block —
        into the coalescing window (or past it; see the module
        docstring's admission rules). Returns immediately unless this
        submission itself trips a flush, in which case the dispatch (and
        any engine backpressure it absorbs) runs on this thread before
        returning."""
        if qos not in QOS_TIERS:
            raise ConfigError(
                f"unknown QoS tier {qos!r}; expected one of {QOS_TIERS}"
            )
        if self._closed:  # unguarded-ok: advisory fast-fail; the decisive check repeats under the condition on the queued path, and the bypass paths tolerate one racing close
            # Checked again under the condition on the queued path; this
            # early check keeps the refusal uniform across the bypass and
            # stale-on-arrival paths too.
            raise ConfigError("scheduler is closed")
        engine = self.engine
        now = self._clock()
        x = np.asarray(x, dtype=engine.dtype)  # sync-ok: requests are host arrays (engine contract)
        if x.ndim == 1:
            if x.shape[0] != engine.k:
                raise ConfigError(
                    f"request length {x.shape[0]} != A columns {engine.k}"
                )
            vector, block = True, x[:, None]
        elif x.ndim != 2 or x.shape[0] != engine.k:
            raise ConfigError(
                f"request must be (k,) or (k, b) with k={engine.k}; got "
                f"shape {x.shape}"
            )
        elif x.shape[1] == 0:
            raise ConfigError("empty request (b=0)")
        else:
            vector, block = False, x
        width = block.shape[1]
        self._c_requests.inc()
        self._rate.observe(now=now)
        fut = CoalescedFuture(
            vector, width, integrity_counter=self._integrity_counter
        )
        # Process-unique correlation id, allocated at ADMISSION: every
        # event this request causes anywhere below (engine dispatch,
        # retries, the batch it coalesces into) shares it.
        rid = next_request_id()
        if deadline_ms is not None and deadline_ms <= 0:
            # Stale on arrival (upstream queueing): fail without touching
            # the window or the engine.
            self._c_deadline_failures.inc()
            self._timeline.emit(
                "deadline_failed", request_id=rid,
                deadline_ms=deadline_ms, at="admission",
            )
            fut._fail(DeadlineExceededError(
                f"request deadline of {deadline_ms} ms elapsed before "
                "admission"
            ))
            return fut

        window_ms = self.current_window_ms(now)
        self._g_window.set(window_ms)
        if deadline_ms is not None and deadline_ms <= (
            window_ms + self.bypass_margin_ms
        ):
            # The deadline cannot survive the window: dispatch alone, now,
            # with the deadline intact for the engine's own gate. The
            # binding hands the admission id to the engine's tracer and
            # every event its dispatch emits.
            self._c_bypass.inc()
            self._timeline.emit(
                "bypass", request_id=rid, deadline_ms=deadline_ms,
                window_ms=window_ms,
            )
            with bind_request(rid):
                fut._adopt(engine.submit(x, deadline_ms=deadline_ms))
            return fut

        deadline = (
            now + deadline_ms / 1e3 if deadline_ms is not None else None
        )
        pend = _Pending(block, width, deadline, qos, fut, rid)
        batch = None
        with self._cond:
            if self._closed:
                raise ConfigError("scheduler is closed")
            self._pending.append(pend)
            self._pending_width += width
            self._last_arrival = now
            tier_window_s = (
                self.max_window_ms if qos == "bulk" else window_ms
            ) / 1e3
            flush_at = now + tier_window_s
            if self._flush_at is None or len(self._pending) == 1:
                self._flush_at = flush_at
            else:
                # A later, more latency-sensitive arrival pulls the whole
                # batch's flush forward; it never pushes it back.
                self._flush_at = min(self._flush_at, flush_at)
            if deadline is not None:
                # Never *plan* to hold a request past its deadline; the
                # margin leaves room for the dispatch itself.
                self._flush_at = min(
                    self._flush_at,
                    deadline - self.bypass_margin_ms / 1e3,
                )
            if (
                qos == "interactive"
                or self._pending_width >= self.engine.max_bucket
            ):
                # Immediate triggers: latency-sensitive tier, or a batch
                # already at the widest bucket (wider only splits).
                batch = self._take_locked()
            else:
                self._cond.notify_all()  # re-arm the flusher's timer
        if batch is not None:
            self._dispatch(batch)
        return fut

    def __call__(self, x) -> np.ndarray:
        """Synchronous convenience: ``submit(x).result()``."""
        return self.submit(x).result()

    # ---- flushing ----

    def _take_locked(self) -> list[_Pending] | None:
        """Swap the pending batch out (caller holds the condition). The
        dispatch happens after release — never under the lock."""
        if not self._pending:
            return None
        batch = self._pending
        self._pending = []
        self._pending_width = 0
        self._flush_at = None
        return batch

    def _dispatch(self, batch: list[_Pending]) -> None:
        """Dispatch one swapped-out batch: fail requests whose deadline
        expired while the window was open (before dispatch, without
        poisoning the rest), column-stack the survivors, and hand the
        stacked block to the engine as ONE request — bisecting on
        failure (``_submit_batch``) so only genuinely-failing requests
        fail. Runs with no scheduler lock held — the engine's
        backpressure gate may block here, and new arrivals must keep
        queueing meanwhile. The coalescing accounting records the
        OFFERED flush (bisection re-dispatches are tallied separately in
        the ``sched_bisect_*`` counters) — except a flush none of whose
        dispatches ran, which produced no coalescing to account."""
        now = self._clock()
        live: list[_Pending] = []
        for p in batch:
            if p.deadline is not None and now > p.deadline:
                self._c_deadline_failures.inc()
                self._timeline.emit(
                    "deadline_failed", request_id=p.rid, at="window",
                )
                p.future._fail(DeadlineExceededError(
                    "request deadline elapsed inside the coalescing "
                    "window before dispatch"
                ))
            else:
                live.append(p)
        if not live:
            return
        # The batch gets its OWN correlation id: the flush's engine
        # dispatch (and everything under it) correlates to the batch,
        # and members find it through the coalesce event's members list
        # (obs timeline's one-hop batch expansion).
        batch_rid = next_request_id()
        self._timeline.emit(
            "coalesce", request_id=batch_rid,
            members=[p.rid for p in live],
            width=sum(p.width for p in live),
        )
        dispatched = self._submit_batch(live, pad_to=None, batch_rid=batch_rid)
        if not dispatched:
            # Every dispatch of the flush failed: no device work ran, so
            # counting it as a coalesced batch (width histogram,
            # amortized bytes) would overstate savings that never
            # materialized. Its failures are in the sched_isolated_* /
            # sched_batch_failures_total counters.
            return
        # Accounting AFTER the dispatch: this bookkeeping overlaps the
        # enqueued device work instead of sitting on the flush's critical
        # path, where every waiter in the batch (and, on a saturated
        # host, the whole arrival pattern the NEXT batch coalesces under)
        # is blocked on it.
        width = sum(p.width for p in live)
        self._c_batches.inc()
        self._h_batch_width.observe(width)
        if len(live) > 1:
            self._c_coalesced.inc(len(live))
        saved = sum(
            self._dispatches_for(p.width) for p in live
        ) - self._dispatches_for(width)
        if saved > 0:
            self._c_amortized_bytes.inc(saved * self._a_bytes)

    def _bisect_pad_target(self, width: int) -> int | None:
        """The bucket a failed flush's halves are zero-padded back to so
        survivors stay bitwise-exact (same executable, same padded
        width as the unfaulted batch). None when the original flush did
        not ride one GEMM bucket — per-column dispatch (below ``b*``) is
        position-independent anyway, and an over-``max_bucket`` flush was
        already a multi-dispatch split."""
        engine = self.engine
        if (
            engine.b_star is not None
            and engine.b_star <= width <= engine.max_bucket
        ):
            return bucket_for(width, engine.max_bucket)
        return None

    def _submit_batch(
        self, live: list[_Pending], pad_to: int | None,
        state: _BisectState | None = None,
        batch_rid: int | None = None,
    ) -> bool:
        """Dispatch a batch of live requests as one engine submit; on
        failure, bisect and re-dispatch (log-depth) until each failing
        request has failed ALONE — blast-radius isolation. Never raises
        (a flusher-thread dispatch error must land in futures, not kill
        the thread); returns True when at least one dispatch of the
        batch's tree ran, so the caller can skip the coalescing
        accounting for a flush that never reached the device.

        Bisection is for failures a REQUEST causes (a poisoned payload
        crashing the kernel); a backend-down outage fails every
        re-dispatch identically, and splitting would re-dispatch each
        request O(log n) times — each with the full retry/ladder cost —
        for nothing. ``state`` tracks the bisection tree's outcomes:
        once :data:`SYSTEMIC_FAILURE_THRESHOLD` dispatches have failed
        with zero successes and the error is not payload-scoped
        (``resilience.is_payload_fault``), the remaining requests fail
        together (``sched_batch_failures_total``, NOT counted as
        bisection-isolated — the failure was never theirs)."""
        engine = self.engine
        if state is not None and state.systemic is not None:
            self._c_batch_failed.inc(len(live))
            self._timeline.emit(
                "batch_failure", cause_id=batch_rid,
                members=[p.rid for p in live],
                error=type(state.systemic).__name__,
            )
            for p in live:
                p.future._fail(state.systemic)
            return False
        stacked = (
            live[0].block if len(live) == 1
            else np.concatenate([p.block for p in live], axis=1)
        )
        width = stacked.shape[1]
        if pad_to is not None and pad_to > width:
            stacked = np.concatenate(
                [stacked, np.zeros((engine.k, pad_to - width), stacked.dtype)],
                axis=1,
            )
        try:
            # The batch id binds around the dispatch: the engine's trace
            # and every nested event (retries, breaker transitions)
            # correlate to the batch, whose members are on the coalesce
            # event.
            with bind_request(batch_rid):
                if self._integrity_counter is None:
                    inner = engine.submit(stacked)
                else:
                    # With the gate on, each CoalescedFuture checks its
                    # own slice — the whole-block check would fail
                    # batchmates.
                    inner = engine.submit(stacked, integrity=False)
        except Exception as e:
            if state is None:
                state = _BisectState()
            state.failures += 1
            if (
                state.successes == 0
                and state.failures >= SYSTEMIC_FAILURE_THRESHOLD
                and not is_payload_fault(e)
            ):
                # Every dispatch of this tree failed and nothing points
                # at a payload: the backend is the problem. This applies
                # at a leaf too — a request that failed alone under a
                # systemic outage was not isolated BY bisection.
                state.systemic = e
                self._c_batch_failed.inc(len(live))
                self._timeline.emit(
                    "batch_failure", cause_id=batch_rid,
                    members=[p.rid for p in live],
                    error=type(e).__name__,
                )
                for p in live:
                    p.future._fail(e)
                return False
            if len(live) == 1:
                # Failed alone: genuinely poisoned — this caller's fate.
                self._c_isolated.inc()
                self._timeline.emit(
                    "isolated_failure", request_id=live[0].rid,
                    cause_id=batch_rid, error=type(e).__name__,
                )
                live[0].future._fail(e)
                return False
            self._c_bisects.inc()
            mid = len(live) // 2
            self._timeline.emit(
                "bisect", cause_id=batch_rid,
                members=[p.rid for p in live], split_at=mid,
            )
            target = (
                pad_to if pad_to is not None
                else self._bisect_pad_target(width)
            )
            left = self._submit_batch(live[:mid], target, state, batch_rid)
            right = self._submit_batch(live[mid:], target, state, batch_rid)
            return left or right
        if state is not None:
            state.successes += 1
        shared = _SharedResult(inner)
        batch_width = stacked.shape[1]
        offset = 0
        for p in live:
            p.future._resolve(shared, offset, batch_width, len(live))
            offset += p.width
        return True

    def _dispatches_for(self, width: int) -> int:
        """How many device programs the engine runs for a block of this
        width: bucketed GEMM chunks at/above the promotion point,
        per-column GEMVs below it."""
        engine = self.engine
        if engine.b_star is not None and width >= engine.b_star:
            return len(split_widths(width, engine.max_bucket))
        return width

    def flush(self) -> int:
        """Flush the open window now (driver/test code — the serve bench
        fences with it before draining). Returns the number of requests
        dispatched or failed."""
        with self._cond:
            batch = self._take_locked()
        if batch is None:
            return 0
        self._dispatch(batch)
        return len(batch)

    def _flush_due_locked(self, now: float) -> float | None:
        """When the open batch should flush (caller holds the condition):
        the window deadline, pulled forward to the next ``settle_ms``
        lull once the accumulated width has reached the tuned flush
        threshold. None with nothing pending."""
        if not self._pending:
            return None
        due = self._flush_at if self._flush_at is not None else now
        if self._pending_width >= self.flush_width:
            due = min(due, self._last_arrival + self.settle_ms / 1e3)
        return due

    def _flusher_loop(self) -> None:
        """Flush watchdog: dispatches the open batch at its due time —
        window expiry, or the first arrival lull once the batch width
        passed the tuned threshold. Interactive and widest-bucket flushes
        happen inline in ``submit``; this thread covers every batch whose
        partners stopped arriving. Note dispatch happens after the
        condition is released — when the engine's backpressure gate
        blocks here, the next whole batch simply accumulates until the
        oldest one drains (batch-granular backpressure)."""
        while True:
            batch = None
            with self._cond:
                if self._closed:
                    return
                now = self._clock()
                due = self._flush_due_locked(now)
                if due is None:
                    self._cond.wait()
                    continue
                if now < due:
                    self._cond.wait(timeout=due - now)
                    continue
                batch = self._take_locked()
            if batch is not None:
                self._dispatch(batch)

    # ---- lifecycle & introspection ----

    @property
    def stats(self) -> SchedulerStats:
        h = self._h_batch_width
        count = h.count
        return SchedulerStats(
            requests=self._c_requests.value,
            batches=self._c_batches.value,
            coalesced_requests=self._c_coalesced.value,
            bypass=self._c_bypass.value,
            deadline_failures=self._c_deadline_failures.value,
            mean_batch_width=(
                h.sum / count if count else float("nan")
            ),
        )

    @property
    def pending_width(self) -> int:
        """Columns waiting in the open window right now."""
        with self._cond:
            return self._pending_width

    def close(self) -> None:
        """Flush the open window, stop the flusher thread, and refuse
        further submits. Does NOT close the engine (the scheduler is a
        front-end; the engine may serve other callers)."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            batch = self._take_locked()
            self._cond.notify_all()
        if batch is not None:
            self._dispatch(batch)
        if self._flusher is not None:
            self._flusher.join(timeout=5.0)

    def __enter__(self) -> "ArrivalWindowScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

"""Cost-model-driven global scheduler: every decision is a prediction.

The serve path grew every ingredient of an SLO-aware scheduler without a
brain wiring them together: the arrival-window scheduler (PR 6) coalesces
only within one tenant, the registry (PR 9) evicts on recency without
knowing what is about to arrive, and the calibrated α–β cost model
(PR 10) can predict any dispatch's duration — yet the serve path never
asked it. This module is the brain: a cross-tenant scheduling layer over
the :class:`~.registry.MatrixRegistry` that consults the
:class:`~..tuning.cost_model.CostModel` on every decision, the
decide-from-a-model-first doctrine of GSPMD (arXiv 2105.04663) and the
TPU distributed-linalg paper (arXiv 2112.09017, PAPERS.md). Four
mechanisms (operator's guide: docs/SCHEDULING.md):

* **predicted-time admission** — each request's ``deadline_ms`` is
  checked at submit time against the queue-aware ETA for its ExecKey
  (:meth:`~..tuning.cost_model.CostModel.predict_admission`: the
  predicted backlog of outstanding dispatches + the restore transfer if
  the tenant's ``A`` is evicted + the dispatch itself). A request that
  cannot make its deadline is **rejected fast** with a typed
  :class:`~..utils.errors.AdmissionRejectedError` — microseconds at the
  door instead of burning a dispatch slot to expire in the backpressure
  gate or serve an answer nobody is waiting for. Admission OWNS the
  deadline: an admitted request is dispatched without one (the
  prediction was the commitment), so deadline-expire after admission is
  structurally zero — the failure mode this layer exists to delete.
* **cross-tenant flush interleaving** — dispatch order is decided across
  tenants, and ahead of a **predicted-long** dispatch the scheduler
  enqueues the hottest evicted tenant's swap-in
  (:meth:`~.registry.MatrixRegistry.prefetch` — the PR 9 async
  ``device_put`` path), so eviction restores hide under compute instead
  of stalling that tenant's next request.
* **cross-tenant coalescing** — tenants whose engines share an exec
  signature AND payload bytes (``registry.coalesce_group``: same
  compiled programs, same ``A``) may share one column-stacked flush;
  per-column results are bitwise-identical to solo submits by the PR 6
  exactness doctrine (which batch column a request rides never changes
  its output). Counted in ``sched_cross_tenant_coalesced_total``. The
  coalescing here is opportunistic over back-to-back submissions (a
  group switch, a width threshold, a deadline, or ``flush()`` closes
  the open batch — there is no timer thread; the arrival-window
  scheduler remains the latency-targeted per-engine coalescer).
* **demand-aware eviction** — the registry's victim score gains a
  predicted-demand term (each tenant's EWMA arrival rate — exported as
  ``tenant_rate_req_per_s{tenant=...}`` — weighed by its predicted
  restore cost; ``MatrixRegistry(demand_weight=...)``), so "about to be
  asked for and expensive to bring back" protects a resident the way
  "recently used" alone cannot. Rejected demand still ticks the
  estimator (``registry.observe_demand``): a tenant refused under load
  is exactly the tenant whose residency would fix the refusals.

**Every decision explains itself**: admit / reject / interleave / evict
(and each coalesced flush) lands in a bounded decision ring — mirrored
to a JSONL file via the obs sink thread when ``decision_jsonl`` is set —
carrying ``predicted_s`` and ``reason`` fields, and is mirrored as
``gsched_*`` metrics the obs CLI renders as the ``global scheduler``
panel (``python -m matvec_mpi_multiplier_tpu.obs metrics``).

**Uncalibrated degrade** (the cold-cache contract): with no calibration
record in the tuning cache the scheduler degrades to the greedy
baseline — every request admitted, deadlines handed through to the
engine's own gate, ONE warning log line — and never rejects on
``predicted_s=None``. Calibrate (``python -m
matvec_mpi_multiplier_tpu.tuning.cost_model --calibrate quick``) to turn
prediction on.

The admission path consults predictions but never *measures* — no probe,
no ``perf_counter`` pair around a dispatch, no calibration. Enforced by
staticcheck rule ``measurement-in-admission-path`` (marker
``admit-ok:``): timing belongs to the tuner and the bench, and an
admission gate that measures has put a benchmark in front of every
request.

Benchmarked by ``bench/serve.py --tenants ... --global-sched on|off|both
--deadline-ms ...`` (same-trace A/B; the committed capture lives in
``data/gsched_demo/``).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable

import numpy as np

from ..obs.sink import JsonlSink
from ..obs.timeline import (
    bind_request,
    bound_request_id,
    get_hub,
    next_request_id,
)
from ..utils.errors import AdmissionRejectedError, ConfigError
from .core import DEFAULT_PROMOTE_B, MatvecFuture
from .registry import MatrixRegistry
from .scheduler import QOS_TIERS, _SharedResult

# Decision vocabulary (the ring's `decision` field and the gsched_*
# counter suffixes).
DECISIONS = ("admit", "reject", "interleave", "evict", "flush", "reshard")

# Bounded decision ring: enough to hold a whole bench trace's decisions
# without growing with uptime.
DEFAULT_DECISION_CAPACITY = 4096

# Fallback per-dispatch queue charge when the model has no formula for a
# config (the backlog estimate must not read an unpredictable dispatch
# as free).
_FALLBACK_DISPATCH_S = 1e-4


class _GsSlice:
    """One coalesced member's future: resolves to its own columns of the
    shared flush result (mirrors the ``MatvecFuture`` face). Materializing
    an un-flushed member triggers the flush itself — a caller can always
    drain."""

    def __init__(self, sched: "GlobalScheduler", vector: bool, width: int):
        self._sched = sched
        self._vector = vector
        self.width = width
        self._event = threading.Event()
        self._shared: _SharedResult | None = None
        self.offset: int | None = None
        self.retired = False

    def _resolve(self, shared: _SharedResult, offset: int) -> None:
        self._shared = shared
        self.offset = offset
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set() and self._shared.done()

    def exception(self) -> Exception | None:
        """The failure this member's flush carries (after someone
        materialized the shared result), or None — including while the
        batch is still open."""
        if self._event.is_set() and self._shared._done:
            return self._shared._error
        return None

    def result(self) -> np.ndarray:
        if not self._event.is_set():
            self._sched.flush()  # self-healing: draining forces the flush
        self._event.wait()
        block = self._shared.value()
        self.retired = True
        if self._vector:
            return block[:, self.offset]
        return block[:, self.offset:self.offset + self.width]


class _PendingMember:
    """One request waiting in the open cross-tenant batch."""

    __slots__ = ("tenant_id", "block", "width", "future", "rid")

    def __init__(self, tenant_id, block, width, future, rid):
        self.tenant_id = tenant_id
        self.block = block
        self.width = width
        self.future = future
        self.rid = rid


class GlobalScheduler:
    """SLO-aware cross-tenant scheduling over a
    :class:`~.registry.MatrixRegistry` (module docstring has the
    doctrine; docs/SCHEDULING.md the operator's guide).

    Parameters
    ----------
    registry : the tenant fleet to schedule. The scheduler registers
        itself as the registry's ``eviction_listener`` (eviction
        decisions enter the trace) and counts into ``registry.metrics``.
    cost_model : ``"auto"`` (any calibration record in the tuning cache,
        largest probed mesh — ``tuning.cost_model.any_model_from_cache``),
        an explicit :class:`~..tuning.cost_model.CostModel`, or None.
        Without a model the scheduler degrades to the greedy baseline
        (one warning line; never rejects).
    deadline_margin : admission rejects when ``eta_s > deadline ·
        margin``. 1.0 rejects exactly at the predicted miss; above 1.0
        admits optimistically (tolerate prediction error), below 1.0
        rejects conservatively (reserve headroom).
    interleave_threshold_s : a dispatch predicted at or above this
        overlaps the hottest evicted tenant's swap-in. None (default):
        the predicted restore cost of a mean-size payload — a dispatch
        long enough to hide the transfer it is covering.
    coalesce : allow same-group cross-tenant coalescing (default True;
        the A/B bench's ``off`` mode disables the whole layer, not this
        flag).
    reshard : ``"auto"`` arms the online-resharding trigger
        (docs/RESHARDING.md): after each admission the scheduler asks
        whether a candidate layout's predicted dispatch time, PLUS the
        migration cost amortized over the tenant's EWMA demand horizon,
        beats the current layout — and if so migrates the resident ``A``
        on-device (``MatrixRegistry.reshard``). The decision is pure
        prediction (``CostModel.predict_reshard``), never a
        re-measurement, and enters the decision trace with its crossover
        arithmetic. ``"off"`` (default) never migrates. Requires a
        calibrated model — greedy mode never reshards.
    reshard_cooldown_s : per-tenant minimum seconds between migrations
        (thrash damper on oscillating demand).
    reshard_horizon_s : the EWMA demand window the migration cost
        amortizes over: expected requests = rate · horizon.
    flush_width : open-batch width that forces a flush — ``None`` uses
        the fleet's tuned promotion point ``b*`` (static default on a
        cold cache).
    decision_jsonl : mirror every decision record to this JSONL file via
        the obs sink thread (None: ring only).
    decision_capacity : bounded decision-ring length.
    clock : injectable monotonic clock (seconds) — deadline arithmetic
        and decision timestamps; tests drive a fake one.
    log : one-line warning sink (default: stderr) — the uncalibrated
        degrade notice.
    """

    def __init__(
        self,
        registry: MatrixRegistry,
        *,
        cost_model="auto",
        deadline_margin: float = 1.0,
        interleave_threshold_s: float | None = None,
        coalesce: bool = True,
        reshard: str = "off",
        reshard_cooldown_s: float = 30.0,
        reshard_horizon_s: float = 30.0,
        flush_width: int | None = None,
        decision_jsonl=None,
        decision_capacity: int = DEFAULT_DECISION_CAPACITY,
        clock: Callable[[], float] = time.monotonic,
        log: Callable[[str], None] | None = None,
    ):
        if deadline_margin <= 0:
            raise ConfigError(
                f"deadline_margin must be > 0, got {deadline_margin}"
            )
        if reshard not in ("auto", "off"):
            raise ConfigError(
                f"reshard must be 'auto' or 'off', got {reshard!r}"
            )
        self.registry = registry
        self.deadline_margin = float(deadline_margin)
        self._interleave_threshold_s = interleave_threshold_s
        self._coalesce = bool(coalesce)
        self._reshard = reshard
        self._reshard_cooldown_s = float(reshard_cooldown_s)
        self._reshard_horizon_s = float(reshard_horizon_s)
        self._last_reshard: dict[str, float] = {}
        self._flush_width = flush_width
        self._clock = clock
        self._log = log if log is not None else (
            lambda line: print(line, file=sys.stderr)
        )
        if cost_model == "auto":
            from ..tuning.cache import TuningCache
            from ..tuning.cost_model import any_model_from_cache

            cost_model = any_model_from_cache(TuningCache.load())
        self.model = cost_model
        if self.model is None:
            # The cold-cache contract: greedy, loudly, exactly once.
            self._log(
                "global scheduler: cost model uncalibrated — degrading "
                "to greedy admission (no predicted-time rejects; run "
                "`python -m matvec_mpi_multiplier_tpu.tuning.cost_model "
                "--calibrate quick` to enable them)"
            )

        # Admission bookkeeping mutex: pending batch, outstanding window,
        # decision ring, prediction memo. Dispatches, prefetches and
        # flushes run AFTER it is released (the engine/ lock disciplines,
        # rules #8/#11).
        self._lock = threading.Lock()
        self._pending: list[_PendingMember] = []
        self._pending_group: tuple | None = None
        self._pending_width = 0
        self._outstanding: list[tuple[object, float]] = []
        self._decisions: list[dict] = []
        self._decision_capacity = int(decision_capacity)
        self._predict_memo: dict[tuple, float | None] = {}
        self._closed = False
        self._sink = (
            JsonlSink(decision_jsonl) if decision_jsonl is not None else None
        )
        self._timeline = get_hub()

        metrics = registry.metrics
        self._c_decisions = metrics.counter(
            "gsched_decisions_total",
            "global-scheduler decisions (admit+reject+interleave+evict"
            "+flush)",
        )
        self._c_admits = metrics.counter(
            "gsched_admits_total", "requests admitted to dispatch"
        )
        self._c_rejects = metrics.counter(
            "gsched_rejects_total",
            "requests rejected fast at admission (typed "
            "AdmissionRejectedError — predicted ETA past the deadline; "
            "rejected != failed in availability accounting)",
        )
        self._c_interleaves = metrics.counter(
            "gsched_interleaves_total",
            "evicted-tenant swap-ins enqueued under a predicted-long "
            "dispatch (prefetch overlapped with compute)",
        )
        self._c_evict_decisions = metrics.counter(
            "gsched_evictions_total",
            "demand-aware evictions recorded in the decision trace",
        )
        self._c_flushes = metrics.counter(
            "gsched_flushes_total", "coalesced flushes dispatched"
        )
        self._c_reshard_decisions = metrics.counter(
            "gsched_reshards_total",
            "cost-model crossover migrations triggered (predicted "
            "new-layout dispatch + amortized migration < old layout "
            "over the EWMA demand horizon)",
        )
        self._c_cross_tenant = metrics.counter(
            "sched_cross_tenant_coalesced_total",
            "requests that shared a coalesced flush with another "
            "tenant's (same exec signature, same payload bytes)",
        )
        self._g_queue = metrics.gauge(
            "gsched_queue_predicted_s",
            "predicted seconds of outstanding dispatch backlog at the "
            "last admission decision",
        )
        self._g_greedy = metrics.gauge(
            "gsched_degraded_greedy",
            "1 while the scheduler is running WITHOUT a calibrated cost "
            "model (greedy admission; no predicted-time rejects)",
        )
        self._g_greedy.set(0 if self.model is not None else 1)
        self._h_predicted = metrics.histogram(
            "gsched_predicted_dispatch_ms",
            "predicted dispatch milliseconds per admitted request",
        )

        if registry.eviction_listener is None:
            registry.eviction_listener = self._on_eviction

    # ---- the decision trace ----

    def _record(self, decision: str, tenant_id: str, *,
                predicted_s, reason: str, request_id=None, cause_id=None,
                **fields) -> None:
        record = {
            "decision": decision,
            "tenant": tenant_id,
            "predicted_s": predicted_s,
            "reason": reason,
            "t_s": self._clock(),
            **fields,
        }
        if request_id is not None:
            record["request_id"] = request_id
        if cause_id is not None:
            record["cause_id"] = cause_id
        with self._lock:
            self._decisions.append(record)
            if len(self._decisions) > self._decision_capacity:
                del self._decisions[: -self._decision_capacity]
        self._c_decisions.inc()
        # Mirror into the correlated event timeline (hot-path-safe:
        # deque append + subscriber appends) so `obs timeline <rid>`
        # shows admission decisions inline with the engine's events.
        self._timeline.emit(
            decision, request_id=request_id, cause_id=cause_id,
            tenant=tenant_id, **fields,
        )
        if self._sink is not None:
            self._sink.put(record)

    def decisions(self) -> list[dict]:
        """Snapshot of the bounded decision ring (newest last)."""
        with self._lock:
            return list(self._decisions)

    def _on_eviction(self, victim: str, caused_by: str, score: float,
                     restore_bytes: int) -> None:
        """Registry eviction listener: the eviction enters the decision
        trace with its predicted restore cost. Runs under the registry
        lock — bookkeeping only (the ring append and a queue put)."""
        self._c_evict_decisions.inc()
        self._record(
            "evict", victim,
            predicted_s=(
                self.model.restore_s(restore_bytes)
                if self.model is not None else None
            ),
            reason=(
                f"lowest demand-aware victim score ({score:.3f}) making "
                f"headroom for {caused_by}"
            ),
            cause_id=bound_request_id(),
            caused_by=caused_by,
            restore_bytes=restore_bytes,
        )

    # ---- prediction ----

    def _predict_dispatch_s(
        self, engine, b: int, rtol: float | None = None,
    ) -> float | None:
        """Predicted seconds for one ``b``-column dispatch through the
        engine's preferred config — memoized per (engine, bucket,
        storage; an eligible ``rtol`` on a speculative-armed engine
        prices the two-tier expected cost, a distinct memo seat). The
        per-column path models ``b`` sequential single-RHS programs; a
        config the formula cannot express predicts None (admitted, never
        rejected)."""
        if self.model is None:
            return None
        cfg = engine.prediction_config(b, rtol)
        memo_key = (id(engine), cfg["b"], cfg["storage"])
        with self._lock:
            if memo_key in self._predict_memo:
                base = self._predict_memo[memo_key]
                return None if base is None else (
                    base * (b if cfg["b"] == 1 else 1)
                )
        try:
            base = self.model.predict(
                cfg["strategy"], cfg["combine"], m=cfg["m"], k=cfg["k"],
                p=cfg["p"], dtype=cfg["dtype"], stages=cfg["stages"],
                b=cfg["b"], storage=cfg["storage"],
            ).total_s
        except Exception:  # swallow-ok: a formula-less schedule honestly predicts None — absence of a prediction IS the recorded outcome (never a rejection)
            base = None
        with self._lock:
            self._predict_memo[memo_key] = base
        return None if base is None else base * (b if cfg["b"] == 1 else 1)

    def _predict_solver_s(self, engine, op: str, k_est: int,
                          restart: int | None,
                          steps: int | None) -> float | None:
        """Predicted seconds for one served solve through the engine's
        preferred config (``CostModel.predict_solver`` at ``k_est`` =
        the request's maxiter — worst-case, so a deadline reject is
        honest about the cap the caller asked for). Un-memoized on
        purpose: ``k_est`` varies per request and the prediction is pure
        arithmetic. None (admit, never reject) when the formula cannot
        express the config."""
        if self.model is None:
            return None
        cfg = engine.prediction_config(1)
        try:
            return self.model.predict_solver(
                op, cfg["strategy"], cfg["combine"], m=cfg["m"],
                k=cfg["k"], p=cfg["p"], dtype=cfg["dtype"],
                stages=cfg["stages"], storage=cfg["storage"],
                k_est=k_est, restart=restart, steps=steps,
            ).total_s
        except Exception:  # swallow-ok: a formula-less schedule honestly predicts None — absence of a prediction IS the recorded outcome (never a rejection)
            return None

    def _queue_s(self) -> float:
        """Predicted backlog: the sum of the outstanding (not yet done)
        dispatches' predictions. Done futures are swept — a non-blocking
        ``is_ready`` probe per entry."""
        with self._lock:
            self._outstanding = [
                (fut, s) for fut, s in self._outstanding if not fut.done()
            ]
            total = sum(s for _, s in self._outstanding)
        self._g_queue.set(total)
        return total

    def _track(self, fut, predicted_s: float | None) -> None:
        """Track one dispatch in the predicted-backlog window. Greedy
        mode (no model) never consults the backlog, so tracking there
        would only accumulate future references that nothing sweeps
        (_queue_s is the sweeper, and only admission calls it)."""
        if self.model is None:
            return
        with self._lock:
            self._outstanding.append(
                (fut, predicted_s if predicted_s is not None
                 else _FALLBACK_DISPATCH_S)
            )

    # ---- interleaving ----

    def _interleave_threshold(self) -> float:
        if self._interleave_threshold_s is not None:
            return self._interleave_threshold_s
        # Default: the restore cost of a mean-size payload — a dispatch
        # long enough to hide the transfer it would cover.
        with self.registry._lock:
            mean = self.registry._mean_payload_locked()
        return self.model.restore_s(int(mean))

    def _maybe_interleave(self, tenant_id: str,
                          dispatch_s: float | None) -> str | None:
        """Ahead of a predicted-long dispatch, pick the hottest evicted
        tenant and enqueue its swap-in so the restore overlaps under the
        dispatch's compute. Returns the prefetched tenant id (or None).
        The prefetch is enqueue-only (``device_put``); the decision is
        recorded BEFORE it is issued, so the trace shows the swap-in
        ordered ahead of the covering dispatch.

        Damped against thrash: under a full budget every prefetch evicts
        someone, so the swap-in only pays when the evicted candidate's
        demand EXCEEDS the coldest unpinned resident's — otherwise the
        fleet is already placed where the demand is, and "overlap a
        swap" would just churn residencies under the hot set."""
        if self.model is None or dispatch_s is None:
            return None
        if dispatch_s < self._interleave_threshold():
            return None
        best, best_rate = None, 0.0
        coldest_resident = None
        for tid in self.registry.tenant_ids():
            if tid == tenant_id:
                continue
            entry = self.registry._tenants.get(tid)
            if entry is None:
                continue
            rate = entry.rate.rate_per_s()
            if entry.engine.resident:
                if not entry.pinned and (
                    coldest_resident is None or rate < coldest_resident
                ):
                    coldest_resident = rate
            elif rate > best_rate:
                best, best_rate = tid, rate
        if best is None:
            return None
        if coldest_resident is not None and best_rate <= coldest_resident:
            return None  # placement already follows demand: don't churn
        entry = self.registry._tenants.get(best)
        if entry is None:
            return None  # raced an unregister between scan and pick
        restore = entry.engine.resident_bytes
        self._c_interleaves.inc()
        self._record(
            "interleave", best,
            predicted_s=self.model.restore_s(restore),
            reason=(
                f"swap-in ({best_rate:.2f} req/s demand) overlapped "
                f"under {tenant_id}'s {dispatch_s * 1e3:.3f} ms dispatch"
            ),
            cause_id=bound_request_id(),
            under=tenant_id,
            restore_bytes=restore,
        )
        try:
            self.registry.prefetch(best, protect=tenant_id)
        except ConfigError:
            return None  # the tenant was unregistered mid-decision
        return best

    # ---- online resharding ----

    def _maybe_reshard(self, tenant_id: str, width: int,
                       dispatch_s: float | None) -> str | None:
        """The ``reshard="auto"`` crossover trigger (docs/RESHARDING.md):
        migrate ``tenant_id``'s resident ``A`` to the layout whose
        predicted per-request dispatch, plus the migration cost
        amortized over the EWMA demand horizon, beats the current
        layout's. Pure prediction — the candidate times come from
        ``CostModel.predict`` and the migration from
        ``predict_reshard``; nothing is measured. Returns the
        destination strategy name when a migration was triggered.

        Damped three ways: a per-tenant cooldown (oscillating demand
        must not thrash layouts), the amortization itself (a cold
        tenant's horizon carries too few requests to pay for the
        collectives), and the strict inequality (ties keep the current
        layout). The migration runs synchronously on THIS admission's
        thread — one request pays the swap latency, and the trace shows
        exactly which one — with ``warm_widths`` forwarding so the
        new-layout compile also lands here, never on steady-state
        requests."""
        if self._reshard != "auto" or self.model is None:
            return None
        if dispatch_s is None:
            return None  # formula-less config: nothing to compare
        entry = self.registry._tenants.get(tenant_id)
        if entry is None:
            return None
        engine = entry.engine
        if not engine.resident or getattr(engine, "resharding", False):
            return None
        now = self._clock()
        with self._lock:
            last = self._last_reshard.get(tenant_id)
            if last is not None and now - last < self._reshard_cooldown_s:
                return None
        rate = entry.rate.rate_per_s()
        horizon_n = rate * self._reshard_horizon_s
        if horizon_n < 1.0:
            return None  # no demand to amortize the collectives over
        from ..models import get_strategy
        from ..parallel.reshard import RESHARD_STRATEGIES

        cfg = engine.prediction_config(width)
        src = cfg["strategy"]
        if src not in RESHARD_STRATEGIES:
            return None  # custom strategy instance: no migration program
        best = None  # (total_s, dst, new_s, migrate_s)
        for dst in RESHARD_STRATEGIES:
            if dst == src:
                continue
            try:
                combine = get_strategy(dst).default_combine(engine.mesh)
                base = self.model.predict(
                    dst, combine, m=cfg["m"], k=cfg["k"], p=cfg["p"],
                    dtype=cfg["dtype"], b=cfg["b"], storage=cfg["storage"],
                ).total_s
                migrate_s = self.model.predict_reshard(
                    src, dst, m=cfg["m"], k=cfg["k"], p=cfg["p"],
                    dtype=cfg["dtype"],
                ).total_s
            except Exception:  # swallow-ok: a formula-less candidate honestly drops out of the comparison, exactly like _predict_dispatch_s's None
                continue
            new_s = base * (width if cfg["b"] == 1 else 1)
            total = new_s + migrate_s / horizon_n
            if best is None or total < best[0]:
                best = (total, dst, new_s, migrate_s)
        if best is None or best[0] >= dispatch_s:
            return None  # current layout already wins the horizon
        _total, dst, new_s, migrate_s = best
        with self._lock:
            self._last_reshard[tenant_id] = now
        self._c_reshard_decisions.inc()
        self._record(
            "reshard", tenant_id,
            predicted_s=migrate_s,
            cause_id=bound_request_id(),
            reason=(
                f"crossover: {dst} predicts {new_s * 1e3:.3f} ms/req vs "
                f"{src} {dispatch_s * 1e3:.3f} ms, and the "
                f"{migrate_s * 1e3:.3f} ms migration amortizes over "
                f"~{horizon_n:.0f} requests ({rate:.2f} req/s x "
                f"{self._reshard_horizon_s:.0f} s horizon)"
            ),
            src=src, dst=dst, old_s=dispatch_s, new_s=new_s,
            migrate_s=migrate_s, horizon_requests=horizon_n,
        )
        try:
            self.registry.reshard(
                tenant_id, dst,
                warm_widths=(1,) if width == 1 else (1, width),
            )
        except ConfigError:
            return None  # unregistered/evicted mid-decision: traced, not fatal
        finally:
            # The memo keys omit the strategy on purpose (one seat per
            # engine identity); a migration makes them stale, so the
            # engine's seats drop and re-predict under the new layout.
            with self._lock:
                self._predict_memo = {
                    key: s for key, s in self._predict_memo.items()
                    if key[0] != id(engine)
                }
        return dst

    # ---- admission & dispatch ----

    def submit(
        self,
        tenant_id: str,
        x=None,
        *,
        deadline_ms: float | None = None,
        qos: str = "standard",
        op: str = "matvec",
        rhs=None,
        rtol: float | None = None,
        maxiter: int | None = None,
        restart: int | None = None,
        steps: int | None = None,
        interval: tuple[float, float] | None = None,
    ):
        """Admit one request for ``tenant_id`` — a ``(k,)`` vector or
        ``(k, b)`` block. Calibrated + deadlined: the queue-aware ETA is
        checked first and an infeasible request fails fast with
        :class:`AdmissionRejectedError` (no dispatch, no eviction
        pressure). Admitted requests dispatch WITHOUT a deadline —
        admission owns it (module docstring). Uncalibrated: greedy —
        everything passes through with its deadline intact for the
        engine's own gate.

        A solver ``op`` (``MatvecEngine.submit(op=...)`` semantics —
        ``rhs``/``rtol``/``maxiter``/``restart``/``steps``/``interval``
        pass through) is admitted against
        :meth:`~..tuning.cost_model.CostModel.predict_solver` at ``k_est
        = maxiter`` and dispatched solo: a solve is one loop against one
        RHS, so cross-tenant column-stacking does not apply — solver
        requests bypass the coalescing layer entirely.

        A MATVEC request declaring ``rtol`` (the speculative contract —
        ``MatvecEngine.submit(rtol=...)``) passes it through and also
        bypasses coalescing: the fused acceptance check carries ONE
        tolerance per dispatch, and stacking members with different
        budgets would verify every column against the tightest. The
        admission prediction prices such a request as
        ``storage="speculate"`` when the tenant's engine is armed."""
        if qos not in QOS_TIERS:
            raise ConfigError(
                f"unknown QoS tier {qos!r}; expected one of {QOS_TIERS}"
            )
        if self._closed:
            raise ConfigError("global scheduler is closed")
        if op != "matvec":
            return self._submit_solver_op(
                tenant_id, x, deadline_ms=deadline_ms, op=op, rhs=rhs,
                rtol=rtol, maxiter=maxiter, restart=restart, steps=steps,
                interval=interval,
            )
        entry = self.registry._entry(tenant_id)
        engine = entry.engine
        block = np.asarray(x, dtype=engine.dtype)  # sync-ok: requests are host arrays (engine contract)
        vector = block.ndim == 1
        if block.ndim not in (1, 2) or block.shape[0] != engine.k or (
            block.ndim == 2 and block.shape[1] == 0
        ):
            raise ConfigError(
                f"request must be (k,) or (k, b) with k={engine.k}; got "
                f"shape {block.shape}"
            )
        if vector:
            block = block[:, None]
        width = block.shape[1]
        # One correlation id per admitted request: every decision line,
        # timeline event, and (via bind_request around the dispatch
        # chain) the engine's own trace share it.
        rid = next_request_id()

        dispatch_s = self._predict_dispatch_s(engine, width, rtol)
        if self.model is not None:
            from ..tuning.cost_model import AdmissionEstimate

            queue_s = self._queue_s()
            swap_bytes = 0 if engine.resident else engine.resident_bytes
            swap_s = self.model.restore_s(swap_bytes) if swap_bytes else 0.0
            # One ETA formula in the repo: AdmissionEstimate composes the
            # terms (the dispatch prediction itself is memoized here, so
            # this is the dataclass, not a re-prediction).
            est = (
                AdmissionEstimate(
                    dispatch_s=dispatch_s, queue_s=queue_s, swap_s=swap_s
                )
                if dispatch_s is not None else None
            )
            eta_s = est.eta_s if est is not None else None
            if deadline_ms is not None and (
                deadline_ms <= 0
                or (
                    eta_s is not None
                    and eta_s * 1e3 > deadline_ms * self.deadline_margin
                )
            ):
                # Reject fast: typed, pre-dispatch, traced. Rejected
                # demand still ticks the tenant's rate estimator — its
                # residency is what would fix the refusals.
                self.registry.observe_demand(tenant_id)
                self._c_rejects.inc()
                reason = (
                    "deadline elapsed before admission"
                    if deadline_ms <= 0 else
                    f"predicted eta {eta_s * 1e3:.3f} ms (queue "
                    f"{queue_s * 1e3:.3f} + swap {swap_s * 1e3:.3f} + "
                    f"dispatch {dispatch_s * 1e3:.3f}) > deadline "
                    f"{deadline_ms:.3f} ms"
                )
                self._record(
                    "reject", tenant_id, predicted_s=dispatch_s,
                    reason=reason, request_id=rid, eta_s=eta_s,
                    queue_s=queue_s, deadline_ms=deadline_ms,
                )
                return MatvecFuture.failed(AdmissionRejectedError(
                    f"request for tenant {tenant_id!r} rejected at "
                    f"admission: {reason}"
                ))
            if dispatch_s is not None:
                self._h_predicted.observe(dispatch_s * 1e3)
            self._record(
                "admit", tenant_id, predicted_s=dispatch_s,
                reason=(
                    "uncalibrated config: admitted without a prediction"
                    if dispatch_s is None else
                    f"predicted eta "
                    f"{(eta_s if eta_s is not None else dispatch_s) * 1e3:.3f}"
                    f" ms within "
                    + (f"deadline {deadline_ms:.3f} ms"
                       if deadline_ms is not None else "no deadline")
                ),
                request_id=rid, eta_s=eta_s, queue_s=queue_s,
                deadline_ms=deadline_ms,
            )
            with bind_request(rid):
                # Bound so consequences (evictions under prefetch, the
                # reshard migration) record cause_id=rid.
                self._maybe_interleave(tenant_id, dispatch_s)
                if self._maybe_reshard(tenant_id, width, dispatch_s):
                    # The migrated layout serves THIS request too:
                    # re-predict so the backlog window charges the new
                    # config's time.
                    dispatch_s = self._predict_dispatch_s(
                        engine, width, rtol
                    )
            # Admission owns the deadline from here (module docstring).
            engine_deadline = None
        else:
            # Greedy degrade: admit, deadline handed through to the
            # engine's own gate, decision still traced (predicted_s is
            # honestly None — and never a reason to reject).
            self._c_admits.inc()
            self._record(
                "admit", tenant_id, predicted_s=None,
                reason="greedy admission (cost model uncalibrated)",
                request_id=rid, deadline_ms=deadline_ms,
            )
            with bind_request(rid):
                fut = self.registry.submit(
                    tenant_id, x, deadline_ms=deadline_ms, rtol=rtol
                )
            self._track(fut, None)
            return fut

        self._c_admits.inc()
        if not self._coalesce or rtol is not None:
            # rtol requests dispatch solo (docstring: one tolerance per
            # fused check) — speculation and coalescing don't stack.
            with bind_request(rid):
                fut = self.registry.submit(
                    tenant_id, x, deadline_ms=engine_deadline, rtol=rtol
                )
            self._track(fut, dispatch_s)
            return fut
        return self._enqueue_coalesced(
            tenant_id, block, vector, width, dispatch_s, rid,
            flush_now=deadline_ms is not None or qos == "interactive",
        )

    def _submit_solver_op(
        self, tenant_id: str, x, *, deadline_ms, op, rhs, rtol, maxiter,
        restart, steps, interval,
    ):
        """The solver ops' admission + dispatch: same predicted-time gate
        as the matvec path with :meth:`_predict_solver_s` supplying the
        dispatch term, no coalescing (one loop, one RHS). Shape/alias
        validation stays the engine's (``_submit_solver``) — the
        scheduler forwards ``x``/``rhs`` untouched so ``submit(x,
        rhs=...)`` double-supply raises the engine's typed error, not a
        scheduler-shaped one."""
        entry = self.registry._entry(tenant_id)
        engine = entry.engine
        kwargs = dict(
            op=op, rhs=rhs, rtol=rtol, maxiter=maxiter,
            restart=restart, steps=steps, interval=interval,
        )
        rid = next_request_id()
        if self.model is None:
            self._c_admits.inc()
            self._record(
                "admit", tenant_id, predicted_s=None,
                reason="greedy admission (cost model uncalibrated)",
                request_id=rid, deadline_ms=deadline_ms, op=op,
            )
            with bind_request(rid):
                fut = self.registry.submit(
                    tenant_id, x, deadline_ms=deadline_ms, **kwargs
                )
            self._track(fut, None)
            return fut

        from .core import DEFAULT_SOLVER_MAXITER
        from ..tuning.cost_model import AdmissionEstimate

        k_est = maxiter if maxiter is not None else DEFAULT_SOLVER_MAXITER
        dispatch_s = self._predict_solver_s(engine, op, k_est, restart,
                                            steps)
        queue_s = self._queue_s()
        swap_bytes = 0 if engine.resident else engine.resident_bytes
        swap_s = self.model.restore_s(swap_bytes) if swap_bytes else 0.0
        est = (
            AdmissionEstimate(
                dispatch_s=dispatch_s, queue_s=queue_s, swap_s=swap_s
            )
            if dispatch_s is not None else None
        )
        eta_s = est.eta_s if est is not None else None
        if deadline_ms is not None and (
            deadline_ms <= 0
            or (
                eta_s is not None
                and eta_s * 1e3 > deadline_ms * self.deadline_margin
            )
        ):
            self.registry.observe_demand(tenant_id)
            self._c_rejects.inc()
            reason = (
                "deadline elapsed before admission"
                if deadline_ms <= 0 else
                f"predicted {op} eta {eta_s * 1e3:.3f} ms at "
                f"maxiter={k_est} (queue {queue_s * 1e3:.3f} + swap "
                f"{swap_s * 1e3:.3f} + solve {dispatch_s * 1e3:.3f}) > "
                f"deadline {deadline_ms:.3f} ms"
            )
            self._record(
                "reject", tenant_id, predicted_s=dispatch_s,
                reason=reason, request_id=rid, eta_s=eta_s,
                queue_s=queue_s, deadline_ms=deadline_ms, op=op,
            )
            return MatvecFuture.failed(AdmissionRejectedError(
                f"request for tenant {tenant_id!r} rejected at "
                f"admission: {reason}"
            ))
        if dispatch_s is not None:
            self._h_predicted.observe(dispatch_s * 1e3)
        self._record(
            "admit", tenant_id, predicted_s=dispatch_s,
            reason=(
                "uncalibrated config: admitted without a prediction"
                if dispatch_s is None else
                f"predicted {op} eta "
                f"{(eta_s if eta_s is not None else dispatch_s) * 1e3:.3f}"
                f" ms (maxiter={k_est}) within "
                + (f"deadline {deadline_ms:.3f} ms"
                   if deadline_ms is not None else "no deadline")
            ),
            request_id=rid, eta_s=eta_s, queue_s=queue_s,
            deadline_ms=deadline_ms, op=op,
        )
        self._c_admits.inc()
        with bind_request(rid):
            self._maybe_interleave(tenant_id, dispatch_s)
            # Admission owns the deadline from here (module docstring).
            fut = self.registry.submit(
                tenant_id, x, deadline_ms=None, **kwargs
            )
        self._track(fut, dispatch_s)
        return fut

    def __call__(self, tenant_id: str, x) -> np.ndarray:
        """Synchronous convenience: ``submit(tenant_id, x).result()``."""
        return self.submit(tenant_id, x).result()

    # ---- coalescing ----

    def _resolved_flush_width(self, engine) -> int:
        if self._flush_width is not None:
            return self._flush_width
        b_star = engine.b_star
        return b_star if b_star is not None else DEFAULT_PROMOTE_B

    def _enqueue_coalesced(self, tenant_id, block, vector, width,
                           dispatch_s, rid, flush_now: bool):
        # Members reach registry.submit only through the flush OWNER, so
        # their demand estimators would under-tick (the eviction score's
        # input); tick each member here instead. The owner gets one
        # extra tick per flush from registry.submit — a bounded
        # overcount that never changes a hot/cold ranking.
        self.registry.observe_demand(tenant_id)
        group = self.registry.coalesce_group(tenant_id)
        fut = _GsSlice(self, vector, width)
        member = _PendingMember(tenant_id, block, width, fut, rid)
        engine = self.registry._entry(tenant_id).engine
        batch = None
        with self._lock:
            if self._pending and self._pending_group != group:
                # Order preservation: a different group's arrival closes
                # the open batch first.
                batch = self._swap_batch_locked()
            self._pending.append(member)
            self._pending_group = group
            self._pending_width += width
            if (
                flush_now
                or self._pending_width >= self._resolved_flush_width(engine)
            ):
                own = self._swap_batch_locked()
            else:
                own = None
        if batch is not None:
            self._flush_batch(batch)
        if own is not None:
            self._flush_batch(own)
        return fut

    def _swap_batch_locked(self) -> list[_PendingMember] | None:
        if not self._pending:
            return None
        batch = self._pending
        self._pending = []
        self._pending_group = None
        self._pending_width = 0
        return batch

    def _flush_batch(self, batch: list[_PendingMember]) -> None:
        """Dispatch one swapped-out batch as ONE registry submit through
        the first member's tenant (the flush owner — its residency and
        hit accounting absorb the dispatch). Runs with no scheduler lock
        held. Cross-tenant members are counted; per-member futures
        resolve to their own columns of the shared result."""
        owner = batch[0].tenant_id
        stacked = (
            batch[0].block if len(batch) == 1
            else np.concatenate([m.block for m in batch], axis=1)
        )
        width = stacked.shape[1]
        owner_engine = self.registry._entry(owner).engine
        predicted = self._predict_dispatch_s(owner_engine, width)
        cross = sum(1 for m in batch if m.tenant_id != owner)
        if cross:
            self._c_cross_tenant.inc(cross + 1)  # every sharing member
        self._c_flushes.inc()
        # One fresh id per flushed batch; `members` lets the timeline
        # walk from any member's rid to the batch and back.
        batch_rid = next_request_id()
        self._record(
            "flush", owner, predicted_s=predicted,
            reason=(
                f"{len(batch)} request(s), {width} column(s)"
                + (f", {cross} from other tenants" if cross else "")
            ),
            request_id=batch_rid, members=[m.rid for m in batch],
            n_requests=len(batch), width=width,
        )
        try:
            with bind_request(batch_rid):
                inner = self.registry.submit(owner, stacked)
        except Exception as e:  # swallow-ok: the failure is parked in every member's future via MatvecFuture.failed — callers re-raise it at result()
            shared = _SharedResult(MatvecFuture.failed(e))
        else:
            self._track(inner, predicted)
            shared = _SharedResult(inner)
        offset = 0
        for m in batch:
            m.future._resolve(shared, offset)
            offset += m.width

    def flush(self) -> int:
        """Dispatch the open batch now (driver/drain code). Returns the
        number of requests flushed."""
        with self._lock:
            batch = self._swap_batch_locked()
        if batch is None:
            return 0
        self._flush_batch(batch)
        return len(batch)

    # ---- lifecycle ----

    def close(self) -> None:
        """Flush the open batch, stop accepting submits, release the
        decision sink. Does NOT close the registry."""
        if self._closed:
            return
        self.flush()
        self._closed = True
        if self._sink is not None:
            self._sink.close()

    def __enter__(self) -> "GlobalScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

"""MatvecEngine: batched multi-RHS dispatch against a resident sharded A.

The paper's benchmark shape is one ``y = A·x`` at a time; the serving shape
(ROADMAP north star) is a *stream* of right-hand sides against a matrix
that never moves. The engine holds ``A`` resident in its strategy sharding
and serves requests through three mechanisms:

* **shape buckets** (``buckets.py``) — request widths quantize to a
  power-of-two ladder, so a mixed-width stream maps onto a bounded
  executable set;
* **AOT executable cache** (``executables.py``) — every (strategy × kernel
  × combine × bucket × dtype) program is ``lower().compile()``d exactly
  once, with the RHS buffer donated; after warmup the hot loop never
  traces, never compiles, and never host-syncs;
* **GEMV→GEMM promotion** — a batch of ``b ≥ b*`` right-hand sides rides
  the strategy's sharded program as ONE block GEMM
  (``MatvecStrategy.build_batched``; the MXU-bound formulation of "Large
  Scale Distributed Linear Algebra With TPUs", PAPERS.md) instead of ``b``
  GEMV dispatches; the crossover ``b*`` is the autotuner's fourth measured
  axis (``tuning/search.py::tune_promotion``), consulted per (strategy,
  shape, mesh, dtype) when ``promote="auto"``.

``submit`` returns a :class:`MatvecFuture` immediately — dispatch is
enqueue-only (JAX arrays are async by construction) and the host sync
happens only when the caller materializes the result. The dispatch path is
lint-enforced sync-free (``tests/test_lint.py``, ``scripts/tier1.sh``),
with one caller-opted exception: ``max_in_flight`` bounds the outstanding
dispatch window, and at the high-water mark ``submit`` blocks draining the
OLDEST dispatch (marked ``sync-ok``) instead of enqueueing unboundedly
ahead of the device. A per-request ``deadline_ms`` fails the future at
that gate rather than dispatching stale work; both are counted in
:class:`EngineStats` next to the compile/hit counters.

Each ``submit`` dispatches alone; coalescing *concurrent* requests into
one wider dispatch — the continuous-batching layer — is
``scheduler.py``'s job, stacked in front of this class.

Requests are HOST arrays (numpy): the engine owns host→device placement,
including dtype normalization and bucket padding. Handing it a device
array still works but the normalization copy becomes a device fetch —
a caller-visible sync the serving contract does not make.

Telemetry (``obs/``): every counter the engine reports lives in a
:class:`~..obs.registry.MetricsRegistry` (:class:`EngineStats` is a
point-in-time view over it — one source of truth, atomic under the
submit/materialize thread split), and every request records a span tree
(submit → gate → bucket_pad → exec_lookup → dispatch → materialize) into
the tracer's ring buffer — and, when ``trace_jsonl`` is set, onto the sink
thread's JSONL file. Recording is lock-free on the dispatch path (list
mutation + queue put; see ``obs/tracing.py``), and the I/O lint
(``tests/test_lint.py``) keeps blocking file writes off this module
entirely.

Fault tolerance (``resilience/``; full doctrine in docs/RESILIENCE.md):
with a :class:`~..resilience.ResiliencePolicy` the engine stops treating
a compile/dispatch exception as the request's fate. Each dispatch walks a
**degradation ladder** of config levels — the preferred (strategy ×
kernel × combine@S) program first, then the safe un-staged ``xla`` tier,
and for block requests the per-column GEMV floor — with a per-ExecKey
**circuit breaker** gating each level (repeated failure of an exotic
config opens its breaker, so later requests skip straight to the
fallback; after the cooldown one request probes the preferred config and
a success restores it). *Retryable* faults get bounded backoff retries
within a level; RESOURCE_EXHAUSTED on a block dispatch shrinks the
bucket (two half-width dispatches) instead. Every reroute is counted
(``resil_*`` metrics) and visible in :meth:`MatvecEngine.health`. A
seeded :class:`~..resilience.FaultPlan` hooks the compile and dispatch
sites so all of this is deterministically testable; an optional
NaN/Inf **integrity gate** at materialization refuses to serve corrupt
results. All of it is pay-for-what-you-use: with no policy, no plan and
no gate, the dispatch path is byte-for-byte the old one.

Multi-tenant residency (``registry.py``; docs/MULTITENANT.md): a
registry-managed engine is ONE tenant's serving instance. Three hooks
make that composition work without touching the dispatch doctrine:

* **releasable residency** — ``retain_host=True`` keeps the host payload
  (the original ``A``, plus the quantized pytree under quantized
  storage), so :meth:`release_residency` can drop the device arrays (a
  pure reference drop: in-flight dispatches hold their own references,
  so eviction never syncs and never corrupts outstanding work) and
  :meth:`ensure_resident` can re-place them — ``device_put`` is
  enqueue-only, so a swap-in overlaps under other tenants' dispatches
  exactly like the staged transfers in ``parallel/ring.py`` overlap
  under the next stage's compute. Re-placement of the SAME host bytes
  through the SAME executable is bitwise-identical by construction.
* **residency accounting** — every change to the engine's device-A
  footprint (placement, release, and the degradation ladder's lazily
  placed native safe tier, which used to allocate outside any
  accounting) reports through ``residency_listener(delta_bytes,
  reason)`` — the registry's HBM accountant charges through it.
* **tenant-scoped identity** — ``label_prefix="tenant-7/"`` prefixes
  every fault-site label, so a chaos plan can target one tenant
  (``--fault-spec 'dispatch:device_error:key=tenant-7/*'``) while
  breakers, degradation state and the integrity gate are per-engine and
  therefore per-tenant already.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Callable, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..models import get_strategy
from ..models.base import (
    STORAGE_INCOMPATIBLE_COMBINES,
    MatvecStrategy,
    mesh_size,
)
from ..obs.registry import MetricsRegistry
from ..ops.quantize import (
    NATIVE,
    default_block,
    fp8_supported,
    normalize_storage,
    quantize_matrix,
    quantized_like,
)
from ..ops.speculative import (
    SPEC_RTOL_FLOOR,
    build_speculative,
    eligible as spec_eligible,
    probe_count,
    probe_matrix,
    project_probes,
)
from ..obs.sink import JsonlSink
from ..obs.timeline import (
    TimelineHub,
    bind_request,
    bound_request_id,
    get_hub,
    next_request_id,
)
from ..obs.tracing import ActiveTrace, RequestTracer
from ..resilience.faults import (
    FaultPlan,
    ResultIntegrityError,
    is_payload_fault,
    refuse_nonfinite,
)
from ..resilience.policy import (
    BREAKER_CLOSED,
    CircuitBreaker,
    ResiliencePolicy,
    classify_failure,
)
from ..solvers import (
    DEFAULT_RESTART,
    DEFAULT_STEPS,
    SOLVER_OPS,
    SolverResult,
    build_solver,
    solver_bucket,
)
from ..utils.errors import (
    ConfigError,
    DeadlineExceededError,
    ResidencyError,
    SolverDivergedError,
)
from .buckets import (
    DEFAULT_MAX_BUCKET,
    bucket_for,
    bucket_ladder,
    pad_columns,
    split_widths,
)
from .executables import DONATE_ARGNUMS, ExecKey, ExecStats, ExecutableCache

# The degradation floor's local kernel: the portable tier every backend
# compiles (the pallas/native tiers are exactly the exotic configs a
# breaker may be routing around).
SAFE_KERNEL = "xla"

# The speculative tier's vocabulary (docs/QUANTIZATION.md "speculative
# serving"): SPECULATE is the storage label speculative ExecKeys carry —
# never a resident FORMAT; a speculative engine's own storage stays
# native so rtol=None requests are bitwise-identical to a plain engine —
# and SPEC_STORAGE is the format the speculative resident quantizes to
# (the compensated pair: ~1e-6 normwise error at 0.52x the bytes, the
# tier the whole speculation exists to serve from).
SPECULATE = "speculate"
SPEC_STORAGE = "int8c"

# Static promotion default on a tuning-cache miss: one GEMM dispatch
# replaces 4+ GEMV dispatches. Conservative on purpose — at b=4 the block
# re-reads A once instead of 4 times, so even bandwidth-bound shapes win,
# while b=2 can sit inside measurement noise on fast local backends.
DEFAULT_PROMOTE_B = 4

# Iteration cap when a solver submit leaves ``maxiter`` unset — generous
# enough for the well-conditioned serving regime, small enough that a
# diverging solve fails typed in bounded time (docs/SOLVERS.md).
DEFAULT_SOLVER_MAXITER = 1000


class MatvecFuture:
    """Async handle to one request's result.

    Holds the device arrays the dispatch produced (padded, when the GEMM
    path ran) plus the real column counts; materialization slices the pad
    columns away — the "masked-result unpad". ``result()`` host-syncs by
    definition (that is what materializing means); everything up to it is
    free of host round-trips.
    """

    def __init__(
        self,
        parts: Sequence[tuple],
        vector: bool,
        trace: ActiveTrace | None = None,
        materialize_hist=None,
        integrity_counter=None,
        timeline: "TimelineHub | None" = None,
    ):
        # parts: (device_array, width[, corrupt[, accept, resolve]]) —
        # width=None marks a rank-1 single column; an int marks a rank-2
        # block whose first `width` columns are real (the rest is bucket
        # padding). corrupt marks a part an injected "nan" fault poisons
        # at materialization (resilience/faults.py — simulated silent
        # device corruption). accept/resolve mark a SPECULATIVE part
        # (docs/QUANTIZATION.md): accept is the on-device verdict of the
        # fused acceptance check, and resolve(accepted) is the engine's
        # settlement callback — bookkeeping on accept, the traced native
        # re-dispatch (its replacement parts) on a miss.
        self._parts = [
            (
                p[0], p[1], bool(p[2]) if len(p) > 2 else False,
                p[3] if len(p) > 4 else None,
                p[4] if len(p) > 4 else None,
            )
            for p in parts
        ]
        # Speculative settlement is memoized: a second result() call
        # re-materializes but must not re-read verdicts or re-escalate.
        self._settled: list[tuple] | None = None
        self._vector = vector
        self._error: Exception | None = None
        # Set once result() has returned (or raised): the caller has
        # consumed this future, so it no longer holds un-materialized
        # result buffers — the registry's per-tenant max_in_flight quota
        # counts futures with retired=False (engine/registry.py).
        self.retired = False
        # Request-lifecycle trace: opened by submit, completed here — the
        # materialize span and the finish that emits the record both run on
        # whichever thread materializes (sequential hand-off; tracing.py).
        self._trace = trace
        self._materialize_hist = materialize_hist
        # Non-None enables the NaN/Inf integrity gate: result() refuses to
        # return a non-finite block (ResultIntegrityError), counting here.
        self._integrity_counter = integrity_counter
        # Correlated event hub: the integrity refusal below is a typed
        # failure the flight recorder triggers on, so it must appear on
        # the timeline with this request's id.
        self._timeline = timeline

    @classmethod
    def failed(
        cls, error: Exception, trace: ActiveTrace | None = None
    ) -> "MatvecFuture":
        """A future that was never dispatched (deadline exceeded):
        ``result()`` raises ``error``, ``done()`` is immediately True."""
        fut = cls([], vector=True, trace=trace)
        fut._error = error
        return fut

    def device_values(self) -> list[jax.Array]:
        """The raw (still padded) device arrays — for callers chaining
        device-side work without materializing (empty for a failed
        future). For a speculative part this is the CANDIDATE (the
        verdict is only read at materialization)."""
        return [arr for arr, *_ in self._parts]

    def done(self) -> bool:
        """True when every part's device computation has completed (never
        blocks). A failed future is done by definition."""
        return all(
            bool(arr.is_ready()) if hasattr(arr, "is_ready") else True
            for arr, *_ in self._parts
        )

    def exception(self) -> Exception | None:
        """The failure this future carries (DeadlineExceededError), or
        None for a dispatched request."""
        return self._error

    @staticmethod
    def _host_part(arr, corrupt: bool) -> np.ndarray:
        """Host copy of one part, with injected NaN corruption applied —
        the simulated silent device fault lands in element [0] / [0, 0]
        of the part (one real column), exactly what the integrity gate
        exists to catch."""
        host = np.asarray(arr)  # sync-ok: caller-requested materialization
        if corrupt and np.issubdtype(host.dtype, np.floating):
            host = np.array(host)  # sync-ok: host-side copy of a host array (corruption needs a writable buffer)
            host[(0, 0) if host.ndim > 1 else 0] = np.nan
        return host

    def _gate(self, out: np.ndarray) -> np.ndarray:
        """The optional NaN/Inf integrity gate: a corrupt result raises
        instead of being served (silent corruption becomes a loud,
        retryable failure). The refusal is cached like any other future
        failure — a second result() raises it again without re-counting,
        and exception() reports it."""
        if self._integrity_counter is not None:
            err = refuse_nonfinite(
                out, self._integrity_counter,
                "the materialized result block",
            )
            if err is not None:
                self._error = err
                if self._timeline is not None:
                    self._timeline.emit(
                        "integrity_refused",
                        request_id=(
                            self._trace.request_id
                            if self._trace is not None else None
                        ),
                    )
                raise err
        return out

    def _resolve_parts(self) -> list[tuple]:
        """Settle every speculative verdict ONCE (memoized): read each
        speculative part's device accept predicate — the one host read
        the speculative path adds, and it happens here because result()
        is the engine's sync point by contract — and either keep the
        verified candidate or splice in the parts of the engine's traced
        native re-dispatch (``resolve(False)``; span kind=escalate).
        Plain parts pass through untouched."""
        if self._settled is None:
            settled: list[tuple] = []
            for arr, width, corrupt, accept, resolve in self._parts:
                if accept is None:
                    settled.append((arr, width, corrupt))
                    continue
                ok = bool(np.asarray(accept))  # sync-ok: caller-requested materialization (the speculative verdict settles here by design)
                if ok:
                    resolve(True)
                    settled.append((arr, width, corrupt))
                else:
                    settled.extend(
                        (p[0], p[1], p[2]) for p in resolve(False)
                    )
            self._settled = settled
        return self._settled

    def result(self) -> np.ndarray:
        """Materialize on host: ``(m,)`` for a vector request, ``(m, b)``
        for a block request (pad columns sliced away). A failed future
        raises its error instead. Records the ``materialize`` span and
        finishes the request's trace (idempotent — a second call
        re-materializes but never re-emits)."""
        if self._error is not None:
            self.retired = True
            raise self._error
        trace = self._trace
        t0 = time.perf_counter()
        span = trace.span("materialize") if trace is not None else None
        status = "ok"
        try:
            parts = self._resolve_parts()
            if self._vector:
                arr, _, corrupt = parts[0]
                return self._gate(self._host_part(arr, corrupt))
            cols = []
            for arr, width, corrupt in parts:
                host = self._host_part(arr, corrupt)
                cols.append(
                    host[:, None] if width is None else host[:, :width]
                )
            return self._gate(
                cols[0] if len(cols) == 1
                else np.concatenate(cols, axis=1)
            )
        except ResultIntegrityError:
            status = "integrity_failed"
            raise
        except BaseException:
            # A device error surfacing at the host fetch must not be
            # recorded as a fast successful request.
            status = "materialize_error"
            raise
        finally:
            self.retired = True
            if span is not None:
                span.__exit__(None, None, None)
                trace.finish(status=status)
            if self._materialize_hist is not None and status == "ok":
                self._materialize_hist.observe(
                    (time.perf_counter() - t0) * 1e3
                )


class SolverFuture:
    """Async handle to one served solve (``engine.submit(op="cg", ...)``).

    Mirrors :class:`MatvecFuture`'s face — ``done()`` / ``exception()`` /
    ``result()`` / ``retired`` — so the tenant registry's quota
    accounting and the global scheduler's tracking duck-type over both.
    What differs is the contract: ``result()`` materializes a
    :class:`~..solvers.common.SolverResult` and either returns a
    CONVERGED answer or raises a typed error — ``SolverDivergedError``
    when the compiled loop hit its iteration cap still above tolerance,
    ``ResultIntegrityError``/``SolverDivergedError`` when the answer is
    non-finite. An unconverged or corrupt ``x`` is never returned: for a
    multiply a wrong block is the caller's to validate, but a solver's
    whole point is the answer, so the refusal is unconditional (not
    gated behind ``integrity_gate``)."""

    def __init__(
        self,
        res: SolverResult,
        op: str,
        rtol: float,
        cap: int,
        trace: ActiveTrace | None = None,
        corrupt: bool = False,
        materialize_hist=None,
        integrity_counter=None,
        iter_hist=None,
        divergence_counter=None,
        residual_gauge=None,
        iter_time_hist=None,
        dispatch_t0: float | None = None,
        timeline: "TimelineHub | None" = None,
    ):
        self._res = res
        self.op = op
        self._rtol = rtol
        self._cap = cap  # maxiter (lanczos: its static step count)
        self._corrupt = bool(corrupt)
        self._error: Exception | None = None
        self.retired = False
        self._trace = trace
        self._materialize_hist = materialize_hist
        self._integrity_counter = integrity_counter
        self._iter_hist = iter_hist
        self._divergence_counter = divergence_counter
        self._residual_gauge = residual_gauge
        self._iter_time_hist = iter_time_hist
        self._dispatch_t0 = dispatch_t0
        self._timeline = timeline

    def _emit_failure(self, kind: str, **fields) -> None:
        """Put one typed-failure event on the timeline (the flight
        recorder's trigger vocabulary), correlated to this solve."""
        if self._timeline is not None:
            self._timeline.emit(
                kind,
                request_id=(
                    self._trace.request_id
                    if self._trace is not None else None
                ),
                op=self.op, **fields,
            )

    @classmethod
    def failed(
        cls, error: Exception, trace: ActiveTrace | None = None
    ) -> "SolverFuture":
        """A solve that was never dispatched (deadline/admission):
        ``result()`` raises ``error``, ``done()`` is immediately True."""
        fut = cls(None, op="", rtol=0.0, cap=0, trace=trace)
        fut._error = error
        return fut

    def done(self) -> bool:
        if self._res is None:
            return True
        arr = self._res.x
        return bool(arr.is_ready()) if hasattr(arr, "is_ready") else True

    def exception(self) -> Exception | None:
        return self._error

    def result(self) -> SolverResult:
        """Materialize the solve on host: a :class:`SolverResult` whose
        ``x`` is a numpy array and whose telemetry fields are Python
        scalars. Raises :class:`SolverDivergedError` if the loop exited
        on its cap (the partial iterate is withheld — retry with a larger
        ``maxiter``/looser ``rtol``); finishes the request trace with
        ``status=ok|diverged|integrity_failed``."""
        if self._error is not None:
            self.retired = True
            raise self._error
        trace = self._trace
        t0 = time.perf_counter()
        span = trace.span("materialize") if trace is not None else None
        status = "ok"
        try:
            x = np.asarray(self._res.x)  # sync-ok: caller-requested materialization
            if self._corrupt and np.issubdtype(x.dtype, np.floating):
                # Injected silent-corruption fault (resilience/faults.py):
                # the poison lands here so the refusal below catches it.
                x = np.array(x)  # sync-ok: host-side writable copy
                x[0] = np.nan
            n_iters = int(self._res.n_iters)  # deliberate host materialization
            rnorm = float(self._res.residual_norm)  # deliberate host materialization
            value = float(self._res.value)  # deliberate host materialization
            converged = bool(self._res.converged)  # deliberate host materialization
            if self._iter_hist is not None:
                self._iter_hist.observe(n_iters)
            if self._residual_gauge is not None:
                self._residual_gauge.set(rnorm)
            if self._iter_time_hist is not None and self._dispatch_t0 is not None:
                # Total solve wall time amortized per iteration — the
                # number the fused tier exists to lower (device wait
                # included: result() IS the solve's completion point).
                self._iter_time_hist.observe(
                    (time.perf_counter() - self._dispatch_t0)
                    * 1e3 / max(n_iters, 1)
                )
            if not np.all(np.isfinite(x)) or not np.isfinite(rnorm):
                if self._integrity_counter is not None:
                    err = refuse_nonfinite(
                        x, self._integrity_counter,
                        f"the materialized {self.op} solution",
                    )
                    if err is not None:
                        status = "integrity_failed"
                        self._error = err
                        self._emit_failure("integrity_refused")
                        raise err
                status = "integrity_failed"
                self._emit_failure("integrity_refused")
                self._error = SolverDivergedError(
                    f"{self.op} solve produced a non-finite result "
                    f"(residual_norm={rnorm}); the answer is withheld — "
                    "check the operand for NaN/Inf or retry on the "
                    "degraded tier"
                )
                raise self._error
            if not converged:
                status = "diverged"
                if self._divergence_counter is not None:
                    self._divergence_counter.inc()
                self._emit_failure(
                    "solver_diverged", n_iters=n_iters,
                    residual_norm=rnorm,
                )
                self._error = SolverDivergedError(
                    f"{self.op} solve exhausted its iteration cap "
                    f"({self._cap}) at residual_norm={rnorm:.6e} without "
                    f"meeting rtol={self._rtol:g}; the partial iterate is "
                    "withheld (docs/SOLVERS.md: converged or typed "
                    "failure, never a silently wrong x) — retry with a "
                    "larger maxiter, a looser rtol, or a better-suited op"
                )
                raise self._error
            return SolverResult(
                x=x, value=value, n_iters=n_iters,
                residual_norm=rnorm, converged=True,
            )
        except (SolverDivergedError, ResultIntegrityError):
            raise
        except BaseException:
            status = "materialize_error"
            raise
        finally:
            self.retired = True
            if span is not None:
                span.__exit__(None, None, None)
                trace.finish(status=status)
            if self._materialize_hist is not None and status == "ok":
                self._materialize_hist.observe(
                    (time.perf_counter() - t0) * 1e3
                )


class EngineStats(ExecStats):
    """Executable-cache counters plus dispatch-level ones.

    ``in_flight`` is the outstanding-dispatch count at snapshot time;
    ``drains`` counts blocking waits the backpressure high-water mark
    forced; ``deadline_failures`` counts requests failed (never dispatched)
    because their ``deadline_ms`` elapsed in the backpressure gate.

    A point-in-time VIEW over the engine's metrics registry (the counters
    are the source of truth — ``engine.metrics.snapshot()`` reports the
    same numbers under the ``engine_*`` names). Updates are atomic
    registry increments, so concurrent submit/materialize/stats threads
    never tear a count (the bare-attribute race this class used to
    carry)."""

    def __init__(
        self, compiles: int, hits: int, requests: int, dispatches: int,
        cols: int, in_flight: int = 0, drains: int = 0,
        deadline_failures: int = 0,
    ):
        super().__init__(compiles=compiles, hits=hits)
        self.requests = requests
        self.dispatches = dispatches
        self.cols = cols
        self.in_flight = in_flight
        self.drains = drains
        self.deadline_failures = deadline_failures


class MatvecEngine:
    """Serve batches of right-hand sides against a resident sharded ``A``.

    Parameters
    ----------
    a : host (m, k) array — placed once with the strategy's A-sharding.
    mesh : target device mesh (default: all devices, ``make_mesh``).
    strategy : strategy name or instance (``models``).
    kernel : local kernel tier name (GEMV registry; the GEMM path maps it
        through ``gemm_kernel_name_for``). ``"auto"`` consults the tuning
        cache per local shape at trace time, as everywhere else.
    combine : combine schedule name, ``"auto"`` (resolved ONCE at engine
        construction from the tuning cache — per-dispatch resolution would
        put a cache lookup in the hot loop), or None for the static
        default.
    stages : stage count for the staged ``overlap`` schedules — an int, or
        None/``"auto"`` for the tuned fifth axis (``tune_overlap``; static
        default on a miss). Resolved ONCE at construction (the engine's
        shapes are fixed) and baked into the executable keys; ignored by
        every non-overlap schedule.
    dtype_storage : resident-A storage format (``ops/quantize.py``):
        None/``"native"`` keeps the plain array residency;
        ``"int8"``/``"int8c"``/``"fp8"`` quantize ``A`` ONCE here at
        residency time (payload + per-block scales placed in the
        strategy's own A-sharding) and every dispatch consumes the
        quantized operand through the tile-upcasting kernels — the HBM
        bytes the resident stream moves shrink to the payload's
        (``engine_resident_bytes`` gauge). ``"auto"`` consults the tuned
        sixth axis (``tuning.lookup_storage``; native on a miss, on an
        unsupported winner, or for a strategy instance bound to an
        A-tiling combine). The storage format is part of every
        :class:`ExecKey`; the degradation ladder treats NATIVE storage as
        the safe tier — under a resilience policy the original ``A`` is
        kept host-side and placed lazily the first time a breaker routes
        around the quantized config.
    dtype : operand dtype (default: ``a``'s).
    max_bucket : widest bucket in the ladder; wider requests split.
    promote : the GEMV→GEMM crossover ``b*``: ``"auto"`` (tuned decision,
        static :data:`DEFAULT_PROMOTE_B` on a miss), an int (explicit),
        or None (never promote — always the per-column path).
    donate : donate the RHS buffer to each dispatch (HBM reuse; ignored by
        backends that cannot donate, e.g. CPU).
    gather_output : as in ``MatvecStrategy.build`` (bools only).
    max_in_flight : backpressure high-water mark — the most outstanding
        dispatches ``submit`` tolerates before blocking on the OLDEST one
        (drain-oldest: the stream stays ordered and bounded instead of
        enqueueing unboundedly ahead of the device). None (default) keeps
        the unbounded contract. Request-granular: one wide split request
        may briefly overshoot by its part count.
    metrics : the obs MetricsRegistry the engine counts into (default: a
        fresh private registry — per-instance isolation). Pass a shared
        one to co-locate engine counters with caller-side metrics (the
        serve bench's dispatch-latency histogram) in one snapshot.
    trace_jsonl : path for the request-trace JSONL sink (``obs/sink.py``
        thread; None — ring buffer only). One line per finished request;
        ``flush_traces()`` fences the file.
    trace_capacity : finished-request records the in-memory ring retains
        (``tracer.traces()``).
    resilience : a :class:`~..resilience.ResiliencePolicy` enabling the
        retry + circuit-breaker + degradation-ladder dispatch path (see
        the module docstring and docs/RESILIENCE.md). None (default):
        dispatch exceptions propagate raw, exactly as before — the
        scheduler's batch bisection still isolates them.
    fault_plan : a seeded :class:`~..resilience.FaultPlan` hooked into
        the compile and dispatch sites (chaos testing / the serve
        bench's ``--fault-spec``). Works with or without ``resilience``:
        without it, injected faults propagate to the caller.
    integrity_gate : check every materialized result for NaN/Inf and
        raise :class:`~..resilience.ResultIntegrityError` instead of
        serving corrupt data (counted in
        ``engine_integrity_failures_total``). Off by default — the check
        is one host-side ``isfinite`` scan per materialization.
    retain_host : keep the host payload (``A`` itself, plus the quantized
        pytree under quantized storage) for the engine's lifetime, so
        residency is releasable (:meth:`release_residency`) and
        restorable (:meth:`ensure_resident`) — the matrix registry's
        swap contract. Off by default: a plain engine keeps the old
        place-once-at-construction footprint.
    defer_placement : skip the construction-time ``device_put`` — the
        first :meth:`ensure_resident` (or the dispatch path's transparent
        re-placement) places ``A``. Requires ``retain_host``; registry
        tenants start evicted so registration of a thousand tenants
        costs no HBM.
    label_prefix : prefix every fault-site label with this string
        (``"tenant-7/"``), making :class:`~..resilience.FaultSpec`
        ``key`` patterns tenant-addressable. Un-prefixed patterns keep
        matching via the base label (``resilience/faults.py``).
    exec_cache : adopt a shared :class:`ExecutableCache` instead of a
        private one. Executables depend on shapes/shardings/config, never
        on ``A``'s values, so registry tenants with equal
        :meth:`exec_signature` share one compiled-program set (N tenants,
        one compile per ExecKey).
    residency_listener : ``callable(delta_bytes, reason)`` invoked after
        every device-A footprint change — ``reason`` is ``"resident"``
        (payload placed), ``"released"`` (residency dropped), or
        ``"native_fallback"`` (the degradation ladder's lazy native
        safe-tier placement under quantized storage). The registry's HBM
        accountant charges through this; exactly-once per transition
        (concurrent placements account once). Never invoked while the
        engine's residency bookkeeping lock is held.
    timeline : the correlated event hub (``obs/timeline.py``) lifecycle
        events emit into — submit/retry/degrade/breaker/escalation, each
        carrying the request's correlation id. Default: the process hub
        (``obs.get_hub()``). Emission is a dict build + ``deque.append``
        (GIL-atomic, no locks, no I/O) — always on, hot-path-safe.
    """

    def __init__(
        self,
        a,
        mesh=None,
        *,
        strategy: str | MatvecStrategy = "rowwise",
        kernel: str | Callable = "xla",
        solver_kernel: str = "xla",
        combine: str | None = None,
        stages: int | str | None = None,
        dtype_storage: str | None = None,
        dtype=None,
        max_bucket: int = DEFAULT_MAX_BUCKET,
        promote: str | int | None = "auto",
        donate: bool = True,
        gather_output: bool = True,
        max_in_flight: int | None = None,
        metrics: MetricsRegistry | None = None,
        trace_jsonl: str | os.PathLike | None = None,
        trace_capacity: int = 256,
        resilience: ResiliencePolicy | None = None,
        fault_plan: FaultPlan | None = None,
        integrity_gate: bool = False,
        retain_host: bool = False,
        defer_placement: bool = False,
        label_prefix: str = "",
        exec_cache: ExecutableCache | None = None,
        residency_listener: Callable[[int, str], None] | None = None,
        timeline: TimelineHub | None = None,
    ):
        if mesh is None:
            from ..parallel.mesh import make_mesh

            mesh = make_mesh(len(jax.devices()))
        self.mesh = mesh
        self.strategy = (
            get_strategy(strategy) if isinstance(strategy, str) else strategy
        )
        a = np.asarray(a, dtype=dtype)  # sync-ok: one-time host staging of A
        if a.ndim != 2:
            raise ConfigError(f"A must be rank 2, got shape {a.shape}")
        self.m, self.k = a.shape
        self.dtype = a.dtype
        self.strategy.validate(self.m, self.k, mesh)
        if not isinstance(gather_output, bool):
            raise ConfigError(
                "engine gather_output must be True or False; got "
                f"{gather_output!r}"
            )
        self.kernel = kernel
        # The solver-path iteration tier (docs/SOLVERS.md "Fused iteration
        # tier"): "xla" is the established per-HLO body, "pallas_fused"
        # the one-kernel-per-iteration tier (ops/pallas_solver.py), "auto"
        # the tuner-backed choice (tune_solver_kernel; xla on a cache
        # miss). Orthogonal to `kernel`, which names the LOCAL GEMV tile
        # kernel inside the XLA tier's matvec.
        if solver_kernel not in ("xla", "pallas_fused", "auto"):
            raise ConfigError(
                f"solver_kernel must be 'xla', 'pallas_fused' or 'auto'; "
                f"got {solver_kernel!r}"
            )
        self.solver_kernel = solver_kernel
        # The REQUESTED combine, kept for the fused solver tier: the
        # fused body owns its own combine spelling, so it must see the
        # user's ask (None/"auto"/explicit), not the matvec-tuned winner.
        self._requested_combine = combine
        self.gather_output = gather_output
        self.max_bucket = max_bucket
        self._donate = DONATE_ARGNUMS if donate else ()
        self._sh_a, self._sh_x = self.strategy.shardings(mesh)
        _, self._sh_b = self.strategy.batched_shardings(mesh)
        # Replicated sharding for the solver path's RHS and scalar operands
        # (rtol/maxiter/interval ride as dynamic scalars — docs/SOLVERS.md).
        self._sh_rep = NamedSharding(mesh, PartitionSpec())
        self.storage = self._resolve_storage_locked(dtype_storage)
        self._a_native = None  # lazy native residency (the ladder's safe tier)
        self.retain_host = bool(retain_host)
        if defer_placement and not self.retain_host:
            raise ConfigError(
                "defer_placement needs retain_host=True — a deferred "
                "engine has only the host payload to place from"
            )
        self._label_prefix = str(label_prefix)
        self._residency_listener = residency_listener
        # Residency bookkeeping mutex: guards WHICH placed array wins a
        # concurrent-placement race and the exactly-once listener
        # decision. Never held across a transfer or a sync, and the
        # listener is never invoked under it (it may take the registry's
        # lock) — the device-transfer-under-registry-lock rule's
        # discipline.
        self._residency_lock = threading.Lock()
        # Online-reshard fence (docs/RESHARDING.md): each dispatch region
        # holds it so one request sees ONE consistent
        # (strategy, shardings, residency) tuple; reshard() holds it only
        # for the pointer swap, so in-flight dispatches finish on the old
        # layout and new submits wait out at most the swap itself — never
        # the migration collectives. RLock: the dispatch region may
        # re-enter through the resilience ladder. Ordering: _swap_lock ->
        # _residency_lock -> registry lock (via the residency listener);
        # the registry never holds its own lock across engine calls, so
        # the chain is acyclic.
        self._swap_lock = threading.RLock()
        # Serializes whole reshard() calls (build + migrate + commit) —
        # distinct from the brief commit fence above.
        self._reshard_lock = threading.Lock()
        # Bumped at every committed layout swap; stale-placement guard for
        # the enqueue-only residency paths (they stage device_puts OUTSIDE
        # the locks, so a swap mid-placement must invalidate the staged
        # old-layout buffer, not install it).
        self._layout_epoch = 0
        # Test seam: called between migration build and commit so the
        # eviction-races-reshard test can inject a release_residency at
        # the worst moment (tests/test_reshard.py).
        self._reshard_pre_commit: Callable[[], None] | None = None
        self._a = None  # device residency; placed below unless deferred
        if self.storage != NATIVE:
            # Quantize ONCE at residency: payload + per-block scales (+ the
            # compensated pair) placed as one pytree in A's own sharding.
            # The host-side pytree survives as the swap-in source when the
            # engine is registry-managed (retain_host) — re-placement of
            # the same host bytes is bitwise-identical, no re-quantize.
            qa = quantize_matrix(
                a, self.storage,
                contraction_shards=self.strategy.contraction_shards(mesh),
            )
            self._qa_host = qa
            # Struct-only template (NOT the host arrays: a large A's
            # quantized copy is 26-52% of its bytes, and the builders
            # only ever need leaf shapes/dtypes).
            self._qa_template = quantized_like(
                qa,
                lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype),
            )
            self._a_host = a  # retained for the native safe tier
            self.storage_block = qa.block
            self.resident_bytes = qa.nbytes
        else:
            self._qa_host = None
            self._qa_template = None
            # Placement source; dropped after the construction-time
            # placement unless retain_host keeps residency releasable.
            self._a_host = a
            self.storage_block = None
            self.resident_bytes = int(a.nbytes)
        if self.speculative:
            # The speculative tier's resident set, built ONCE here
            # (docs/QUANTIZATION.md "speculative serving"): the
            # compensated-int8 payload the candidate dispatches against,
            # the seeded probe matrix U, and its float64-accumulated
            # projection P = U A off the NATIVE operand (the check must
            # measure the quantization error, so its reference cannot
            # itself be quantized). Probe count is sized for the tightest
            # ELIGIBLE tolerance (the SPEC_RTOL_FLOOR eligibility gate),
            # so one fixed P/U serves every admissible rtol.
            self._spec_probes = probe_count(SPEC_RTOL_FLOOR)
            sq = quantize_matrix(
                a, SPEC_STORAGE,
                contraction_shards=self.strategy.contraction_shards(mesh),
            )
            self._spec_qa_host = sq
            self._spec_qa_template = quantized_like(
                sq,
                lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype),
            )
            self.spec_storage_block = sq.block
            u = probe_matrix(self._spec_probes, self.m, self.dtype)
            p = project_probes(u, a, self.dtype)
            self._spec_u_host, self._spec_p_host = u, p
            # P contracts against the request x, so it shards over the
            # strategy's x spec (the fused check closes the product with
            # one psum of s scalars); U contracts against the gathered
            # candidate and rides replicated.
            spec_x = self.strategy.specs(mesh)[1]
            self._sh_p = NamedSharding(
                mesh, PartitionSpec(None, *tuple(spec_x))
            )
            self.spec_resident_bytes = int(sq.nbytes + u.nbytes + p.nbytes)
            # The speculative set is placed/released WITH the payload —
            # one residency, honestly accounted as one footprint.
            self.resident_bytes += self.spec_resident_bytes
        else:
            self._spec_probes = None
            self._spec_qa_host = self._spec_qa_template = None
            self._spec_u_host = self._spec_p_host = None
            self._sh_p = None
            self.spec_storage_block = None
            self.spec_resident_bytes = 0
        self._spec_qa = self._spec_p = self._spec_u = None
        self._matvec_combine, self._gemm_combine = self._resolve_combine_locked(
            combine
        )
        if self.storage != NATIVE:
            # Auto-resolved combine winners from the A-tiling family cannot
            # consume the payload pytree: drop to the static default (the
            # same filter the build layer's auto tier applies). Explicit
            # incompatible names already failed in _resolve_combine_locked.
            if self._matvec_combine in STORAGE_INCOMPATIBLE_COMBINES:
                self._matvec_combine = None
            if self._gemm_combine in STORAGE_INCOMPATIBLE_COMBINES:
                self._gemm_combine = None
        if self.solver_kernel == "pallas_fused":
            # Fail the strategy/combine half of the fused-tier contract at
            # construction (ShardingError), not requests deep; the op half
            # (cg/chebyshev only) is submit()'s to check — this engine may
            # legitimately serve matvec traffic alongside fused solves.
            from ..ops.pallas_solver import check_fused_solver

            check_fused_solver(
                "cg", self.strategy.name, self._requested_combine, mesh
            )
        # The REQUESTED stage/promotion asks, kept so a reshard can
        # re-resolve them against the destination strategy exactly as a
        # fresh construction would (same tuning lookups, same clamps).
        self._requested_stages = stages
        self._requested_promote = promote
        self.stages = self._resolve_stages_locked(stages)
        self.b_star = self._resolve_promotion_locked(promote)
        if max_in_flight is not None and max_in_flight < 1:
            raise ConfigError(
                f"max_in_flight must be >= 1, got {max_in_flight}"
            )
        self.max_in_flight = max_in_flight
        self._outstanding: deque[jax.Array] = deque()
        # One source of truth for every count the engine reports: the
        # registry's atomic counters (EngineStats is a view; the serve
        # bench's --metrics-out snapshot is the same numbers).
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._c_requests = self.metrics.counter(
            "engine_requests_total", "submit() calls"
        )
        self._c_dispatches = self.metrics.counter(
            "engine_dispatches_total", "device programs enqueued"
        )
        self._c_cols = self.metrics.counter(
            "engine_cols_total", "right-hand-side columns accepted"
        )
        self._c_drains = self.metrics.counter(
            "engine_drains_total", "backpressure drain-oldest waits"
        )
        self._c_deadline_failures = self.metrics.counter(
            "engine_deadline_failures_total",
            "requests failed in the gate (deadline_ms elapsed)",
        )
        self._g_in_flight = self.metrics.gauge(
            "engine_in_flight", "outstanding dispatches at last snapshot"
        )
        self._g_resident = self.metrics.gauge(
            "engine_resident_bytes",
            "HBM bytes of the resident A operand (payload + scales for "
            "quantized storage; plus the native safe tier once placed)",
        )
        self._g_resident.set(0)
        # Info metric, Prometheus-style: the label set carries the fact,
        # the value is always 1 (the obs `storage` panel reads it). The
        # `reason` label says WHY this format serves — "explicit"/"tuned"
        # vs "auto_degraded" — so a silent degrade is visible in any
        # metrics snapshot, not just health().
        self.metrics.gauge(
            f'engine_storage_format{{format="{self.storage}",'
            f'dtype="{self.dtype}",reason="{self.storage_reason}"}}',
            "resident-A storage format (info metric; value is always 1)",
        ).set(1)
        # Storage-axis fallback visibility: every time the engine passes
        # on the storage tier it was asked or tuned for — an auto winner
        # degraded at construction, or a speculative-armed engine serving
        # an rtol request native (breaker open, or rtol under the
        # eligibility floor). Created only when the storage axis is
        # engaged, so a plain engine's snapshot stays clean.
        if dtype_storage is not None:
            self._c_storage_fallbacks = self.metrics.counter(
                "engine_storage_fallbacks_total",
                "requests (or the construction itself) served native "
                "despite a quantized/speculative storage ask",
            )
            if self._storage_degraded:
                self._c_storage_fallbacks.inc()
        else:
            self._c_storage_fallbacks = None
        if self.speculative:
            self._c_speculative = self.metrics.counter(
                "engine_speculative_dispatches_total",
                "requests served through the speculative int8c tier "
                "(candidate + fused acceptance check, one program)",
            )
            self._c_escalations = self.metrics.counter(
                "engine_escalations_total",
                "speculative candidates the on-device check rejected "
                "(a traced native re-dispatch served the request)",
            )
            # Windowed EWMA (τ = 60 s), not a lifetime ratio: the cost
            # model's ε feed must track RECENT traffic — an engine that
            # escalated heavily an hour ago but serves cleanly now should
            # read near zero, and a fresh escalation burst should move
            # the needle immediately instead of being averaged away by a
            # long clean history. Exported in snapshots under the same
            # gauge name, so CostModel.refresh_escalation_rate reads it
            # unchanged.
            self._g_escalation_rate = self.metrics.ewma_gauge(
                "engine_escalation_rate",
                "escalation EWMA over speculative dispatches (τ=60s), "
                "refreshed at each speculative settlement (the cost "
                "model's ε feed)",
            )
        else:
            self._c_speculative = None
            self._c_escalations = None
            self._g_escalation_rate = None
        self._h_submit = self.metrics.histogram(
            "engine_submit_latency_ms", "submit() entry-to-return host time"
        )
        self._h_materialize = self.metrics.histogram(
            "engine_materialize_latency_ms",
            "result() materialization host time (device wait included)",
        )
        self._c_dispatch_failures = self.metrics.counter(
            "engine_dispatch_failures_total",
            "submit() calls that raised at dispatch (post-retry/ladder)",
        )
        self._cache = exec_cache if exec_cache is not None else (
            ExecutableCache(
                compile_counter=self.metrics.counter(
                    "engine_compiles_total", "AOT executable compiles"
                ),
                hit_counter=self.metrics.counter(
                    "engine_hits_total", "executable-cache hits"
                ),
            )
        )
        self.tracer = RequestTracer(
            capacity=trace_capacity,
            sink=JsonlSink(trace_jsonl) if trace_jsonl is not None else None,
        )
        # Correlated event timeline (obs/timeline.py): lifecycle events
        # emit here with the request's correlation id. Always on —
        # emission is a dict + deque.append, hot-path-safe by the obs
        # doctrine.
        self._timeline = timeline if timeline is not None else get_hub()
        # engine.health()["slo"]'s burn-rate monitor, built lazily on the
        # first health() call so a plain engine's snapshot carries no
        # slo_* vocabulary (the solver-metric-handles doctrine).
        self._slo_monitor = None
        self._closed = False

        # ---- resilience state (docs/RESILIENCE.md). Counters exist only
        # when the machinery is configured, so a plain engine's metrics
        # snapshot (and the obs `resilience` panel trigger) stays clean.
        self._resilience = resilience
        self._fault_plan = fault_plan
        self.integrity_gate = bool(integrity_gate)
        self._breakers: dict[ExecKey, CircuitBreaker] = {}
        self._breakers_lock = threading.Lock()
        self._degraded: dict[str, str] = {}  # preferred label -> serving label
        # Ladders are pure functions of the (fixed-at-construction) engine
        # config plus the bucket — memoized off the resilient hot path.
        self._ladders: dict = {}
        # Solver metric handles, created on the FIRST solver submit so a
        # pure-matvec engine's snapshot (and the obs `solvers` panel
        # trigger) stays clean — same doctrine as the resilience counters.
        self._solver_metrics = None
        self._retry_serials = itertools.count()
        if resilience is not None or fault_plan is not None:
            self._c_faults = self.metrics.counter(
                "resil_faults_injected_total",
                "faults the FaultPlan injected (all kinds)",
            )
            self._c_retries = self.metrics.counter(
                "resil_retries_total",
                "dispatch retries after a retryable fault",
            )
            self._c_downgrades = self.metrics.counter(
                "resil_downgrades_total",
                "dispatches served by a degradation-ladder fallback "
                "(safe combine, shrunken bucket, or GEMV floor)",
            )
            self._c_breaker_opens = self.metrics.counter(
                "resil_breaker_opens_total",
                "circuit-breaker closed/half-open -> open transitions",
            )
            self._c_recoveries = self.metrics.counter(
                "resil_recoveries_total",
                "circuit-breaker half-open -> closed recoveries "
                "(preferred config restored)",
            )
            self._g_breakers_open = self.metrics.gauge(
                "resil_breakers_open",
                "breakers not in the closed state at last health() call",
            )
        else:
            self._c_faults = self._c_retries = self._c_downgrades = None
            self._c_breaker_opens = self._c_recoveries = None
            self._g_breakers_open = None
        self._c_integrity = (
            self.metrics.counter(
                "engine_integrity_failures_total",
                "materializations the NaN/Inf integrity gate refused",
            )
            if self.integrity_gate else None
        )
        if not defer_placement:
            self.ensure_resident()  # the classic resident-for-engine-life path
            if not self.retain_host:
                # PR 8 doctrine: a plain quantized engine keeps the
                # struct-only template (plus the original A for the
                # native safe tier), never the host payload copy; a plain
                # native engine keeps no host copy at all. The speculative
                # host set follows the same rule — a non-releasable
                # engine's speculative residency is placed once, for life.
                self._qa_host = None
                self._spec_qa_host = None
                self._spec_u_host = self._spec_p_host = None
                if self.storage == NATIVE:
                    self._a_host = None

    # ---- residency lifecycle (registry.py; docs/MULTITENANT.md) ----

    @property
    def resident(self) -> bool:
        """True while the payload ``A`` operand is device-resident."""
        return self._a is not None  # unguarded-ok: presence probe; a stale answer is benign — the dispatch path self-heals via ensure_resident (refcounted residency)

    @property
    def device_resident_bytes(self) -> int:
        """HBM bytes this engine's A residencies currently hold: the
        payload when resident, plus the native safe tier once the
        degradation ladder has placed it."""
        total = self.resident_bytes if self._a is not None else 0  # unguarded-ok: accounting snapshot; the ledger RECONCILES to this value so a racing transition converges next notification (HbmAccountant doctrine)
        if self._a_native is not None:  # unguarded-ok: same accounting-snapshot tolerance as the payload read above
            total += int(self._a_host.nbytes)
        return total

    def _notify_residency(self, delta: int, reason: str) -> None:
        self._g_resident.set(self.device_resident_bytes)
        if self._residency_listener is not None and delta:
            self._residency_listener(delta, reason)

    def ensure_resident(self) -> bool:
        """Place the payload ``A`` operand if it is not device-resident;
        True when this call placed it. Enqueue-only (``device_put`` is
        async — the swap-in overlaps under other tenants' in-flight
        dispatches) and race-safe: concurrent callers may both stage a
        placement, but exactly one wins the bookkeeping and the listener
        fires once (the loser's buffer is dropped, freed by refcount).
        Raises :class:`ResidencyError` when the engine was evicted
        without ``retain_host`` (no payload to place from)."""
        if self._a is not None:  # unguarded-ok: double-checked placement — the decisive re-check runs under _residency_lock below; this bare read only skips staging work
            return False
        while True:
            # Layout-epoch guard: the staging below reads the host payload
            # and sharding OUTSIDE the lock (device_put must not run under
            # it), so a reshard commit in between would otherwise install
            # an old-layout buffer over the new config. A bumped epoch
            # restages against the post-swap sharding instead.
            epoch = self._layout_epoch  # unguarded-ok: deliberate stage-outside-lock read; the epoch re-check under _residency_lock below is decisive, and a lost race is a dropped buffer, not corruption
            payload = (
                self._qa_host if self.storage != NATIVE else self._a_host  # unguarded-ok: deliberate stage-outside-lock read; the epoch re-check under _residency_lock below is decisive, and a lost race is a dropped buffer, not corruption
            )
            if payload is None:
                raise ResidencyError(
                    "resident A was released and the engine retains no host "
                    "payload (construct with retain_host=True for releasable "
                    "residency)"
                )
            placed = jax.device_put(payload, self._sh_a)  # unguarded-ok: deliberate stage-outside-lock read; the epoch re-check under _residency_lock below is decisive, and a lost race is a dropped buffer, not corruption
            spec = None
            if self.speculative:  # unguarded-ok: deliberate stage-outside-lock read; the epoch re-check under _residency_lock below is decisive, and a lost race is a dropped buffer, not corruption
                # The speculative set rides the payload residency: placed
                # together, accounted together (resident_bytes includes it),
                # re-placed bitwise-identically from the same host arrays on
                # a registry swap-in.
                spec = (
                    jax.device_put(self._spec_qa_host, self._sh_a),  # unguarded-ok: deliberate stage-outside-lock read; the epoch re-check under _residency_lock below is decisive, and a lost race is a dropped buffer, not corruption
                    jax.device_put(self._spec_p_host, self._sh_p),  # unguarded-ok: deliberate stage-outside-lock read; the epoch re-check under _residency_lock below is decisive, and a lost race is a dropped buffer, not corruption
                    jax.device_put(self._spec_u_host, self._sh_rep),
                )
            with self._residency_lock:
                if self._layout_epoch != epoch:
                    continue  # resharded mid-placement: restage
                if self._a is not None:
                    return False  # lost a concurrent placement race
                self._a = placed
                if spec is not None:
                    self._spec_qa, self._spec_p, self._spec_u = spec
            break
        self._notify_residency(self.resident_bytes, "resident")  # unguarded-ok: accounting snapshot taken after the commit; the listener reconciles against the ledger
        return True

    def release_residency(self) -> int:
        """Drop the device residency (payload AND any placed native safe
        tier), keeping the host payload for a later
        :meth:`ensure_resident`. Returns the HBM bytes released. A pure
        reference drop — no device sync: in-flight dispatches hold their
        own references to the arrays, so their results are unaffected and
        the buffers free when the last reference drops (refcounted
        residency). Safe to call under the registry lock by the same
        discipline."""
        if not self.retain_host:
            raise ResidencyError(
                "release_residency needs retain_host=True — without the "
                "host payload the engine could never serve again"
            )
        with self._residency_lock:
            released = self.resident_bytes if self._a is not None else 0
            if self._a_native is not None:
                released += int(self._a_host.nbytes)
                self._a_native = None
            self._a = None
            # The speculative set is part of the payload residency
            # (resident_bytes already includes it).
            self._spec_qa = self._spec_p = self._spec_u = None
        self._notify_residency(-released, "released")
        return released

    def reshard(self, strategy, *, warm_widths=None) -> dict:
        """Migrate the resident operand set to another strategy ON-DEVICE
        (docs/RESHARDING.md): the payload — and a quantized resident's
        payload+scale leaves — move between layouts as the minimal
        ``all_to_all``/``ppermute`` program (``parallel/reshard.py``),
        never a host gather, and the engine's config (shardings, combine,
        stages, b*) re-resolves against the destination exactly as a
        fresh construction would. In-flight dispatches finish on the old
        layout; a submit racing the commit waits out only the pointer
        swap (``_swap_lock``), never the migration collectives. The
        migrated resident is bitwise-identical to a fresh registration in
        the destination layout (each device shard equal; tests pin it).

        Per-block scales are recomputed from the retained host ``A``
        ONLY when the block→shard mapping changes between the layouts
        (the destination's contraction split forces a different block
        size); same-block migrations move the existing scale leaves with
        the payload, bitwise.

        An eviction that lands mid-migration aborts cleanly: the commit
        swaps the CONFIG only (the next ``ensure_resident`` places in
        the destination layout from host), so the HBM ledger never holds
        a double footprint. Returns a summary dict —
        ``{src, dst, migrated, aborted, requantized, bytes_moved}`` —
        ``bytes_moved`` being the per-mesh payload bytes the collective
        program redistributed (0 for a config-only or host-fallback
        swap). ``warm_widths`` forwards to :meth:`warmup` after the
        swap: the one-time new-layout compile, off the request path.
        """
        from ..parallel.reshard import (
            RESHARD_STRATEGIES,
            build_reshard,
            validate_reshard,
        )

        dst = (
            get_strategy(strategy) if isinstance(strategy, str) else strategy
        )
        with self._reshard_lock:  # serialize whole migrations
            src = self.strategy
            result = dict(
                src=src.name, dst=dst.name, migrated=False, aborted=False,
                requantized=False, bytes_moved=0,
            )
            if dst.name == src.name:
                return result
            for name in (src.name, dst.name):
                if name not in RESHARD_STRATEGIES:
                    raise ConfigError(
                        f"online reshard covers {RESHARD_STRATEGIES}; "
                        f"asked for {src.name!r} -> {dst.name!r}"
                    )
            dst.validate(self.m, self.k, self.mesh)
            validate_reshard((self.m, self.k), self.mesh)
            if self.storage != NATIVE and not dst.storage_combine_ok(None):
                raise ConfigError(
                    f"strategy {dst.name!r} binds an A-tiling combine and "
                    f"cannot host the quantized resident (storage="
                    f"{self.storage!r})"
                )
            # The explicit combine ask re-validates against the destination;
            # one with no destination spelling degrades to the static default
            # (a reshard must not fail a tenant over a schedule name).
            req = self._requested_combine
            if req not in (None, "auto") and (
                not dst.supports_combine(req)
                or (
                    self.storage != NATIVE
                    and not dst.storage_combine_ok(req)
                )
            ):
                req = None

            with self._residency_lock:
                src_a = self._a
                src_spec_qa = self._spec_qa
                src_spec_p = self._spec_p
                src_spec_u = self._spec_u
            resident = src_a is not None
            dst_shards = dst.contraction_shards(self.mesh)
            new_sh_a, new_sh_x = dst.shardings(self.mesh)
            _, new_sh_b = dst.batched_shardings(self.mesh)
            new_sh_p = None
            if self.speculative:
                spec_x = dst.specs(self.mesh)[1]
                new_sh_p = NamedSharding(
                    self.mesh, PartitionSpec(None, *tuple(spec_x))
                )

            # ---- payload migration plan (outside every lock: builds,
            # collectives and device_puts are all enqueue-only).
            requant = None
            new_block = self.storage_block
            fn = None
            new_a = None
            bytes_moved = 0
            if self.storage != NATIVE:
                new_block = default_block(self.k, dst_shards)
                scales_shape = (self.m, self.k // new_block)
                try:
                    if new_block != self.storage_block:
                        raise ConfigError("block→shard mapping changed")
                    validate_reshard(scales_shape, self.mesh, what="scales")
                except ConfigError:
                    # Scales must be recomputed (or cannot split across
                    # the mesh): re-quantize from the retained host A —
                    # quantized engines always keep it (the native safe
                    # tier's source).
                    if self._a_host is None:
                        raise ResidencyError(
                            "reshard needs the host A to recompute "
                            "per-block scales, and this engine retains "
                            "none"
                        )
                    requant = quantize_matrix(
                        self._a_host, self.storage,
                        contraction_shards=dst_shards,
                    )
                    new_block = requant.block
            if resident:
                if requant is not None:
                    new_a = jax.device_put(requant, new_sh_a)  # registry-ok: enqueue-only placement under the per-engine migration serializer, not the registry mutex — no tenant admission waits on _reshard_lock
                else:
                    fn = build_reshard(self.mesh, src.name, dst.name)
                    new_a = fn(src_a)
                    bytes_moved = sum(
                        leaf.nbytes
                        for leaf in jax.tree_util.tree_leaves(src_a)
                    )
            new_spec = (None, None, None)
            if self.speculative and resident:
                # The speculative set rides along: the int8c candidate
                # payload takes the same collective program when its
                # block survives the move; the probe projection P only
                # changes SHARDING (its values are layout-free), and U
                # stays replicated.
                spec_block = default_block(self.k, dst_shards)
                spec_scales = (self.m, self.k // spec_block)
                try:
                    if spec_block != self.spec_storage_block:
                        raise ConfigError("spec block changed")
                    validate_reshard(spec_scales, self.mesh, what="scales")
                    if fn is None:
                        fn = build_reshard(self.mesh, src.name, dst.name)
                    new_spec_qa = fn(src_spec_qa)
                    bytes_moved += sum(
                        leaf.nbytes
                        for leaf in jax.tree_util.tree_leaves(src_spec_qa)
                    )
                except ConfigError:
                    if self._a_host is None:
                        raise ResidencyError(
                            "reshard needs the host A to recompute the "
                            "speculative int8c scales, and this engine "
                            "retains none"
                        )
                    sq = quantize_matrix(
                        self._a_host, SPEC_STORAGE,
                        contraction_shards=dst_shards,
                    )
                    self.spec_storage_block = sq.block
                    if self.retain_host:
                        self._spec_qa_host = sq
                    new_spec_qa = jax.device_put(sq, new_sh_a)  # registry-ok: enqueue-only placement under the per-engine migration serializer, not the registry mutex — no tenant admission waits on _reshard_lock
                new_spec = (
                    new_spec_qa,
                    jax.device_put(src_spec_p, new_sh_p),  # registry-ok: enqueue-only placement under the per-engine migration serializer, not the registry mutex — no tenant admission waits on _reshard_lock
                    src_spec_u,
                )

            if self._reshard_pre_commit is not None:
                self._reshard_pre_commit()  # test seam (docstring above)

            # ---- commit: the only window a submit ever waits on.
            with self._swap_lock:
                with self._residency_lock:
                    before = self.device_resident_bytes
                    aborted = resident and self._a is not src_a
                    if aborted:
                        # Evicted (or re-placed) mid-build: drop every
                        # migrated buffer and swap CONFIG only — never
                        # two payload footprints. A racing re-placement
                        # is old-layout, so it is dropped too; the next
                        # ensure_resident heals in the new layout.
                        self._a = None
                        self._spec_qa = self._spec_p = self._spec_u = None
                        bytes_moved = 0
                    else:
                        self._a = new_a
                        if self.speculative and resident:
                            (
                                self._spec_qa, self._spec_p, self._spec_u,
                            ) = new_spec
                    # The native safe tier is sharded by the OLD layout:
                    # drop it; a degraded dispatch re-places lazily.
                    self._a_native = None
                    self._layout_epoch += 1
                    # Config swap — still under the fence, so a dispatch sees
                    # old-everything or new-everything, never a mix.
                    self.strategy = dst
                    self._sh_a, self._sh_x = new_sh_a, new_sh_x
                    self._sh_b = new_sh_b
                    if self.speculative:
                        self._sh_p = new_sh_p
                    if requant is not None:
                        self.storage_block = requant.block
                        self._qa_host = requant if self.retain_host else None
                        self._qa_template = quantized_like(
                            requant,
                            lambda leaf: jax.ShapeDtypeStruct(
                                leaf.shape, leaf.dtype
                            ),
                        )
                        self.resident_bytes = (
                            requant.nbytes + self.spec_resident_bytes
                        )
                    self._matvec_combine, self._gemm_combine = (
                        self._resolve_combine_locked(req)
                    )
                    if self.storage != NATIVE:
                        if self._matvec_combine in STORAGE_INCOMPATIBLE_COMBINES:
                            self._matvec_combine = None
                        if self._gemm_combine in STORAGE_INCOMPATIBLE_COMBINES:
                            self._gemm_combine = None
                    self.stages = self._resolve_stages_locked(self._requested_stages)
                    self.b_star = self._resolve_promotion_locked(
                        self._requested_promote
                    )
                    # Degradation ladders embed old-layout ExecKeys.
                    self._ladders.clear()
                    delta = self.device_resident_bytes - before
            result.update(
                migrated=resident and not aborted,
                aborted=bool(aborted),
                requantized=requant is not None,
                bytes_moved=int(bytes_moved),
            )
        self._notify_residency(delta, "reshard")  # fired after every engine lock is released (the PR 9 rule); the ledger reconciles, so ordering vs a racing placement is benign
        if warm_widths is not None:
            # The one-time destination-layout compile, off the hot path.
            self.warmup(widths=warm_widths)
        return result

    def exec_signature(self) -> tuple:
        """Identity of this engine's compiled-program space. Executables
        depend on shapes, shardings and config — never on ``A``'s values
        — so two engines with equal signatures may share one
        :class:`ExecutableCache` (``exec_cache=``): the registry compiles
        each ExecKey once across N same-shaped tenants."""
        return (
            self.mesh,
            self.strategy.name,  # unguarded-ok: stable config snapshot — the registry re-homes exec caches under its own lock only after reshard() returns, and taking _swap_lock here would invert the registry->engine lock order
            # The kernel OBJECT for callables (two different callables
            # that share a __name__ must not share compiled programs);
            # strings compare by value as before.
            self.kernel,
            self._combine_label(self._matvec_combine),  # unguarded-ok: stable config snapshot — the registry re-homes exec caches under its own lock only after reshard() returns, and taking _swap_lock here would invert the registry->engine lock order
            self._combine_label(self._gemm_combine),  # unguarded-ok: stable config snapshot — the registry re-homes exec caches under its own lock only after reshard() returns, and taking _swap_lock here would invert the registry->engine lock order
            self.stages,  # unguarded-ok: stable config snapshot — the registry re-homes exec caches under its own lock only after reshard() returns, and taking _swap_lock here would invert the registry->engine lock order
            self.m,
            self.k,
            str(self.dtype),
            self.storage,
            self.storage_block,
            self.gather_output,
            self.max_bucket,
            self._donate,
            # Speculative arming extends the compiled-program space (the
            # fused check programs); a plain engine's signature is
            # byte-identical to pre-speculation, so existing shared
            # caches keep sharing.
        ) + ((SPECULATE, self._spec_probes) if self.speculative else ())  # unguarded-ok: stable config snapshot — the registry re-homes exec caches under its own lock only after reshard() returns, and taking _swap_lock here would invert the registry->engine lock order

    def exec_keyspace(
        self,
        solver_ops: Sequence[str] = (),
        *,
        restart: int | None = None,
        steps: int | None = None,
    ) -> dict[str, list[str]]:
        """The finite ExecKey space this engine can compile, classified by
        WHEN each key may compile — built from the engine's own key
        constructors (never a parallel re-derivation), so it is the
        ground truth the static keyspace auditor
        (``staticcheck/keyspace.py``) cross-checks its symbolic
        enumeration against.

        Classes (sorted ``ExecKey.label()`` lists):

        - ``"warmup"`` — the exact set :meth:`warmup` (``widths=None``)
          compiles, plus the preferred key of every DECLARED solver op
          (a serve config that declares solver traffic warms those at
          first submit — part of the warm phase by doctrine).
        - ``"steady"`` — every key :meth:`submit`/:meth:`submit_solver`
          routing can reach on the healthy path, computed by literally
          evaluating the routing over every chunk width (a genuinely
          different path from the warmup enumeration — that is what
          makes ``steady ⊆ warmup`` a checkable invariant rather than a
          tautology). ``compiles_steady == 0`` holds iff this is a
          subset of ``"warmup"``.
        - ``"fault_only"`` — degradation-ladder safe tiers reachable
          only after a breaker trips (RESOURCE_EXHAUSTED bucket-halving
          re-enters the ladder at ladder buckets, so it adds no keys
          beyond these). Compiles here are fault-path, never steady.
        """
        restart = DEFAULT_RESTART if restart is None else int(restart)
        steps = DEFAULT_STEPS if steps is None else int(steps)
        for op in solver_ops:
            if op not in SOLVER_OPS:
                raise ConfigError(
                    f"unknown solver op {op!r}; expected one of "
                    f"{sorted(SOLVER_OPS)}"
                )
        with self._swap_lock:
            warm: set[ExecKey] = {self._matvec_key_locked()}
            if self.speculative:
                warm.add(self._spec_matvec_key())
            if self.b_star is not None:
                for bucket in bucket_ladder(self.max_bucket):
                    warm.add(self._gemm_key_locked(bucket))
                    if self.speculative:
                        warm.add(self._spec_gemm_key(bucket))
            steady: set[ExecKey] = {self._matvec_key_locked()}
            if self.speculative:
                steady.add(self._spec_matvec_key())
            if self.b_star is not None:
                # submit() promotes any request with b >= b* to the block
                # path and splits it into max_bucket chunks plus one
                # remainder — so every width in 1..max_bucket is a
                # reachable chunk, riding the bucket bucket_for() routes
                # it to. Evaluate that routing exhaustively.
                for width in range(1, self.max_bucket + 1):
                    bucket = bucket_for(width, self.max_bucket)
                    steady.add(self._gemm_key_locked(bucket))
                    if self.speculative:
                        steady.add(self._spec_gemm_key(bucket))
            fault: set[ExecKey] = set()
            for key, _ in self._matvec_levels_locked()[1:]:
                fault.add(key)
            if self.b_star is not None:
                for bucket in bucket_ladder(self.max_bucket):
                    for key, _ in self._gemm_levels_locked(bucket)[1:]:
                        fault.add(key)
            for op in solver_ops:
                bucket = solver_bucket(op, restart=restart, steps=steps)
                levels = self._solver_levels_locked(op, bucket, restart, steps)
                warm.add(levels[0][0])
                steady.add(levels[0][0])
                for key, _ in levels[1:]:
                    fault.add(key)
        return {
            "warmup": sorted(k.label() for k in warm),
            "steady": sorted(k.label() for k in steady),
            "fault_only": sorted(k.label() for k in fault - warm - steady),
        }

    def prediction_config(self, b: int = 1, rtol: float | None = None) -> dict:
        """The cost model's view of one dispatch through this engine's
        PREFERRED config (``tuning.cost_model.CostModel.predict`` /
        ``predict_admission`` kwargs): the resolved combine schedule —
        the strategy's static default when none was pinned, since that is
        the schedule a ``combine=None`` build lowers — at the bucket a
        ``b``-column request would actually ride (``b >= b*`` promotes to
        the padded GEMM bucket; below it the per-column path dispatches
        ``b`` single-RHS programs, which the caller models as ``b``
        sequential ``b=1`` predictions). A request declaring an ELIGIBLE
        ``rtol`` on a speculative-armed engine prices as
        ``storage="speculate"`` — the two-tier expected cost
        ``T_quant + T_check + ε·T_native`` (tuning/cost_model.py).
        Degradation-ladder fallbacks are deliberately not modeled —
        admission predicts the healthy path, and sustained divergence is
        the cost model's own regression signal (docs/COST_MODEL.md)."""
        gemm = self.b_star is not None and b >= self.b_star  # unguarded-ok: advisory cost-model snapshot; a racing reshard yields one stale prediction, never corruption
        combine = self._effective_combine(
            self._gemm_combine if gemm else self._matvec_combine  # unguarded-ok: advisory cost-model snapshot; a racing reshard yields one stale prediction, never corruption
        )
        if combine is None:
            combine = self.strategy.default_combine(self.mesh)  # unguarded-ok: advisory cost-model snapshot; a racing reshard yields one stale prediction, never corruption
        storage = self.storage
        if self.speculative and spec_eligible(rtol):  # unguarded-ok: advisory cost-model snapshot; a racing reshard yields one stale prediction, never corruption
            storage = SPECULATE
        return dict(
            strategy=self.strategy.name,  # unguarded-ok: advisory cost-model snapshot; a racing reshard yields one stale prediction, never corruption
            combine=combine,
            stages=self.stages,  # unguarded-ok: advisory cost-model snapshot; a racing reshard yields one stale prediction, never corruption
            m=self.m,
            k=self.k,
            p=mesh_size(self.mesh),
            dtype=str(self.dtype),
            b=bucket_for(b, self.max_bucket) if gemm else 1,
            storage=storage,
        )

    # ---- construction-time resolution ----

    def _resolve_storage_locked(self, dtype_storage: str | None) -> str:
        """Pin the resident-A storage format at construction (the quantize
        step is once-at-residency by doctrine). ``"auto"`` consults the
        tuned sixth axis and degrades to native on a miss, an
        unknown/unsupported winner (a foreign cache's fp8 on a backend
        without the dtype), or a strategy instance bound to an A-tiling
        combine — auto must never be worse-informed than native. An
        EXPLICIT format fails loudly instead: a serve config that asked
        for quantized storage must not silently serve full-width bytes.

        Also the ONE place the speculative tier arms
        (``dtype_storage="speculate"``, or a tuned ``speculate`` winner
        under ``"auto"``) and the one place ``storage_reason`` is
        written: health()/obs must distinguish "explicitly quantized"
        from "auto-degraded to native", and a degrade here is counted in
        ``engine_storage_fallbacks_total`` once the metrics registry
        exists (``_storage_degraded``)."""
        self.speculative = False
        self._storage_degraded = False
        self.storage_reason = (
            "default" if dtype_storage is None else "explicit"
        )

        def _degrade() -> str:
            self.storage_reason = "auto_degraded"
            self._storage_degraded = True
            return NATIVE

        if dtype_storage == SPECULATE:
            if not self.strategy.storage_combine_ok(None):
                raise ConfigError(
                    f"strategy {self.strategy.name!r} binds an A-tiling "
                    "combine schedule, which cannot compose with the "
                    "speculative int8c resident (dtype_storage="
                    f"{SPECULATE!r}; docs/QUANTIZATION.md)"
                )
            # The PRIMARY residency stays native: rtol=None requests ride
            # the exact pre-speculation path, bitwise-identical.
            self.speculative = True
            return NATIVE
        if dtype_storage == "auto":
            from ..tuning import lookup_storage

            decision = lookup_storage(
                strategy=self.strategy.name, m=self.m, k=self.k,
                p=mesh_size(self.mesh), dtype=str(self.dtype),
            )
            fmt = (decision or {}).get("storage") or NATIVE
            if fmt == SPECULATE:
                if not self.strategy.storage_combine_ok(None):
                    return _degrade()
                self.speculative = True
                self.storage_reason = "tuned"
                return NATIVE
            try:
                fmt = normalize_storage(fmt)
            except ConfigError:
                return _degrade()  # foreign cache, unknown format name
            if fmt == "fp8" and not fp8_supported():
                return _degrade()
            if fmt != NATIVE and not self.strategy.storage_combine_ok(None):
                return _degrade()
            self.storage_reason = "tuned" if decision else "auto_miss"
            return fmt
        fmt = normalize_storage(dtype_storage)
        if fmt != NATIVE and not self.strategy.storage_combine_ok(None):
            raise ConfigError(
                f"strategy {self.strategy.name!r} binds an A-tiling "
                "combine schedule, which cannot compose with quantized "
                f"dtype_storage={fmt!r} (docs/QUANTIZATION.md)"
            )
        return fmt

    def _resolve_combine_locked(
        self, combine: str | None
    ) -> tuple[str | None, str | None]:
        """Pin the combine schedule for both paths at construction.

        ``"auto"`` reads the tuning cache here, once — the engine's shapes
        are fixed for its lifetime, so deferring to trace time (what
        ``build(combine="auto")`` does) would only move a dict lookup into
        the dispatch path. An explicit name binds the matvec path always,
        and the batched path when the strategy has an in-body batched face
        for it (the matvec-only ``"ring"`` output gather falls back to the
        batched default: on that path the output gather is XLA's).
        """
        mesh = self.mesh
        if combine not in (None, "auto") and not self.strategy.supports_combine(
            combine
        ):
            # Fail at construction, not at first-dispatch compile: a serve
            # loop must not discover a bad schedule name requests deep.
            raise ConfigError(
                f"strategy {self.strategy.name!r} has no combine schedule "
                f"{combine!r}"
            )
        if (
            self.storage != NATIVE
            and combine not in (None, "auto")
            and not self.strategy.storage_combine_ok(combine)
        ):
            raise ConfigError(
                f"combine {combine!r} tiles A inside its schedule body and "
                f"cannot compose with quantized dtype_storage="
                f"{self.storage!r} (docs/QUANTIZATION.md)"
            )
        if combine == "auto":
            from ..tuning import lookup_combine

            common = dict(
                strategy=self.strategy.name, m=self.m, k=self.k,
                p=mesh_size(mesh), dtype=str(self.dtype),
            )
            mv = lookup_combine(op="matvec", **common)
            if mv not in self.strategy.combine_candidates(mesh):
                mv = None
            gm = lookup_combine(op="gemm", **common)
            if gm not in self.strategy.combine_candidates_batched(mesh):
                gm = None
            return mv, gm
        if combine is None:
            return None, None
        batched_ok = combine in self.strategy.combine_candidates_batched(
            mesh
        )
        return combine, (combine if batched_ok else None)

    def _effective_combine(self, combine: str | None) -> str | None:
        """The schedule a path actually runs: the explicit/resolved name,
        or the strategy instance's own binding (colwise_overlap & co.)
        when none was given."""
        if combine is not None:
            return combine
        return getattr(self.strategy, "combine", None)  # unguarded-ok: label helper serves both fenced dispatches and snapshot paths; readers tolerate a one-transition-stale name

    def _is_overlap(self, combine: str | None) -> bool:
        c = self._effective_combine(combine)
        return c is not None and c.startswith("overlap")

    def _resolve_stages_locked(self, stages: int | str | None) -> int | None:
        """Pin the overlap stage count S at construction (None when no
        path runs an overlap schedule — explicitly, via the auto tier, or
        through the strategy instance's own binding): the engine's shapes
        are fixed, so the tuned decision — or the explicit int, clamped to
        the shape's valid ladder — is resolved once and baked into the
        executable keys rather than looked up per dispatch."""
        if not (
            self._is_overlap(self._matvec_combine)
            or self._is_overlap(self._gemm_combine)
        ):
            return None
        return self.strategy.resolve_stages(
            self.m, self.k, self.mesh, stages,
            self.strategy.overlap_chunk_devices(self.mesh), self.dtype,
        )

    def _resolve_promotion_locked(self, promote: str | int | None) -> int | None:
        """The crossover ``b*``: requests of ``b >= b_star`` columns take
        the single-GEMM path; below it, per-column GEMV dispatches. None
        disables promotion entirely."""
        if promote is None:
            return None
        if promote == "auto":
            from ..tuning import lookup_promotion

            decision = lookup_promotion(
                strategy=self.strategy.name, m=self.m, k=self.k,
                p=mesh_size(self.mesh), dtype=str(self.dtype),
            )
            if decision is None:
                return DEFAULT_PROMOTE_B  # cache miss: static default
            # Measured "promotion never won" is None here — honored, not
            # treated as a miss.
            return decision.get("b_star")
        b_star = int(promote)
        if b_star < 1:
            raise ConfigError(f"promote must be >= 1, got {promote}")
        return b_star

    # ---- AOT builders ----

    def _kernel_label(self) -> str:
        return self.kernel if isinstance(self.kernel, str) else getattr(
            self.kernel, "__name__", "custom"
        )

    def _combine_label(self, combine: str | None) -> str | None:
        """The combine identity an executable is cached under: the staged
        schedules embed their pinned S (`overlap@4`) — a different stage
        count is a different compiled program. Strategy-bound overlap
        (colwise_overlap with combine=None) labels the same way."""
        if self.stages is not None and self._is_overlap(combine):  # unguarded-ok: label helper serves both fenced dispatches and snapshot paths; readers tolerate a one-transition-stale name
            return f"{self._effective_combine(combine)}@{self.stages}"  # unguarded-ok: label helper serves both fenced dispatches and snapshot paths; readers tolerate a one-transition-stale name
        return combine

    def _matvec_key_locked(self) -> ExecKey:
        return ExecKey(
            "matvec", self.strategy.name, self._kernel_label(),
            self._combine_label(self._matvec_combine), 1, str(self.dtype),
            self.storage,
        )

    def _gemm_key_locked(self, bucket: int) -> ExecKey:
        return ExecKey(
            "gemm", self.strategy.name, self._kernel_label(),
            self._combine_label(self._gemm_combine), bucket,
            str(self.dtype), self.storage,
        )

    def _a_struct_locked(self, storage: str):
        """The A-side argument struct for one storage format: the plain
        (m, k) array, or the quantized pytree's leaf structs — all carrying
        A's own sharding (the scales shard alongside their blocks)."""
        if storage == NATIVE:
            return jax.ShapeDtypeStruct(
                (self.m, self.k), self.dtype, sharding=self._sh_a
            )
        return quantized_like(
            self._qa_template,
            lambda leaf: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=self._sh_a
            ),
        )

    def _matvec_builder_for(self, kernel, combine, stages, storage=None):
        storage = self.storage if storage is None else storage

        def builder():
            fn = self.strategy.build(
                self.mesh, kernel=kernel,
                gather_output=self.gather_output,
                combine=combine, stages=stages,
                dtype_storage=None if storage == NATIVE else storage,
            )
            structs = (
                self._a_struct_locked(storage),
                jax.ShapeDtypeStruct(
                    (self.k,), self.dtype, sharding=self._sh_x
                ),
            )
            return fn, structs, self._donate

        return builder

    def _matvec_builder_locked(self):
        return self._matvec_builder_for(
            self.kernel, self._matvec_combine, self.stages
        )()

    def _gemm_builder_for(self, bucket: int, kernel, combine, stages,
                          storage=None):
        storage = self.storage if storage is None else storage

        def builder():
            fn = self.strategy.build_batched(
                self.mesh, kernel=kernel,
                gather_output=self.gather_output,
                combine=combine, stages=stages,
                dtype_storage=None if storage == NATIVE else storage,
            )
            structs = (
                self._a_struct_locked(storage),
                jax.ShapeDtypeStruct(
                    (self.k, bucket), self.dtype, sharding=self._sh_b
                ),
            )
            return fn, structs, self._donate

        return builder

    def _gemm_builder_locked(self, bucket: int):
        return self._gemm_builder_for(
            bucket, self.kernel, self._gemm_combine, self.stages
        )

    # ---- the speculative tier (docs/QUANTIZATION.md "speculative
    # serving"): candidate + fused acceptance check in ONE program,
    # keyed under storage="speculate" so it never collides with (or
    # perturbs) the native executables the rtol=None path rides. ----

    def _spec_combine(self, combine: str | None) -> str | None:
        """The combine the speculative (quantized) program runs: the
        engine's resolved name unless it tiles A inside its schedule
        body — the same filter quantized residency applies — in which
        case the static default serves."""
        return None if combine in STORAGE_INCOMPATIBLE_COMBINES else combine

    def _spec_matvec_key(self) -> ExecKey:
        return ExecKey(
            "matvec", self.strategy.name, self._kernel_label(),  # unguarded-ok: breaker-identity key; outside the fence only breaker admission/settlement reads it, and a stale key touches the old config's breaker once — benign
            self._spec_combine(self._matvec_combine), 1, str(self.dtype),  # unguarded-ok: breaker-identity key; outside the fence only breaker admission/settlement reads it, and a stale key touches the old config's breaker once — benign
            SPECULATE,
        )

    def _spec_gemm_key(self, bucket: int) -> ExecKey:
        return ExecKey(
            "gemm", self.strategy.name, self._kernel_label(),  # unguarded-ok: breaker-identity key; outside the fence only breaker admission/settlement reads it, and a stale key touches the old config's breaker once — benign
            self._spec_combine(self._gemm_combine), bucket,  # unguarded-ok: breaker-identity key; outside the fence only breaker admission/settlement reads it, and a stale key touches the old config's breaker once — benign
            str(self.dtype), SPECULATE,
        )

    def _spec_builder_for_locked(self, bucket: int | None = None):
        """Builder for the fused speculative program
        (``ops/speculative.py::build_speculative``). Operands are
        ``(aq, p, u, x, rtol)`` — the request ``x`` is python-arg 3, so
        donation names index 3, not the native paths' DONATE_ARGNUMS;
        ``rtol`` rides as a dynamic replicated scalar (changing
        tolerance never recompiles, the solver operands' rule)."""
        combine = self._spec_combine(
            self._matvec_combine if bucket is None else self._gemm_combine
        )

        def builder():
            fn = build_speculative(
                self.strategy, self.mesh, probes=self._spec_probes,
                kernel=self.kernel, combine=combine, stages=None,
                storage=SPEC_STORAGE, gather_output=self.gather_output,
                b=bucket,
            )
            s = self._spec_probes
            if bucket is None:
                x_struct = jax.ShapeDtypeStruct(
                    (self.k,), self.dtype, sharding=self._sh_x
                )
            else:
                x_struct = jax.ShapeDtypeStruct(
                    (self.k, bucket), self.dtype, sharding=self._sh_b
                )
            structs = (
                quantized_like(
                    self._spec_qa_template,
                    lambda leaf: jax.ShapeDtypeStruct(
                        leaf.shape, leaf.dtype, sharding=self._sh_a
                    ),
                ),
                jax.ShapeDtypeStruct(
                    (s, self.k), self.dtype, sharding=self._sh_p
                ),
                jax.ShapeDtypeStruct(
                    (s, self.m), self.dtype, sharding=self._sh_rep
                ),
                x_struct,
                jax.ShapeDtypeStruct((), np.float32, sharding=self._sh_rep),
            )
            return fn, structs, ((3,) if self._donate else ())

        return builder

    def _resolve_solver_kernel_locked(self, op: str) -> str:
        """The iteration tier one solve of ``op`` runs: "pallas_fused" or
        "xla". Explicit "pallas_fused" re-raises the fused tier's typed
        errors (the strategy/combine half already passed at construction;
        the op half lands here). "auto" asks the tuning cache
        (``tune_solver_kernel``'s axis) and stays on the established XLA
        tier on a miss — the tuner, not a default, flips the switch."""
        sk = self.solver_kernel
        if sk == "xla" or op not in ("cg", "chebyshev"):
            if sk == "pallas_fused":
                from ..ops.pallas_solver import check_fused_solver

                check_fused_solver(
                    op, self.strategy.name, self._requested_combine,
                    self.mesh,
                )
            return "xla"
        if sk == "pallas_fused":
            return "pallas_fused"
        from ..ops.pallas_solver import fused_solver_supported

        if not fused_solver_supported(
            op, self.strategy.name, self._requested_combine, self.mesh
        ):
            return "xla"
        from ..tuning import lookup_solver_kernel

        decision = lookup_solver_kernel(
            op=op, strategy=self.strategy.name, m=self.m, k=self.k,
            p=mesh_size(self.mesh), dtype=str(self.dtype),
            storage=self.storage,
        )
        if decision is None:
            return "xla"
        return decision.get("solver_kernel") or "xla"

    def _solver_key_locked(self, op: str, bucket: int) -> ExecKey:
        """A solver executable's cache identity: the matvec key with the
        op swapped in and the op's static shape parameter (GMRES restart,
        Lanczos steps) in the bucket field — differing rtol/maxiter
        values are dynamic operands, never new keys. A fused-tier solve
        keys on kernel="pallas_fused" and the fused body's canonical
        combine — honest identity for the artifact actually compiled."""
        if self._resolve_solver_kernel_locked(op) == "pallas_fused":
            from ..ops.pallas_solver import check_fused_solver

            return ExecKey(
                op, self.strategy.name, "pallas_fused",
                check_fused_solver(
                    op, self.strategy.name, self._requested_combine,
                    self.mesh,
                ),
                bucket, str(self.dtype), self.storage,
            )
        return ExecKey(
            op, self.strategy.name, self._kernel_label(),
            self._combine_label(self._matvec_combine), bucket,
            str(self.dtype), self.storage,
        )

    def _solver_builder_for(self, op, kernel, combine, stages, *,
                            restart, steps, storage=None):
        storage = self.storage if storage is None else storage

        def builder():
            fn = build_solver(
                op, self.strategy, self.mesh, dtype=self.dtype,
                kernel=kernel, combine=combine, stages=stages,
                dtype_storage=None if storage == NATIVE else storage,
                restart=restart, steps=steps,
            )
            scalar_f = jax.ShapeDtypeStruct(
                (), np.float32, sharding=self._sh_rep
            )
            structs = (
                self._a_struct_locked(storage),
                # The RHS rides replicated (the solver constrains it there
                # anyway; re-slicing a replicated vector to a strategy's
                # sharded x spec is a local slice, no collective).
                jax.ShapeDtypeStruct(
                    (self.k,), self.dtype, sharding=self._sh_rep
                ),
                scalar_f,  # rtol
                jax.ShapeDtypeStruct((), np.int32, sharding=self._sh_rep),
                scalar_f,  # interval lo (chebyshev; others ignore)
                scalar_f,  # interval hi
            )
            return fn, structs, self._donate

        return builder

    # ---- degradation ladders (resilience; docs/RESILIENCE.md) ----
    #
    # A ladder is an ordered list of (ExecKey, builder) config levels for
    # one logical dispatch: the preferred config first, the safe tier
    # (portable xla kernel, un-staged default combine, no overlap stages)
    # last. Levels whose key equals an earlier one are dropped, so an
    # engine already running the safe config has a one-level ladder. The
    # one blind spot: a strategy *instance* that binds its own combine
    # (colwise_overlap) keeps that binding under combine=None, so its
    # "safe" level is the same schedule under a different key — the
    # ladder still converges, it just cannot un-bind the instance.

    def _matvec_levels_locked(self) -> list[tuple[ExecKey, Callable]]:
        levels = self._ladders.get("matvec")
        if levels is not None:
            return levels
        levels = [(self._matvec_key_locked(), self._matvec_builder_locked)]
        # The safe tier is NATIVE storage by doctrine: a quantized config
        # that keeps failing should not be retried through another
        # quantized program — the unquantized original A (placed lazily,
        # _a_for_locked) is the known-good floor.
        safe_key = ExecKey(
            "matvec", self.strategy.name, SAFE_KERNEL, None, 1,
            str(self.dtype), NATIVE,
        )
        if safe_key != levels[0][0]:
            safe_builder = self._matvec_builder_for(
                SAFE_KERNEL, None, None, storage=NATIVE
            )
            levels.append((safe_key, safe_builder))
        self._ladders["matvec"] = levels
        return levels

    def _gemm_levels_locked(self, bucket: int) -> list[tuple[ExecKey, Callable]]:
        levels = self._ladders.get(bucket)
        if levels is not None:
            return levels
        levels = [(self._gemm_key_locked(bucket), self._gemm_builder_locked(bucket))]
        safe_key = ExecKey(
            "gemm", self.strategy.name, SAFE_KERNEL, None, bucket,
            str(self.dtype), NATIVE,
        )
        if safe_key != levels[0][0]:
            safe_builder = self._gemm_builder_for(
                bucket, SAFE_KERNEL, None, None, storage=NATIVE
            )
            levels.append((safe_key, safe_builder))
        self._ladders[bucket] = levels
        return levels

    def _solver_levels_locked(
        self, op: str, bucket: int, restart: int, steps: int
    ) -> list[tuple[ExecKey, Callable]]:
        """The solver's degradation ladder: the engine's preferred
        kernel/combine/storage first, then the same NATIVE-storage
        xla/default-combine safe floor every other dispatch path falls
        back to — a breaker opening on an exotic solver config degrades
        the solve, never refuses it."""
        cache_key = ("solver", op, bucket)
        levels = self._ladders.get(cache_key)
        if levels is not None:
            return levels
        preferred = self._solver_key_locked(op, bucket)
        if self._resolve_solver_kernel_locked(op) == "pallas_fused":
            # The fused tier: build_solver routes kernel="pallas_fused"
            # to ops/pallas_solver.py. It sees the REQUESTED combine
            # (the fused body owns its combine spelling) and no stages
            # (nothing left to overlap with).
            preferred_builder = self._solver_builder_for(
                op, "pallas_fused", self._requested_combine, None,
                restart=restart, steps=steps,
            )
        else:
            preferred_builder = self._solver_builder_for(
                op, self.kernel, self._matvec_combine, self.stages,
                restart=restart, steps=steps,
            )
        levels = [(preferred, preferred_builder)]
        safe_key = ExecKey(
            op, self.strategy.name, SAFE_KERNEL, None, bucket,
            str(self.dtype), NATIVE,
        )
        if safe_key != preferred:
            levels.append((
                safe_key,
                self._solver_builder_for(
                    op, SAFE_KERNEL, None, None,
                    restart=restart, steps=steps, storage=NATIVE,
                ),
            ))
        self._ladders[cache_key] = levels
        return levels

    # ---- dispatch (the hot path: enqueue-only, no host syncs) ----

    def _reclaim(self) -> None:
        """Drop completed dispatches from the outstanding window — a
        non-blocking sweep (``is_ready`` never waits)."""
        while self._outstanding and (
            bool(self._outstanding[0].is_ready())
            if hasattr(self._outstanding[0], "is_ready") else True
        ):
            self._outstanding.popleft()

    def _admit(self) -> None:
        """The backpressure gate: when the outstanding window is at its
        high-water mark even after reclaiming completed work, block on the
        OLDEST dispatch until it finishes (drain-oldest keeps the stream
        ordered and the device queue bounded — the enqueue-unboundedly
        contract the ROADMAP flagged). The blocking wait is a deliberate
        exception to the sync-free dispatch rule, confined to the
        over-high-water state the caller opted into."""
        if self.max_in_flight is None:
            return
        self._reclaim()
        while len(self._outstanding) >= self.max_in_flight:
            oldest = self._outstanding.popleft()
            if hasattr(oldest, "block_until_ready"):  # capability probe only; the wait is the next line
                oldest.block_until_ready()  # sync-ok: backpressure drain-oldest at the caller-set high-water mark
            self._c_drains.inc()
            self._reclaim()

    def _track(self, arr: jax.Array) -> jax.Array:
        if self.max_in_flight is not None:
            self._outstanding.append(arr)
        return arr

    def _a_for_locked(self, key: ExecKey):
        """The resident A operand matching one config level's storage
        format. Under quantized residency the native safe tier places the
        retained host A lazily on its FIRST degraded dispatch and keeps
        it — the extra HBM is spent only once a breaker actually routes
        around the quantized config, never up front. The placement is
        accounted like any other residency change (``native_fallback``
        listener reason): a degraded dispatch must not silently double a
        tenant's footprint. An evicted registry-managed engine re-places
        transparently here (a scheduler flush racing an eviction lands on
        a healed residency, not a crash)."""
        if key.storage == self.storage:
            if self._a is None:  # self-heal probe; ensure_resident re-checks under _residency_lock, and a lost race drops a buffer, not correctness
                # Transparent re-admission: enqueue-only, accounted, and
                # bitwise-identical to the pre-eviction residency.
                self.ensure_resident()  # lock-order-ok: phantom edge — the _locked convention assumes every own lock held, but every real caller of this dispatch tree holds only the _swap fence; callback-ok: the residency listener reconciles the registry ledger, which never re-enters engine locks, so firing here cannot deadlock
            return self._a  # the dispatch captures its own reference; refcounted residency keeps a concurrently evicted buffer alive for this dispatch
        if self._a_native is None:  # double-checked lazy placement — the decisive re-check runs under _residency_lock below
            while True:
                # Same layout-epoch guard as ensure_resident: never
                # install a pre-reshard-sharded safe tier over the
                # post-swap config.
                epoch = self._layout_epoch
                # Enqueue-only placement (device_put is async), not a sync.
                placed = jax.device_put(self._a_host, self._sh_a)
                with self._residency_lock:  # lock-order-ok: phantom edge — the _locked convention assumes every own lock held, but every real caller of this dispatch tree holds only the _swap fence
                    if self._layout_epoch != epoch:
                        continue  # resharded mid-placement: restage
                    if self._a_native is not None:
                        return self._a_native  # lost a concurrent race
                    self._a_native = placed
                break
            self._notify_residency(  # callback-ok: the residency listener reconciles the registry ledger, which never re-enters engine locks, so firing here cannot deadlock
                int(self._a_host.nbytes), "native_fallback"
            )
        return self._a_native  # same refcounted-capture tolerance as the payload return above

    def _get_traced(self, trace: ActiveTrace, key, builder):
        """Executable-cache lookup under its span, the hit|compile outcome
        read off the compile counter (no cache API change needed)."""
        with trace.span("exec_lookup") as span:
            before = self._cache.stats.compiles
            exe = self._cache.get(key, builder)
            span.attrs = {
                "outcome": (
                    "compile" if self._cache.stats.compiles > before
                    else "hit"
                )
            }
        return exe

    # ---- fault sites (no-ops without a FaultPlan) ----

    def _check_faults(self, site: str, key: ExecKey, block=None) -> bool:
        """Consult the fault plan at one site. Error kinds raise here;
        latency stalls here; returns True for a "nan" corruption (the
        caller marks the result part). False = healthy. A tenant-scoped
        engine presents its prefixed label (``tenant-7/op:...``) so specs
        can target one tenant; un-prefixed patterns still match via the
        base label (``FaultPlan.check``)."""
        plan = self._fault_plan
        if plan is None:
            return False
        label = key.label()
        action = plan.check(
            site, self._label_prefix + label, block=block,
            base_label=label if self._label_prefix else None,
        )
        if action is None:
            return False
        self._c_faults.inc()
        if action.error is not None:
            raise action.error
        if action.latency_ms > 0:
            # Injected straggler: a deliberate stall, not a host sync.
            time.sleep(action.latency_ms / 1e3)
            return False
        return action.corrupt

    def _exec_matvec_locked(
        self, col: np.ndarray, trace: ActiveTrace,
        key: ExecKey | None = None, builder=None,
    ) -> tuple[jax.Array, bool]:
        """One single-column dispatch at one config level. Returns the
        tracked device array plus the injected-corruption flag."""
        if key is None:
            key, builder = self._matvec_key_locked(), self._matvec_builder_locked
        if self._fault_plan is not None and key not in self._cache:
            self._check_faults("compile", key)
        exe = self._get_traced(trace, key, builder)
        corrupt = self._check_faults("dispatch", key, block=col)
        self._c_dispatches.inc()
        with trace.span("dispatch", op="matvec"):
            out = exe(self._a_for_locked(key), jax.device_put(col, self._sh_x))  # lock-order-ok: phantom edge — the _locked convention assumes every own lock held, but every real caller of this dispatch tree holds only the _swap fence
        return self._track(out), corrupt

    def _exec_gemm_locked(
        self, padded: np.ndarray, trace: ActiveTrace,
        key: ExecKey | None = None, builder=None,
    ) -> tuple[jax.Array, bool]:
        """One bucket-padded block dispatch at one config level."""
        bucket = padded.shape[1]
        if key is None:
            key, builder = self._gemm_key_locked(bucket), self._gemm_builder_locked(bucket)
        if self._fault_plan is not None and key not in self._cache:
            self._check_faults("compile", key)
        exe = self._get_traced(trace, key, builder)
        corrupt = self._check_faults("dispatch", key, block=padded)
        self._c_dispatches.inc()
        with trace.span("dispatch", op="gemm", bucket=bucket):
            out = exe(self._a_for_locked(key), jax.device_put(padded, self._sh_b))  # lock-order-ok: phantom edge — the _locked convention assumes every own lock held, but every real caller of this dispatch tree holds only the _swap fence
        return self._track(out), corrupt

    def _exec_solver_locked(
        self, op: str, rhs: np.ndarray, rtol: float, maxiter: int,
        lo: float, hi: float, trace: ActiveTrace,
        key: ExecKey, builder,
    ) -> tuple[SolverResult, bool]:
        """ONE solver dispatch at one config level: the whole iteration —
        loop, convergence predicate, residuals — is inside the compiled
        program, so this is a single enqueue exactly like a matvec
        dispatch (one ``dispatch`` trace span per solve, the property the
        solver demo's trace capture shows). The dynamic knobs ride as
        replicated scalar operands; the fault sites are the matvec path's
        ``compile``/``dispatch``, so existing fault specs match solver
        keys by the same label grammar."""
        if self._fault_plan is not None and key not in self._cache:
            self._check_faults("compile", key)
        exe = self._get_traced(trace, key, builder)
        corrupt = self._check_faults("dispatch", key, block=rhs)
        self._c_dispatches.inc()
        rep = self._sh_rep
        with trace.span("dispatch", op=op, bucket=key.bucket):
            out = exe(
                self._a_for_locked(key),
                jax.device_put(rhs, rep),
                jax.device_put(np.float32(rtol), rep),
                jax.device_put(np.int32(maxiter), rep),
                jax.device_put(np.float32(lo), rep),
                jax.device_put(np.float32(hi), rep),
            )
        self._track(out.x)
        return out, corrupt

    # ---- resilient dispatch: retries, breakers, the ladder ----

    def _breaker_for(self, key: ExecKey) -> CircuitBreaker:
        br = self._breakers.get(key)  # unguarded-ok: double-checked get-or-create fast path; the decisive lookup repeats under _breakers_lock below
        if br is None:
            with self._breakers_lock:
                br = self._breakers.get(key)
                if br is None:
                    # The transition callbacks stay lock-free (the
                    # callback-ok contract at every ladder call site):
                    # one counter inc plus one timeline append. The
                    # event carries cause_id — a state transition is a
                    # background consequence of the request whose
                    # dispatch tripped it, not the request itself.
                    label = key.label()

                    def _opened(label=label):
                        self._c_breaker_opens.inc()
                        self._timeline.emit(
                            "breaker_open",
                            cause_id=bound_request_id(), key=label,
                        )

                    def _recovered(label=label):
                        self._c_recoveries.inc()
                        self._timeline.emit(
                            "breaker_close",
                            cause_id=bound_request_id(), key=label,
                        )

                    br = self._resilience.make_breaker(
                        on_open=_opened, on_close=_recovered,
                    )
                    self._breakers[key] = br
        return br

    def _attempt_with_retry(self, key: ExecKey, builder, attempt_fn):
        """One ladder level, with bounded backoff retries for retryable
        faults (transient device errors). Non-retryable faults — compile
        failures, RESOURCE_EXHAUSTED, poisoned payloads — raise on the
        first attempt; the ladder (or the bucket shrink) takes over."""
        retry = self._resilience.retry
        serial = next(self._retry_serials)
        attempt = 1
        while True:
            try:
                return attempt_fn(key, builder)
            except Exception as exc:
                retryable, _ = classify_failure(exc)
                if not retryable or attempt >= retry.max_attempts:
                    raise
                self._c_retries.inc()
                # Correlates via the submit()-bound request id (the
                # retry runs synchronously inside the dispatch).
                self._timeline.emit(
                    "retry", key=key.label(), attempt=attempt,
                    fault=type(exc).__name__,
                )
                self._resilience.sleep(retry.delay_s(serial, attempt))
                attempt += 1

    def _walk_ladder(self, levels, attempt_fn):
        """Serve one dispatch from the first ladder level whose breaker
        admits it and whose attempt succeeds. The floor level is always
        attempted when reached — an open breaker must degrade a request,
        never refuse it. RESOURCE_EXHAUSTED propagates immediately (the
        fix is a smaller program, not a different schedule — the
        caller's bucket shrink). Payload faults (a poisoned request) are
        the REQUEST's fault, not the config's: they never feed the
        breaker (a client sending bad payloads must not degrade healthy
        traffic at the same key)."""
        last_exc: Exception | None = None
        preferred_label = levels[0][0].label()
        for i, (key, builder) in enumerate(levels):
            breaker = self._breaker_for(key)
            floor = i == len(levels) - 1
            if not breaker.allow() and not floor:
                continue
            try:
                out = self._attempt_with_retry(key, builder, attempt_fn)
            except Exception as exc:
                if is_payload_fault(exc):
                    breaker.record_inconclusive()
                else:
                    breaker.record_failure()
                last_exc = exc
                _, exhausted = classify_failure(exc)
                if exhausted:
                    raise
                continue
            breaker.record_success()
            with self._breakers_lock:  # health() copies _degraded under it
                if i == 0:
                    self._degraded.pop(preferred_label, None)
                else:
                    self._degraded[preferred_label] = key.label()
            if i > 0:
                self._c_downgrades.inc()
                self._timeline.emit(
                    "degrade", preferred=preferred_label,
                    served=key.label(), level=i,
                )
            return out
        raise last_exc  # every level failed: the request's real fate

    def _dispatch_matvec_locked(self, col: np.ndarray, trace: ActiveTrace) -> tuple:
        """One column -> one result part ``(array, None, corrupt)``."""
        if self._resilience is None:
            arr, corrupt = self._exec_matvec_locked(col, trace)  # lock-order-ok: phantom edge — the _locked convention assumes every own lock held, but every real caller of this dispatch tree holds only the _swap fence
            return (arr, None, corrupt)

        def attempt(key, builder):
            return self._exec_matvec_locked(col, trace, key, builder)

        arr, corrupt = self._walk_ladder(self._matvec_levels_locked(), attempt)  # lock-order-ok: phantom edge — the _locked convention assumes every own lock held, but every real caller of this dispatch tree holds only the _swap fence; callback-ok: the breaker's open callback is a metrics counter inc — no locks, no ledger re-entry
        return (arr, None, corrupt)

    def _dispatch_block_locked(self, chunk: np.ndarray, trace: ActiveTrace) -> list:
        """One <= max_bucket-wide chunk of real columns -> its dispatched
        parts: one bucket-padded GEMM part on the happy path; several
        under degradation (shrunken buckets on RESOURCE_EXHAUSTED, or the
        per-column GEMV floor when every GEMM level failed).

        Payload faults walk the same ladder/floor: a fault scoped to the
        GEMM configs (``key="gemm:*"`` poison) is legitimately SERVED by
        the GEMV floor — the ISSUE's promotion-GEMM→per-request-GEMV
        rung — so the walk cannot be short-circuited on
        ``is_payload_fault`` alone (the error does not say which keys
        its spec matches). The cost is bounded: an unscoped (``"*"``)
        poison wastes at most one bucket's per-column dispatches per
        bisection node, and only under an armed fault plan."""
        width = chunk.shape[1]
        bucket = bucket_for(width, self.max_bucket)
        with trace.span("bucket_pad", width=width, bucket=bucket):
            padded = pad_columns(chunk, bucket)
        if self._resilience is None:
            arr, corrupt = self._exec_gemm_locked(padded, trace)  # lock-order-ok: phantom edge — the _locked convention assumes every own lock held, but every real caller of this dispatch tree holds only the _swap fence
            return [(arr, width, corrupt)]

        def attempt(key, builder):
            return self._exec_gemm_locked(padded, trace, key, builder)

        try:
            arr, corrupt = self._walk_ladder(self._gemm_levels_locked(bucket), attempt)  # lock-order-ok: phantom edge — the _locked convention assumes every own lock held, but every real caller of this dispatch tree holds only the _swap fence; callback-ok: the breaker's open callback is a metrics counter inc — no locks, no ledger re-entry
            return [(arr, width, corrupt)]
        except Exception as exc:
            _, exhausted = classify_failure(exc)
            if exhausted and width > 1:
                # Shrunken bucket ladder: RESOURCE_EXHAUSTED means the
                # program is too big at this width — halve it and recurse
                # (each half re-enters the ladder at its own bucket key).
                self._c_downgrades.inc()
                mid = (width + 1) // 2
                return (
                    self._dispatch_block_locked(chunk[:, :mid], trace)  # lock-order-ok: phantom edge — the _locked convention assumes every own lock held, but every real caller of this dispatch tree holds only the _swap fence
                    + self._dispatch_block_locked(chunk[:, mid:], trace)  # lock-order-ok: phantom edge — the _locked convention assumes every own lock held, but every real caller of this dispatch tree holds only the _swap fence
                )
            # The GEMV floor: the promotion decision itself degrades —
            # serve the chunk per column through the matvec ladder. A
            # fault that also poisons the matvec path (payload poison,
            # key="*") still fails loudly here, as it must.
            self._c_downgrades.inc()
            return [
                self._dispatch_matvec_locked(chunk[:, j], trace)  # lock-order-ok: phantom edge — the _locked convention assumes every own lock held, but every real caller of this dispatch tree holds only the _swap fence
                for j in range(width)
            ]

    # ---- speculative dispatch (serve int8c first, verify on-device,
    # escalate only on miss — the ISSUE's two-tier path) ----

    def _spec_operands(self):
        """The speculative tier's device operands (quantized payload,
        projection P, probes U), self-healing residency exactly like
        :meth:`_a_for_locked`: an evicted registry tenant re-places
        transparently, enqueue-only, accounted under the payload
        residency."""
        if self._spec_qa is None:  # unguarded-ok: self-heal probe; ensure_resident re-checks under _residency_lock and a lost race is a dropped buffer, not corruption
            self.ensure_resident()
        return self._spec_qa, self._spec_p, self._spec_u  # unguarded-ok: the dispatch captures its own references; refcounted residency keeps concurrently evicted buffers alive for this dispatch

    def _spec_allowed(self) -> bool:
        """The speculative breaker's admission: escalation storms open it
        (record_failure per miss at settlement) and the tier stands down
        to native until the cooldown half-opens it — the existing
        breaker ladder, not a new mechanism. One breaker (the matvec
        spec key's) governs the whole tier; without resilience the tier
        is always admitted (escalations still count)."""
        if self._resilience is None:
            return True
        return self._breaker_for(self._spec_matvec_key()).allow()

    def _spec_admit(self, rtol: float | None) -> float | None:
        """The routing decision for one matvec/GEMM request: the declared
        tolerance when the speculative tier should serve it — armed,
        eligible (rtol at or above the floor the int8c budget sets), and
        the breaker admits. A pass on an ARMED engine is a visible
        storage fallback, never silent."""
        if rtol is None:
            return None
        rtol = float(rtol)
        if not (rtol > 0.0):
            raise ConfigError(f"rtol must be > 0, got {rtol}")
        if not self.speculative:  # unguarded-ok: routing probe outside the fence; a stale read routes one request to the old tier, and the fenced dispatch itself sees one consistent layout
            return None
        if not spec_eligible(rtol) or not self._spec_allowed():
            self._c_storage_fallbacks.inc()
            return None
        return rtol

    def _spec_record(self, accepted: bool) -> None:
        """Settlement bookkeeping (runs at materialization, host-side by
        contract): verdict counters, the ε gauge the cost model reads,
        and the speculative breaker — a miss is the CONFIG's failure
        signal (quantization budget blown for this operand mix), so it
        feeds the breaker like any degraded dispatch."""
        if not accepted:
            self._c_escalations.inc()
        # One EWMA observation per settlement (1.0 = miss): the ε feed
        # tracks RECENT traffic, not the lifetime ratio — a clean hour
        # decays an old escalation storm out of the estimate instead of
        # averaging it in forever (obs/registry.py EwmaGauge).
        self._g_escalation_rate.observe(0.0 if accepted else 1.0)
        if self._resilience is not None:
            br = self._breaker_for(self._spec_matvec_key())
            (br.record_success if accepted else br.record_failure)()

    def _exec_spec_locked(self, x, rtol, trace, key, builder, bucket=None):
        """One speculative dispatch: candidate + fused check, ONE enqueue
        (the accept predicate is a device output of the same program —
        nothing here syncs; the verdict settles at materialization)."""
        if self._fault_plan is not None and key not in self._cache:
            self._check_faults("compile", key)
        exe = self._get_traced(trace, key, builder)
        corrupt = self._check_faults("dispatch", key, block=x)
        self._c_dispatches.inc()
        self._c_speculative.inc()
        qa, p, u = self._spec_operands()  # lock-order-ok: phantom edge — the _locked convention assumes every own lock held, but every real caller of this dispatch tree holds only the _swap fence; callback-ok: the residency listener reconciles the registry ledger, which never re-enters engine locks, so firing here cannot deadlock
        attrs = {"op": "matvec"} if bucket is None else {
            "op": "gemm", "bucket": bucket,
        }
        with trace.span("dispatch", kind="speculate", **attrs):
            y, _est, accept = exe(
                qa, p, u,
                jax.device_put(
                    x, self._sh_x if bucket is None else self._sh_b
                ),
                jax.device_put(np.float32(rtol), self._sh_rep),
            )
        self._track(y)
        return y, accept, corrupt

    def _spec_fallback(self, exc: Exception) -> None:
        """A speculative COMPILE/DISPATCH error (not a verdict miss) must
        never fail a request native would have served: classify it for
        the breaker, count the visible fallback, and let the caller ride
        native — whose own ladder/bucket machinery owns any further
        recovery (including RESOURCE_EXHAUSTED's bucket shrink)."""
        if self._resilience is not None:
            br = self._breaker_for(self._spec_matvec_key())
            if is_payload_fault(exc):
                br.record_inconclusive()
            else:
                br.record_failure()
        self._c_storage_fallbacks.inc()

    def _spec_part_matvec_locked(self, col: np.ndarray, rtol: float,
                          trace: ActiveTrace) -> tuple:
        """One column through the speculative tier -> one 5-part
        ``(candidate, None, corrupt, accept, resolve)``. ``resolve``
        runs at settlement: bookkeeping on accept; on a miss it IS the
        escalation — a traced native re-dispatch (span kind=escalate)
        through the regular ladder, never a silent wrong answer."""
        try:
            y, accept, corrupt = self._exec_spec_locked(
                col, rtol, trace, self._spec_matvec_key(),
                self._spec_builder_for_locked(),
            )
        except Exception as exc:  # swallow-ok: _spec_fallback records it (breaker + fallbacks counter); the request rides the native ladder, which owns recovery
            self._spec_fallback(exc)  # callback-ok: the breaker's open callback is a metrics counter inc — no locks, no ledger re-entry
            return self._dispatch_matvec_locked(col, trace)

        def resolve(accepted: bool) -> list:
            # Settlement runs on the materializing thread: re-bind the
            # request id so the breaker feed and the re-dispatch's
            # events correlate like the original dispatch did.
            with bind_request(trace.request_id):
                self._spec_record(accepted)
                if accepted:
                    return []
                self._timeline.emit("escalate", op="matvec")
                # Settlement-time escalation is a dispatch like any
                # other: it must see ONE layout under the swap fence (a
                # reshard may have committed between the speculative
                # enqueue and this verdict).
                with self._swap_lock:
                    with trace.span(
                        "escalate", op="matvec", kind="escalate"
                    ):
                        return [self._dispatch_matvec_locked(col, trace)]

        return (y, None, corrupt, accept, resolve)

    def _spec_part_block_locked(self, chunk: np.ndarray, rtol: float,
                         trace: ActiveTrace) -> list:
        """One <= max_bucket-wide chunk through the speculative GEMM
        tier; the batched check accepts only when EVERY real column
        passes (pad columns are zero and trivially pass), so a miss
        escalates the whole chunk through the native block path."""
        width = chunk.shape[1]
        bucket = bucket_for(width, self.max_bucket)
        with trace.span("bucket_pad", width=width, bucket=bucket):
            padded = pad_columns(chunk, bucket)
        try:
            y, accept, corrupt = self._exec_spec_locked(
                padded, rtol, trace, self._spec_gemm_key(bucket),
                self._spec_builder_for_locked(bucket), bucket=bucket,
            )
        except Exception as exc:  # swallow-ok: _spec_fallback records it (breaker + fallbacks counter); the chunk rides the native block path, which owns recovery
            self._spec_fallback(exc)  # callback-ok: the breaker's open callback is a metrics counter inc — no locks, no ledger re-entry
            return self._dispatch_block_locked(chunk, trace)

        def resolve(accepted: bool) -> list:
            # Same re-binding + swap-fence rules as the matvec
            # escalation above.
            with bind_request(trace.request_id):
                self._spec_record(accepted)
                if accepted:
                    return []
                self._timeline.emit("escalate", op="gemm", width=width)
                with self._swap_lock:
                    with trace.span(
                        "escalate", op="gemm", kind="escalate"
                    ):
                        return self._dispatch_block_locked(chunk, trace)

        return [(y, width, corrupt, accept, resolve)]

    def submit(
        self,
        x=None,
        *,
        deadline_ms: float | None = None,
        integrity: bool | None = None,
        op: str = "matvec",
        rhs=None,
        rtol: float | None = None,
        maxiter: int | None = None,
        restart: int | None = None,
        steps: int | None = None,
        interval: tuple[float, float] | None = None,
    ) -> MatvecFuture:
        """Dispatch one request: a ``(k,)`` vector or a ``(k, b)`` block of
        ``b`` right-hand sides (columns). Returns immediately (unless the
        backpressure high-water mark forces a drain); the result future
        materializes (and unpads) on demand.

        ``deadline_ms``: a request whose deadline has elapsed before
        dispatch gets a FAILED future (``result()`` raises
        :class:`DeadlineExceededError`) and no device work is enqueued —
        stale work is dropped at the door, not served late. The deadline
        is checked on entry (a non-positive value fails immediately) and
        again after the backpressure drain; the drain itself is NOT
        interrupted mid-wait — the outstanding window must shrink for
        every later request regardless, and JAX exposes no timed wait — so
        the call can outlast the deadline by up to one drain before the
        failure is returned. A request that made it to dispatch always
        completes.

        ``integrity``: per-request override of the engine's NaN/Inf
        integrity gate (None = the engine default). The batching
        scheduler passes False and gates each coalesced request's own
        slice instead, so one corrupt column cannot fail its batchmates.

        A dispatch that fails despite the resilience ladder (or with no
        ladder configured) raises out of this call after finishing the
        request's trace with ``status=dispatch_failed`` and counting
        ``engine_dispatch_failures_total`` — callers (the scheduler's
        bisection, the serve bench's chaos loop) treat that as the
        request's failure, not the engine's.

        ``op`` (default ``"matvec"``) selects a SERVED SOLVER instead of
        a multiply: ``"cg"``/``"gmres"``/``"chebyshev"`` solve ``A x = b``
        against the resident A, ``"power"``/``"lanczos"`` estimate its
        extremal eigenpair (the request vector is then the start vector).
        ``rhs`` is an alias for the positional request (the
        ``engine.submit(op="cg", rhs=b, ...)`` spelling); ``rtol``/
        ``maxiter`` are DYNAMIC operands of one compiled loop (changing
        them never recompiles), while ``restart`` (gmres) and ``steps``
        (lanczos) are static shapes keyed into the executable's bucket.
        ``interval=(λ_min, λ_max)`` is chebyshev's required spectral
        interval. Solver submits return a :class:`SolverFuture`; see
        docs/SOLVERS.md for the convergence contract. The solver knobs
        other than ``rtol`` are ignored for ``op="matvec"``.

        ``rtol`` on a PLAIN matvec/GEMM request is the speculative
        contract (docs/QUANTIZATION.md "speculative serving"): the
        caller declares a relative tolerance, and a speculative-armed
        engine (``dtype_storage="speculate"``) may serve the request
        from the int8c resident — candidate and acceptance check fused
        in one program — escalating to a traced native re-dispatch only
        when the on-device check misses. ``rtol=None`` (the default)
        means EXACT: the dispatch is bitwise-identical to an engine with
        no speculative tier. For solver ops ``rtol=None`` keeps the
        historical 1e-6 default.
        """
        t0 = time.monotonic()
        t0_perf = time.perf_counter()
        if rhs is not None:
            if x is not None:
                raise ConfigError(
                    "pass the request as either the positional x or "
                    "rhs=, not both"
                )
            x = rhs
        if x is None:
            raise ConfigError("submit() needs a request vector or block")
        x = np.asarray(x, dtype=self.dtype)  # sync-ok: requests are host arrays (see module docstring)
        self._c_requests.inc()
        if op != "matvec":
            return self._submit_solver(
                x, op=op, rtol=rtol, maxiter=maxiter, restart=restart,
                steps=steps, interval=interval, deadline_ms=deadline_ms,
                t0=t0, t0_perf=t0_perf,
            )
        if x.ndim == 1:
            if x.shape[0] != self.k:
                raise ConfigError(
                    f"request length {x.shape[0]} != A columns {self.k}"
                )
        elif x.ndim != 2 or x.shape[0] != self.k:
            raise ConfigError(
                f"request must be (k,) or (k, b) with k={self.k}; got "
                f"shape {x.shape}"
            )
        elif x.shape[1] == 0:
            raise ConfigError("empty request (b=0)")
        # Direct submits (no scheduler above — warmup, tests, embedders)
        # allocate their correlation id from the SAME process counter the
        # schedulers use, so timeline ids never collide across layers;
        # the tracer adopts it via the momentary binding.
        if bound_request_id() is None:
            with bind_request(next_request_id()):
                trace = self.tracer.start(
                    cols=1 if x.ndim == 1 else int(x.shape[1]),
                    kind="vector" if x.ndim == 1 else "block",
                )
        else:
            trace = self.tracer.start(
                cols=1 if x.ndim == 1 else int(x.shape[1]),
                kind="vector" if x.ndim == 1 else "block",
            )
        self._timeline.emit(
            "submit", request_id=trace.request_id,
            cols=1 if x.ndim == 1 else int(x.shape[1]),
            shape="vector" if x.ndim == 1 else "block",
        )

        def _expired() -> bool:
            return (
                deadline_ms is not None
                and (time.monotonic() - t0) * 1e3 > deadline_ms
            )

        def _fail() -> MatvecFuture:
            self._c_deadline_failures.inc()
            trace.finish(status="deadline_failed")
            self._timeline.emit(
                "deadline_failed", request_id=trace.request_id,
                deadline_ms=deadline_ms,
            )
            self._h_submit.observe((time.perf_counter() - t0_perf) * 1e3)
            return MatvecFuture.failed(DeadlineExceededError(
                f"request deadline of {deadline_ms} ms elapsed in the "
                "backpressure gate before dispatch"
            ), trace=trace)

        spec_rtol = self._spec_admit(rtol)
        gate = self.integrity_gate if integrity is None else bool(integrity)
        integrity_counter = self._integrity_counter() if gate else None
        if spec_rtol is not None and integrity_counter is None:
            # Speculative answers are refused unconditionally when
            # non-finite (the solver doctrine): the caller declared a
            # tolerance, so a poisoned candidate must fail typed, never
            # serve within it — even when the optional gate is off.
            integrity_counter = self._integrity_counter()
        # The binding is what correlates everything fired from INSIDE the
        # dispatch — retries, ladder downgrades, breaker transitions —
        # with this request, with no per-call-site plumbing.
        with bind_request(trace.request_id), trace.span("submit"):
            if deadline_ms is not None and deadline_ms <= 0:
                # Stale on arrival (upstream queueing): skip even the drain.
                return _fail()
            with trace.span("gate", max_in_flight=self.max_in_flight):
                self._admit()  # may block draining the oldest dispatch
            if _expired():
                return _fail()
            try:
                # swap fence: the whole dispatch sees one layout; a
                # concurrent reshard commits strictly before or after it
                # (docs/RESHARDING.md). The backpressure drain above
                # stays OUTSIDE the fence — a blocked drain must not
                # stall a migration commit.
                with self._swap_lock:
                    if x.ndim == 1:
                        self._c_cols.inc()
                        part = (
                            self._spec_part_matvec_locked(x, spec_rtol, trace)
                            if spec_rtol is not None
                            else self._dispatch_matvec_locked(x, trace)
                        )
                        fut = MatvecFuture(
                            [part], vector=True,
                            trace=trace,
                            materialize_hist=self._h_materialize,
                            integrity_counter=integrity_counter,
                            timeline=self._timeline,
                        )
                        self._h_submit.observe(
                            (time.perf_counter() - t0_perf) * 1e3
                        )
                        return fut
                    b = x.shape[1]
                    self._c_cols.inc(b)
                    parts: list[tuple] = []
                    if self.b_star is not None and b >= self.b_star:
                        offset = 0
                        for width in split_widths(b, self.max_bucket):
                            chunk = x[:, offset:offset + width]
                            offset += width
                            parts.extend(
                                self._spec_part_block_locked(
                                    chunk, spec_rtol, trace
                                )
                                if spec_rtol is not None
                                else self._dispatch_block_locked(chunk, trace)
                            )
                    else:
                        for j in range(b):
                            parts.append(
                                self._spec_part_matvec_locked(
                                    x[:, j], spec_rtol, trace
                                )
                                if spec_rtol is not None
                                else self._dispatch_matvec_locked(x[:, j], trace)
                            )
                    fut = MatvecFuture(
                        parts, vector=False,
                        trace=trace, materialize_hist=self._h_materialize,
                        integrity_counter=integrity_counter,
                        timeline=self._timeline,
                    )
                    self._h_submit.observe(
                        (time.perf_counter() - t0_perf) * 1e3
                    )
                    return fut
            except BaseException as exc:
                # The dispatch failed past every configured recovery: the
                # request's trace must close (status says why) and the
                # failure must count before it surfaces to the caller.
                self._c_dispatch_failures.inc()
                trace.finish(status="dispatch_failed")
                self._timeline.emit(
                    "dispatch_failed", request_id=trace.request_id,
                    error=type(exc).__name__,
                )
                self._h_submit.observe((time.perf_counter() - t0_perf) * 1e3)
                raise

    def _solver_metric_handles(self):
        """The obs `solvers` panel's vocabulary, created on first use
        (constructor comment): iterations histogram, divergence counter,
        residual gauge, request counter."""
        if self._solver_metrics is None:
            self._solver_metrics = (
                self.metrics.counter(
                    "solver_requests_total", "solver submits accepted"
                ),
                self.metrics.histogram(
                    "solver_iterations",
                    "iterations the compiled solver loop ran per solve",
                ),
                self.metrics.counter(
                    "solver_divergences_total",
                    "solves that exhausted their cap unconverged "
                    "(SolverDivergedError raised at materialization)",
                ),
                self.metrics.gauge(
                    "solver_residual_norm",
                    "true residual norm of the last materialized solve",
                ),
                self.metrics.histogram(
                    "solver_iteration_time",
                    "per-iteration solve wall time, ms (submit-to-"
                    "materialize / n_iters) — the fused tier's win, "
                    "visible in the obs solvers panel",
                ),
            )
        return self._solver_metrics

    def _submit_solver(
        self, rhs: np.ndarray, *, op, rtol, maxiter, restart, steps,
        interval, deadline_ms, t0, t0_perf,
    ) -> SolverFuture:
        """The solver twin of :meth:`submit`'s dispatch tail: validate
        host-side (the knobs are Python values here — the last place a
        typed ConfigError can catch them), run the deadline/backpressure
        gate, then ONE dispatch through the solver's degradation
        ladder."""
        if op not in SOLVER_OPS:
            raise ConfigError(
                f"unknown op {op!r}; expected 'matvec' or one of "
                f"{sorted(SOLVER_OPS)}"
            )
        if self.m != self.k:
            raise ConfigError(
                f"op={op!r} iterates against a square resident A; this "
                f"engine holds {self.m}x{self.k}"
            )
        if rhs.ndim != 1 or rhs.shape[0] != self.k:
            raise ConfigError(
                f"op={op!r} takes one (k,) right-hand side with "
                f"k={self.k}; got shape {rhs.shape}"
            )
        # None keeps the solvers' historical default: submit()'s rtol
        # default changed to None for the speculative matvec contract
        # (None = exact there), but a solver ALWAYS has a tolerance.
        rtol = float(1e-6 if rtol is None else rtol)
        if not (rtol > 0.0):
            raise ConfigError(f"rtol must be > 0, got {rtol}")
        maxiter = (
            DEFAULT_SOLVER_MAXITER if maxiter is None else int(maxiter)
        )
        if maxiter < 1:
            raise ConfigError(f"maxiter must be >= 1, got {maxiter}")
        restart = DEFAULT_RESTART if restart is None else int(restart)
        steps = DEFAULT_STEPS if steps is None else int(steps)
        if op == "chebyshev":
            if interval is None:
                raise ConfigError(
                    "op='chebyshev' needs interval=(lambda_min, "
                    "lambda_max) — the semi-iteration is defined by its "
                    "spectral interval (estimate one with op='power'/"
                    "'lanczos'; docs/SOLVERS.md)"
                )
            lo, hi = float(interval[0]), float(interval[1])
            # Strictly ordered: reversed endpoints flip the recurrence's
            # sign structure and a zero-width interval makes c = 0 with
            # d = lo, degenerating the semi-iteration to a fixed-point
            # scheme the convergence theory doesn't cover — both are
            # config mistakes, caught here as typed errors rather than
            # discovered as a maxiter'd divergence.
            if not (0.0 < lo < hi):
                raise ConfigError(
                    f"chebyshev interval needs 0 < lambda_min < "
                    f"lambda_max (strict: a reversed or zero-width "
                    f"interval has no convergent semi-iteration); got "
                    f"({lo}, {hi})"
                )
        else:
            lo = hi = 0.0
        bucket = solver_bucket(op, restart=restart, steps=steps)
        (
            c_requests, iter_hist, c_div, g_resid, iter_time_hist,
        ) = self._solver_metric_handles()
        c_requests.inc()
        # Same global-id allocation as the matvec path for unscheduled
        # submits (the correlation-id contract: one process counter).
        if bound_request_id() is None:
            with bind_request(next_request_id()):
                trace = self.tracer.start(cols=1, kind=op)
        else:
            trace = self.tracer.start(cols=1, kind=op)
        self._timeline.emit(
            "submit", request_id=trace.request_id, cols=1, op=op,
        )

        def _expired() -> bool:
            return (
                deadline_ms is not None
                and (time.monotonic() - t0) * 1e3 > deadline_ms
            )

        def _fail() -> SolverFuture:
            self._c_deadline_failures.inc()
            trace.finish(status="deadline_failed")
            self._timeline.emit(
                "deadline_failed", request_id=trace.request_id,
                deadline_ms=deadline_ms,
            )
            self._h_submit.observe((time.perf_counter() - t0_perf) * 1e3)
            return SolverFuture.failed(DeadlineExceededError(
                f"request deadline of {deadline_ms} ms elapsed in the "
                "backpressure gate before dispatch"
            ), trace=trace)

        # Same correlation binding as the matvec path: ladder/breaker
        # events fired inside the solver dispatch carry this request id.
        with bind_request(trace.request_id), trace.span("submit"):
            if deadline_ms is not None and deadline_ms <= 0:
                return _fail()
            with trace.span("gate", max_in_flight=self.max_in_flight):
                self._admit()
            if _expired():
                return _fail()
            try:
                self._c_cols.inc()
                # swap fence: same one-layout-per-dispatch rule as the
                # matvec path (docs/RESHARDING.md).
                with self._swap_lock:
                    levels = self._solver_levels_locked(op, bucket, restart, steps)
                    if self._resilience is None:
                        key, builder = levels[0]
                        res, corrupt = self._exec_solver_locked(
                            op, rhs, rtol, maxiter, lo, hi, trace,
                            key, builder,
                        )
                    else:
                        def attempt(key, builder):
                            return self._exec_solver_locked(
                                op, rhs, rtol, maxiter, lo, hi, trace,
                                key, builder,
                            )

                        res, corrupt = self._walk_ladder(levels, attempt)  # callback-ok: the breaker's open callback is a metrics counter inc — no locks, no ledger re-entry
                fut = SolverFuture(
                    res, op=op, rtol=rtol,
                    cap=steps if op == "lanczos" else maxiter,
                    trace=trace, corrupt=corrupt,
                    materialize_hist=self._h_materialize,
                    integrity_counter=(
                        self._integrity_counter()
                        if self.integrity_gate else None
                    ),
                    iter_hist=iter_hist, divergence_counter=c_div,
                    residual_gauge=g_resid,
                    iter_time_hist=iter_time_hist,
                    dispatch_t0=time.perf_counter(),
                    timeline=self._timeline,
                )
                self._h_submit.observe((time.perf_counter() - t0_perf) * 1e3)
                return fut
            except BaseException as exc:
                self._c_dispatch_failures.inc()
                trace.finish(status="dispatch_failed")
                self._timeline.emit(
                    "dispatch_failed", request_id=trace.request_id,
                    error=type(exc).__name__,
                )
                self._h_submit.observe((time.perf_counter() - t0_perf) * 1e3)
                raise

    def __call__(self, x) -> np.ndarray:
        """Synchronous convenience: ``submit(x).result()``."""
        return self.submit(x).result()

    # ---- warmup & introspection ----

    def warmup(self, widths: Sequence[int] | None = None) -> int:
        """Pre-compile the executable set a request stream will hit: the
        single-RHS program plus (when promotion is on) every GEMM bucket —
        by default the whole ladder (any split remainder can land on any
        bucket), or exactly the buckets requests of ``widths`` columns
        would dispatch to under :meth:`submit`'s routing (sub-``b*`` widths
        take the per-column path, so they compile no GEMM bucket). Returns
        the number of fresh compiles. After this, a stream confined to
        those widths never compiles again — the serve bench's warm phase.
        A speculative-armed engine warms BOTH tiers (the fused check
        programs alongside the native ones), so a mixed rtol/exact
        stream — escalations included — runs compile-free."""
        with self._swap_lock:
            # Fence: a warm compiles against ONE layout — a racing
            # reshard commit waits for it, exactly like a dispatch.
            before = self._cache.stats.compiles
            self._cache.get(self._matvec_key_locked(), self._matvec_builder_locked)
            if self.speculative:
                self._cache.get(
                    self._spec_matvec_key(), self._spec_builder_for_locked()
                )
            if self.b_star is not None:
                if widths is None:
                    buckets = set(bucket_ladder(self.max_bucket))
                else:
                    buckets = set()
                    for w in widths:
                        if w < self.b_star:
                            continue  # submit() serves these per column
                        for chunk in split_widths(w, self.max_bucket):
                            buckets.add(bucket_for(chunk, self.max_bucket))
                for bucket in sorted(buckets):
                    self._cache.get(
                        self._gemm_key_locked(bucket), self._gemm_builder_locked(bucket)
                    )
                    if self.speculative:
                        self._cache.get(
                            self._spec_gemm_key(bucket),
                            self._spec_builder_for_locked(bucket),
                        )
            return self._cache.stats.compiles - before

    def _integrity_counter(self):
        """Get-or-create the integrity-failure counter (lazy so a plain
        engine's snapshot carries no gate vocabulary, but a per-request
        ``integrity=True`` override still counts)."""
        if self._c_integrity is None:
            self._c_integrity = self.metrics.counter(
                "engine_integrity_failures_total",
                "materializations the NaN/Inf integrity gate refused",
            )
        return self._c_integrity

    def health(self) -> dict:
        """Point-in-time resilience snapshot: breaker states per ExecKey,
        the configs currently serving degraded (preferred label → the
        fallback label actually dispatching), fault-injection tallies,
        the recovery counters, and the engine-local SLO burn-rate
        evaluation (``"slo"``; obs/slo.py — each call is one sample, so
        a polled endpoint accumulates burn history). Refreshes the
        ``resil_breakers_open`` gauge, so an obs snapshot taken after
        ``health()`` agrees with it. Cheap and lock-light — a health
        endpoint may poll it."""
        with self._breakers_lock:
            items = list(self._breakers.items())
            # _walk_ladder mutates _degraded under the same lock — an
            # unlocked dict() copy can raise mid-iteration when a config
            # flips between degraded and recovered on another thread.
            degraded = dict(self._degraded)
        breakers = {key.label(): br.snapshot() for key, br in items}
        if self._g_breakers_open is not None:
            self._g_breakers_open.set(
                sum(
                    1 for snap in breakers.values()
                    if snap["state"] != BREAKER_CLOSED
                )
            )

        def _val(counter) -> int:
            return counter.value if counter is not None else 0

        # Sustained predicted-vs-measured divergence of the tuning cost
        # model (tuning/cost_model.py): a regression signal — either the
        # machine drifted from its calibration or a schedule's real cost
        # changed. Read off the process default registry (the tuner's
        # emitter), not this engine's: tuning races run process-wide.
        from ..tuning.cost_model import divergence_health

        # Engine-local SLO burn rates (obs/slo.py, ENGINE_TARGETS): each
        # health() call is one sample, so a polled health endpoint
        # accumulates the burn history for free. Built lazily so a plain
        # engine's metrics snapshot carries no slo_* vocabulary until
        # someone actually polls health (the solver-metrics doctrine).
        if self._slo_monitor is None:
            from ..obs.slo import ENGINE_TARGETS, SloMonitor

            self._slo_monitor = SloMonitor(
                self.metrics, ENGINE_TARGETS
            )
        self._slo_monitor.sample()
        slo = self._slo_monitor.evaluate()

        return {
            "resilience": self._resilience is not None,
            "cost_model": divergence_health(),
            "slo": slo,
            "integrity_gate": self.integrity_gate,
            "storage": {
                "format": self.storage,
                # WHY this format serves: "explicit"/"tuned" vs
                # "auto_degraded"/"auto_miss"/"default" — the field that
                # makes an auto-degrade distinguishable from a caller's
                # own native ask (the satellite fix).
                "reason": self.storage_reason,  # unguarded-ok: health() is a monotone point-in-time probe; staleness by one transition is its contract
                "resident": self.resident,
                "resident_bytes": self.resident_bytes,  # unguarded-ok: health() is a monotone point-in-time probe; staleness by one transition is its contract
                "device_resident_bytes": self.device_resident_bytes,
                "block": self.storage_block,
                # True once the native safe tier has been placed (HBM is
                # then holding BOTH residencies — a degraded quantized
                # engine costs more than either alone).
                "native_fallback_resident": self._a_native is not None,  # unguarded-ok: health() is a monotone point-in-time probe; staleness by one transition is its contract
                "speculative": self.speculative,  # unguarded-ok: health() is a monotone point-in-time probe; staleness by one transition is its contract
                "escalation_rate": (
                    self._g_escalation_rate.value
                    if self._g_escalation_rate is not None else 0.0
                ),
            },
            "breakers": breakers,
            "degraded": degraded,
            "fault_injection": (
                self._fault_plan.summary()
                if self._fault_plan is not None else None
            ),
            "counters": {
                "retries": _val(self._c_retries),
                "downgrades": _val(self._c_downgrades),
                "breaker_opens": _val(self._c_breaker_opens),
                "recoveries": _val(self._c_recoveries),
                "faults_injected": _val(self._c_faults),
                "dispatch_failures": self._c_dispatch_failures.value,
                "deadline_failures": self._c_deadline_failures.value,
                "integrity_failures": _val(self._c_integrity),
                "storage_fallbacks": _val(self._c_storage_fallbacks),
                "speculative_dispatches": _val(self._c_speculative),
                "escalations": _val(self._c_escalations),
            },
        }

    @property
    def stats(self) -> EngineStats:
        s = self._cache.stats
        self._reclaim()  # in_flight reports live work, not finished stubs
        in_flight = len(self._outstanding)
        self._g_in_flight.set(in_flight)
        return EngineStats(
            compiles=s.compiles, hits=s.hits,
            requests=self._c_requests.value,
            dispatches=self._c_dispatches.value,
            cols=self._c_cols.value,
            in_flight=in_flight, drains=self._c_drains.value,
            deadline_failures=self._c_deadline_failures.value,
        )

    def flush_traces(self, timeout: float = 5.0) -> bool:
        """Fence the JSONL trace sink: every request finished before this
        call is on disk when it returns True (trivially so without
        ``trace_jsonl``). False means the sink could not confirm — a dead
        writer thread (unwritable path) or timeout — i.e. the trace file
        is missing or incomplete. Driver/reader code only — never part of
        the dispatch path."""
        return self.tracer.flush(timeout=timeout)

    def close(self) -> None:
        """Release the trace sink (writer thread + file handle) after
        draining it. An engine without ``trace_jsonl`` has nothing to
        release; an engine WITH one should be closed when retired —
        each sink is one daemon thread and one open append handle.

        Idempotent and exception-safe: a second ``close()`` is a no-op,
        and the sink is released even when the drain-fence cannot confirm
        (dead writer thread) or in-flight futures hold failures — their
        traces were finished at failure time, so the flush here is what
        puts them on disk. Outstanding-dispatch references are dropped
        (the device work itself cannot be cancelled; its results are
        simply no longer retained by the engine)."""
        if self._closed:
            return
        self._closed = True
        self._outstanding.clear()
        try:
            self.flush_traces()
        finally:
            self.tracer.close()

    @property
    def n_executables(self) -> int:
        return len(self._cache)

"""MatvecEngine: batched multi-RHS dispatch against a resident sharded A.

The paper's benchmark shape is one ``y = A·x`` at a time; the serving shape
(ROADMAP north star) is a *stream* of right-hand sides against a matrix
that never moves. The engine holds ``A`` resident in its strategy sharding
and serves requests through three mechanisms:

* **shape buckets** (``buckets.py``) — request widths quantize to a
  power-of-two ladder, so a mixed-width stream maps onto a bounded
  executable set;
* **AOT executable cache** (``executables.py``) — every (strategy × kernel
  × combine × bucket × dtype) program is ``lower().compile()``d exactly
  once, with the RHS buffer donated; after warmup the hot loop never
  traces, never compiles, and never host-syncs;
* **GEMV→GEMM promotion** — a batch of ``b ≥ b*`` right-hand sides rides
  the strategy's sharded program as ONE block GEMM
  (``MatvecStrategy.build_batched``; the MXU-bound formulation of "Large
  Scale Distributed Linear Algebra With TPUs", PAPERS.md) instead of ``b``
  GEMV dispatches; the crossover ``b*`` is the autotuner's fourth measured
  axis (``tuning/search.py::tune_promotion``), consulted per (strategy,
  shape, mesh, dtype) when ``promote="auto"``.

``submit`` returns a :class:`MatvecFuture` immediately — dispatch is
enqueue-only (JAX arrays are async by construction) and the host sync
happens only when the caller materializes the result. The dispatch path is
lint-enforced sync-free (``tests/test_lint.py``, ``scripts/tier1.sh``),
with one caller-opted exception: ``max_in_flight`` bounds the outstanding
dispatch window, and at the high-water mark ``submit`` blocks draining the
OLDEST dispatch (marked ``sync-ok``) instead of enqueueing unboundedly
ahead of the device. A per-request ``deadline_ms`` fails the future at
that gate rather than dispatching stale work; both are counted in
:class:`EngineStats` next to the compile/hit counters.

Each ``submit`` dispatches alone; coalescing *concurrent* requests into
one wider dispatch — the continuous-batching layer — is
``scheduler.py``'s job, stacked in front of this class.

Requests are HOST arrays (numpy): the engine owns host→device placement,
including dtype normalization and bucket padding. Handing it a device
array still works but the normalization copy becomes a device fetch —
a caller-visible sync the serving contract does not make.

Telemetry (``obs/``): every counter the engine reports lives in a
:class:`~..obs.registry.MetricsRegistry` (:class:`EngineStats` is a
point-in-time view over it — one source of truth, atomic under the
submit/materialize thread split), and every request records a span tree
(submit → gate → bucket_pad → exec_lookup → dispatch → materialize) into
the tracer's ring buffer — and, when ``trace_jsonl`` is set, onto the sink
thread's JSONL file. Recording is lock-free on the dispatch path (list
mutation + queue put; see ``obs/tracing.py``), and the I/O lint
(``tests/test_lint.py``) keeps blocking file writes off this module
entirely.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Callable, Sequence

import jax
import numpy as np

from ..models import get_strategy
from ..models.base import MatvecStrategy, mesh_size
from ..obs.registry import MetricsRegistry
from ..obs.sink import JsonlSink
from ..obs.tracing import ActiveTrace, RequestTracer
from ..utils.errors import ConfigError, DeadlineExceededError
from .buckets import (
    DEFAULT_MAX_BUCKET,
    bucket_for,
    bucket_ladder,
    pad_columns,
    split_widths,
)
from .executables import ExecKey, ExecStats, ExecutableCache

# Static promotion default on a tuning-cache miss: one GEMM dispatch
# replaces 4+ GEMV dispatches. Conservative on purpose — at b=4 the block
# re-reads A once instead of 4 times, so even bandwidth-bound shapes win,
# while b=2 can sit inside measurement noise on fast local backends.
DEFAULT_PROMOTE_B = 4


class MatvecFuture:
    """Async handle to one request's result.

    Holds the device arrays the dispatch produced (padded, when the GEMM
    path ran) plus the real column counts; materialization slices the pad
    columns away — the "masked-result unpad". ``result()`` host-syncs by
    definition (that is what materializing means); everything up to it is
    free of host round-trips.
    """

    def __init__(
        self,
        parts: Sequence[tuple[jax.Array, int | None]],
        vector: bool,
        trace: ActiveTrace | None = None,
        materialize_hist=None,
    ):
        # parts: (device_array, width) — width=None marks a rank-1 single
        # column; an int marks a rank-2 block whose first `width` columns
        # are real (the rest is bucket padding).
        self._parts = list(parts)
        self._vector = vector
        self._error: Exception | None = None
        # Request-lifecycle trace: opened by submit, completed here — the
        # materialize span and the finish that emits the record both run on
        # whichever thread materializes (sequential hand-off; tracing.py).
        self._trace = trace
        self._materialize_hist = materialize_hist

    @classmethod
    def failed(
        cls, error: Exception, trace: ActiveTrace | None = None
    ) -> "MatvecFuture":
        """A future that was never dispatched (deadline exceeded):
        ``result()`` raises ``error``, ``done()`` is immediately True."""
        fut = cls([], vector=True, trace=trace)
        fut._error = error
        return fut

    def device_values(self) -> list[jax.Array]:
        """The raw (still padded) device arrays — for callers chaining
        device-side work without materializing (empty for a failed
        future)."""
        return [arr for arr, _ in self._parts]

    def done(self) -> bool:
        """True when every part's device computation has completed (never
        blocks). A failed future is done by definition."""
        return all(
            bool(arr.is_ready()) if hasattr(arr, "is_ready") else True
            for arr, _ in self._parts
        )

    def exception(self) -> Exception | None:
        """The failure this future carries (DeadlineExceededError), or
        None for a dispatched request."""
        return self._error

    def result(self) -> np.ndarray:
        """Materialize on host: ``(m,)`` for a vector request, ``(m, b)``
        for a block request (pad columns sliced away). A failed future
        raises its error instead. Records the ``materialize`` span and
        finishes the request's trace (idempotent — a second call
        re-materializes but never re-emits)."""
        if self._error is not None:
            raise self._error
        trace = self._trace
        t0 = time.perf_counter()
        span = trace.span("materialize") if trace is not None else None
        status = "ok"
        try:
            if self._vector:
                arr, _ = self._parts[0]
                return np.asarray(arr)  # sync-ok: caller-requested materialization
            cols = []
            for arr, width in self._parts:
                host = np.asarray(arr)  # sync-ok: caller-requested materialization
                cols.append(
                    host[:, None] if width is None else host[:, :width]
                )
            return (
                cols[0] if len(cols) == 1
                else np.concatenate(cols, axis=1)
            )
        except BaseException:
            # A device error surfacing at the host fetch must not be
            # recorded as a fast successful request.
            status = "materialize_error"
            raise
        finally:
            if span is not None:
                span.__exit__(None, None, None)
                trace.finish(status=status)
            if self._materialize_hist is not None and status == "ok":
                self._materialize_hist.observe(
                    (time.perf_counter() - t0) * 1e3
                )


class EngineStats(ExecStats):
    """Executable-cache counters plus dispatch-level ones.

    ``in_flight`` is the outstanding-dispatch count at snapshot time;
    ``drains`` counts blocking waits the backpressure high-water mark
    forced; ``deadline_failures`` counts requests failed (never dispatched)
    because their ``deadline_ms`` elapsed in the backpressure gate.

    A point-in-time VIEW over the engine's metrics registry (the counters
    are the source of truth — ``engine.metrics.snapshot()`` reports the
    same numbers under the ``engine_*`` names). Updates are atomic
    registry increments, so concurrent submit/materialize/stats threads
    never tear a count (the bare-attribute race this class used to
    carry)."""

    def __init__(
        self, compiles: int, hits: int, requests: int, dispatches: int,
        cols: int, in_flight: int = 0, drains: int = 0,
        deadline_failures: int = 0,
    ):
        super().__init__(compiles=compiles, hits=hits)
        self.requests = requests
        self.dispatches = dispatches
        self.cols = cols
        self.in_flight = in_flight
        self.drains = drains
        self.deadline_failures = deadline_failures


class MatvecEngine:
    """Serve batches of right-hand sides against a resident sharded ``A``.

    Parameters
    ----------
    a : host (m, k) array — placed once with the strategy's A-sharding.
    mesh : target device mesh (default: all devices, ``make_mesh``).
    strategy : strategy name or instance (``models``).
    kernel : local kernel tier name (GEMV registry; the GEMM path maps it
        through ``gemm_kernel_name_for``). ``"auto"`` consults the tuning
        cache per local shape at trace time, as everywhere else.
    combine : combine schedule name, ``"auto"`` (resolved ONCE at engine
        construction from the tuning cache — per-dispatch resolution would
        put a cache lookup in the hot loop), or None for the static
        default.
    stages : stage count for the staged ``overlap`` schedules — an int, or
        None/``"auto"`` for the tuned fifth axis (``tune_overlap``; static
        default on a miss). Resolved ONCE at construction (the engine's
        shapes are fixed) and baked into the executable keys; ignored by
        every non-overlap schedule.
    dtype : operand dtype (default: ``a``'s).
    max_bucket : widest bucket in the ladder; wider requests split.
    promote : the GEMV→GEMM crossover ``b*``: ``"auto"`` (tuned decision,
        static :data:`DEFAULT_PROMOTE_B` on a miss), an int (explicit),
        or None (never promote — always the per-column path).
    donate : donate the RHS buffer to each dispatch (HBM reuse; ignored by
        backends that cannot donate, e.g. CPU).
    gather_output : as in ``MatvecStrategy.build`` (bools only).
    max_in_flight : backpressure high-water mark — the most outstanding
        dispatches ``submit`` tolerates before blocking on the OLDEST one
        (drain-oldest: the stream stays ordered and bounded instead of
        enqueueing unboundedly ahead of the device). None (default) keeps
        the unbounded contract. Request-granular: one wide split request
        may briefly overshoot by its part count.
    metrics : the obs MetricsRegistry the engine counts into (default: a
        fresh private registry — per-instance isolation). Pass a shared
        one to co-locate engine counters with caller-side metrics (the
        serve bench's dispatch-latency histogram) in one snapshot.
    trace_jsonl : path for the request-trace JSONL sink (``obs/sink.py``
        thread; None — ring buffer only). One line per finished request;
        ``flush_traces()`` fences the file.
    trace_capacity : finished-request records the in-memory ring retains
        (``tracer.traces()``).
    """

    def __init__(
        self,
        a,
        mesh=None,
        *,
        strategy: str | MatvecStrategy = "rowwise",
        kernel: str | Callable = "xla",
        combine: str | None = None,
        stages: int | str | None = None,
        dtype=None,
        max_bucket: int = DEFAULT_MAX_BUCKET,
        promote: str | int | None = "auto",
        donate: bool = True,
        gather_output: bool = True,
        max_in_flight: int | None = None,
        metrics: MetricsRegistry | None = None,
        trace_jsonl: str | os.PathLike | None = None,
        trace_capacity: int = 256,
    ):
        if mesh is None:
            from ..parallel.mesh import make_mesh

            mesh = make_mesh(len(jax.devices()))
        self.mesh = mesh
        self.strategy = (
            get_strategy(strategy) if isinstance(strategy, str) else strategy
        )
        a = np.asarray(a, dtype=dtype)  # sync-ok: one-time host staging of A
        if a.ndim != 2:
            raise ConfigError(f"A must be rank 2, got shape {a.shape}")
        self.m, self.k = a.shape
        self.dtype = a.dtype
        self.strategy.validate(self.m, self.k, mesh)
        if not isinstance(gather_output, bool):
            raise ConfigError(
                "engine gather_output must be True or False; got "
                f"{gather_output!r}"
            )
        self.kernel = kernel
        self.gather_output = gather_output
        self.max_bucket = max_bucket
        self._donate = (1,) if donate else ()
        self._sh_a, self._sh_x = self.strategy.shardings(mesh)
        _, self._sh_b = self.strategy.batched_shardings(mesh)
        self._a = jax.device_put(a, self._sh_a)  # resident for engine life
        self._matvec_combine, self._gemm_combine = self._resolve_combine(
            combine
        )
        self.stages = self._resolve_stages(stages)
        self.b_star = self._resolve_promotion(promote)
        if max_in_flight is not None and max_in_flight < 1:
            raise ConfigError(
                f"max_in_flight must be >= 1, got {max_in_flight}"
            )
        self.max_in_flight = max_in_flight
        self._outstanding: deque[jax.Array] = deque()
        # One source of truth for every count the engine reports: the
        # registry's atomic counters (EngineStats is a view; the serve
        # bench's --metrics-out snapshot is the same numbers).
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._c_requests = self.metrics.counter(
            "engine_requests_total", "submit() calls"
        )
        self._c_dispatches = self.metrics.counter(
            "engine_dispatches_total", "device programs enqueued"
        )
        self._c_cols = self.metrics.counter(
            "engine_cols_total", "right-hand-side columns accepted"
        )
        self._c_drains = self.metrics.counter(
            "engine_drains_total", "backpressure drain-oldest waits"
        )
        self._c_deadline_failures = self.metrics.counter(
            "engine_deadline_failures_total",
            "requests failed in the gate (deadline_ms elapsed)",
        )
        self._g_in_flight = self.metrics.gauge(
            "engine_in_flight", "outstanding dispatches at last snapshot"
        )
        self._h_submit = self.metrics.histogram(
            "engine_submit_latency_ms", "submit() entry-to-return host time"
        )
        self._h_materialize = self.metrics.histogram(
            "engine_materialize_latency_ms",
            "result() materialization host time (device wait included)",
        )
        self._cache = ExecutableCache(
            compile_counter=self.metrics.counter(
                "engine_compiles_total", "AOT executable compiles"
            ),
            hit_counter=self.metrics.counter(
                "engine_hits_total", "executable-cache hits"
            ),
        )
        self.tracer = RequestTracer(
            capacity=trace_capacity,
            sink=JsonlSink(trace_jsonl) if trace_jsonl is not None else None,
        )

    # ---- construction-time resolution ----

    def _resolve_combine(
        self, combine: str | None
    ) -> tuple[str | None, str | None]:
        """Pin the combine schedule for both paths at construction.

        ``"auto"`` reads the tuning cache here, once — the engine's shapes
        are fixed for its lifetime, so deferring to trace time (what
        ``build(combine="auto")`` does) would only move a dict lookup into
        the dispatch path. An explicit name binds the matvec path always,
        and the batched path when the strategy has an in-body batched face
        for it (the matvec-only ``"ring"`` output gather falls back to the
        batched default: on that path the output gather is XLA's).
        """
        mesh = self.mesh
        if combine not in (None, "auto") and not self.strategy.supports_combine(
            combine
        ):
            # Fail at construction, not at first-dispatch compile: a serve
            # loop must not discover a bad schedule name requests deep.
            raise ConfigError(
                f"strategy {self.strategy.name!r} has no combine schedule "
                f"{combine!r}"
            )
        if combine == "auto":
            from ..tuning import lookup_combine

            common = dict(
                strategy=self.strategy.name, m=self.m, k=self.k,
                p=mesh_size(mesh), dtype=str(self.dtype),
            )
            mv = lookup_combine(op="matvec", **common)
            if mv not in self.strategy.combine_candidates(mesh):
                mv = None
            gm = lookup_combine(op="gemm", **common)
            if gm not in self.strategy.combine_candidates_batched(mesh):
                gm = None
            return mv, gm
        if combine is None:
            return None, None
        batched_ok = combine in self.strategy.combine_candidates_batched(
            mesh
        )
        return combine, (combine if batched_ok else None)

    def _effective_combine(self, combine: str | None) -> str | None:
        """The schedule a path actually runs: the explicit/resolved name,
        or the strategy instance's own binding (colwise_overlap & co.)
        when none was given."""
        if combine is not None:
            return combine
        return getattr(self.strategy, "combine", None)

    def _is_overlap(self, combine: str | None) -> bool:
        c = self._effective_combine(combine)
        return c is not None and c.startswith("overlap")

    def _resolve_stages(self, stages: int | str | None) -> int | None:
        """Pin the overlap stage count S at construction (None when no
        path runs an overlap schedule — explicitly, via the auto tier, or
        through the strategy instance's own binding): the engine's shapes
        are fixed, so the tuned decision — or the explicit int, clamped to
        the shape's valid ladder — is resolved once and baked into the
        executable keys rather than looked up per dispatch."""
        if not (
            self._is_overlap(self._matvec_combine)
            or self._is_overlap(self._gemm_combine)
        ):
            return None
        return self.strategy.resolve_stages(
            self.m, self.k, self.mesh, stages,
            self.strategy.overlap_chunk_devices(self.mesh), self.dtype,
        )

    def _resolve_promotion(self, promote: str | int | None) -> int | None:
        """The crossover ``b*``: requests of ``b >= b_star`` columns take
        the single-GEMM path; below it, per-column GEMV dispatches. None
        disables promotion entirely."""
        if promote is None:
            return None
        if promote == "auto":
            from ..tuning import lookup_promotion

            decision = lookup_promotion(
                strategy=self.strategy.name, m=self.m, k=self.k,
                p=mesh_size(self.mesh), dtype=str(self.dtype),
            )
            if decision is None:
                return DEFAULT_PROMOTE_B  # cache miss: static default
            # Measured "promotion never won" is None here — honored, not
            # treated as a miss.
            return decision.get("b_star")
        b_star = int(promote)
        if b_star < 1:
            raise ConfigError(f"promote must be >= 1, got {promote}")
        return b_star

    # ---- AOT builders ----

    def _kernel_label(self) -> str:
        return self.kernel if isinstance(self.kernel, str) else getattr(
            self.kernel, "__name__", "custom"
        )

    def _combine_label(self, combine: str | None) -> str | None:
        """The combine identity an executable is cached under: the staged
        schedules embed their pinned S (`overlap@4`) — a different stage
        count is a different compiled program. Strategy-bound overlap
        (colwise_overlap with combine=None) labels the same way."""
        if self.stages is not None and self._is_overlap(combine):
            return f"{self._effective_combine(combine)}@{self.stages}"
        return combine

    def _matvec_key(self) -> ExecKey:
        return ExecKey(
            "matvec", self.strategy.name, self._kernel_label(),
            self._combine_label(self._matvec_combine), 1, str(self.dtype),
        )

    def _gemm_key(self, bucket: int) -> ExecKey:
        return ExecKey(
            "gemm", self.strategy.name, self._kernel_label(),
            self._combine_label(self._gemm_combine), bucket,
            str(self.dtype),
        )

    def _matvec_builder(self):
        fn = self.strategy.build(
            self.mesh, kernel=self.kernel,
            gather_output=self.gather_output,
            combine=self._matvec_combine, stages=self.stages,
        )
        structs = (
            jax.ShapeDtypeStruct(
                (self.m, self.k), self.dtype, sharding=self._sh_a
            ),
            jax.ShapeDtypeStruct((self.k,), self.dtype, sharding=self._sh_x),
        )
        return fn, structs, self._donate

    def _gemm_builder(self, bucket: int):
        def builder():
            fn = self.strategy.build_batched(
                self.mesh, kernel=self.kernel,
                gather_output=self.gather_output,
                combine=self._gemm_combine, stages=self.stages,
            )
            structs = (
                jax.ShapeDtypeStruct(
                    (self.m, self.k), self.dtype, sharding=self._sh_a
                ),
                jax.ShapeDtypeStruct(
                    (self.k, bucket), self.dtype, sharding=self._sh_b
                ),
            )
            return fn, structs, self._donate

        return builder

    # ---- dispatch (the hot path: enqueue-only, no host syncs) ----

    def _reclaim(self) -> None:
        """Drop completed dispatches from the outstanding window — a
        non-blocking sweep (``is_ready`` never waits)."""
        while self._outstanding and (
            bool(self._outstanding[0].is_ready())
            if hasattr(self._outstanding[0], "is_ready") else True
        ):
            self._outstanding.popleft()

    def _admit(self) -> None:
        """The backpressure gate: when the outstanding window is at its
        high-water mark even after reclaiming completed work, block on the
        OLDEST dispatch until it finishes (drain-oldest keeps the stream
        ordered and the device queue bounded — the enqueue-unboundedly
        contract the ROADMAP flagged). The blocking wait is a deliberate
        exception to the sync-free dispatch rule, confined to the
        over-high-water state the caller opted into."""
        if self.max_in_flight is None:
            return
        self._reclaim()
        while len(self._outstanding) >= self.max_in_flight:
            oldest = self._outstanding.popleft()
            if hasattr(oldest, "block_until_ready"):  # sync-ok: capability probe only, the wait is the next line
                oldest.block_until_ready()  # sync-ok: backpressure drain-oldest at the caller-set high-water mark
            self._c_drains.inc()
            self._reclaim()

    def _track(self, arr: jax.Array) -> jax.Array:
        if self.max_in_flight is not None:
            self._outstanding.append(arr)
        return arr

    def _get_traced(self, trace: ActiveTrace, key, builder):
        """Executable-cache lookup under its span, the hit|compile outcome
        read off the compile counter (no cache API change needed)."""
        with trace.span("exec_lookup") as span:
            before = self._cache.stats.compiles
            exe = self._cache.get(key, builder)
            span.attrs = {
                "outcome": (
                    "compile" if self._cache.stats.compiles > before
                    else "hit"
                )
            }
        return exe

    def _dispatch_matvec(self, col: np.ndarray, trace: ActiveTrace) -> jax.Array:
        exe = self._get_traced(
            trace, self._matvec_key(), self._matvec_builder
        )
        self._c_dispatches.inc()
        with trace.span("dispatch", op="matvec"):
            out = exe(self._a, jax.device_put(col, self._sh_x))
        return self._track(out)

    def _dispatch_gemm(self, padded: np.ndarray, trace: ActiveTrace) -> jax.Array:
        bucket = padded.shape[1]
        exe = self._get_traced(
            trace, self._gemm_key(bucket), self._gemm_builder(bucket)
        )
        self._c_dispatches.inc()
        with trace.span("dispatch", op="gemm", bucket=bucket):
            out = exe(self._a, jax.device_put(padded, self._sh_b))
        return self._track(out)

    def submit(self, x, *, deadline_ms: float | None = None) -> MatvecFuture:
        """Dispatch one request: a ``(k,)`` vector or a ``(k, b)`` block of
        ``b`` right-hand sides (columns). Returns immediately (unless the
        backpressure high-water mark forces a drain); the result future
        materializes (and unpads) on demand.

        ``deadline_ms``: a request whose deadline has elapsed before
        dispatch gets a FAILED future (``result()`` raises
        :class:`DeadlineExceededError`) and no device work is enqueued —
        stale work is dropped at the door, not served late. The deadline
        is checked on entry (a non-positive value fails immediately) and
        again after the backpressure drain; the drain itself is NOT
        interrupted mid-wait — the outstanding window must shrink for
        every later request regardless, and JAX exposes no timed wait — so
        the call can outlast the deadline by up to one drain before the
        failure is returned. A request that made it to dispatch always
        completes.
        """
        t0 = time.monotonic()
        t0_perf = time.perf_counter()
        x = np.asarray(x, dtype=self.dtype)  # sync-ok: requests are host arrays (see module docstring)
        self._c_requests.inc()
        if x.ndim == 1:
            if x.shape[0] != self.k:
                raise ConfigError(
                    f"request length {x.shape[0]} != A columns {self.k}"
                )
        elif x.ndim != 2 or x.shape[0] != self.k:
            raise ConfigError(
                f"request must be (k,) or (k, b) with k={self.k}; got "
                f"shape {x.shape}"
            )
        elif x.shape[1] == 0:
            raise ConfigError("empty request (b=0)")
        trace = self.tracer.start(
            cols=1 if x.ndim == 1 else int(x.shape[1]),
            kind="vector" if x.ndim == 1 else "block",
        )

        def _expired() -> bool:
            return (
                deadline_ms is not None
                and (time.monotonic() - t0) * 1e3 > deadline_ms
            )

        def _fail() -> MatvecFuture:
            self._c_deadline_failures.inc()
            trace.finish(status="deadline_failed")
            self._h_submit.observe((time.perf_counter() - t0_perf) * 1e3)
            return MatvecFuture.failed(DeadlineExceededError(
                f"request deadline of {deadline_ms} ms elapsed in the "
                "backpressure gate before dispatch"
            ), trace=trace)

        with trace.span("submit"):
            if deadline_ms is not None and deadline_ms <= 0:
                # Stale on arrival (upstream queueing): skip even the drain.
                return _fail()
            with trace.span("gate", max_in_flight=self.max_in_flight):
                self._admit()  # may block draining the oldest dispatch
            if _expired():
                return _fail()
            if x.ndim == 1:
                self._c_cols.inc()
                fut = MatvecFuture(
                    [(self._dispatch_matvec(x, trace), None)], vector=True,
                    trace=trace, materialize_hist=self._h_materialize,
                )
                self._h_submit.observe(
                    (time.perf_counter() - t0_perf) * 1e3
                )
                return fut
            b = x.shape[1]
            self._c_cols.inc(b)
            parts: list[tuple[jax.Array, int | None]] = []
            if self.b_star is not None and b >= self.b_star:
                offset = 0
                for width in split_widths(b, self.max_bucket):
                    chunk = x[:, offset:offset + width]
                    offset += width
                    bucket = bucket_for(width, self.max_bucket)
                    with trace.span("bucket_pad", width=width, bucket=bucket):
                        padded = pad_columns(chunk, bucket)
                    parts.append((self._dispatch_gemm(padded, trace), width))
            else:
                for j in range(b):
                    parts.append(
                        (self._dispatch_matvec(x[:, j], trace), None)
                    )
            fut = MatvecFuture(
                parts, vector=False,
                trace=trace, materialize_hist=self._h_materialize,
            )
            self._h_submit.observe((time.perf_counter() - t0_perf) * 1e3)
            return fut

    def __call__(self, x) -> np.ndarray:
        """Synchronous convenience: ``submit(x).result()``."""
        return self.submit(x).result()

    # ---- warmup & introspection ----

    def warmup(self, widths: Sequence[int] | None = None) -> int:
        """Pre-compile the executable set a request stream will hit: the
        single-RHS program plus (when promotion is on) every GEMM bucket —
        by default the whole ladder (any split remainder can land on any
        bucket), or exactly the buckets requests of ``widths`` columns
        would dispatch to under :meth:`submit`'s routing (sub-``b*`` widths
        take the per-column path, so they compile no GEMM bucket). Returns
        the number of fresh compiles. After this, a stream confined to
        those widths never compiles again — the serve bench's warm phase."""
        before = self._cache.stats.compiles
        self._cache.get(self._matvec_key(), self._matvec_builder)
        if self.b_star is not None:
            if widths is None:
                buckets = set(bucket_ladder(self.max_bucket))
            else:
                buckets = set()
                for w in widths:
                    if w < self.b_star:
                        continue  # submit() serves these per column
                    for chunk in split_widths(w, self.max_bucket):
                        buckets.add(bucket_for(chunk, self.max_bucket))
            for bucket in sorted(buckets):
                self._cache.get(
                    self._gemm_key(bucket), self._gemm_builder(bucket)
                )
        return self._cache.stats.compiles - before

    @property
    def stats(self) -> EngineStats:
        s = self._cache.stats
        self._reclaim()  # in_flight reports live work, not finished stubs
        in_flight = len(self._outstanding)
        self._g_in_flight.set(in_flight)
        return EngineStats(
            compiles=s.compiles, hits=s.hits,
            requests=self._c_requests.value,
            dispatches=self._c_dispatches.value,
            cols=self._c_cols.value,
            in_flight=in_flight, drains=self._c_drains.value,
            deadline_failures=self._c_deadline_failures.value,
        )

    def flush_traces(self, timeout: float = 5.0) -> bool:
        """Fence the JSONL trace sink: every request finished before this
        call is on disk when it returns True (trivially so without
        ``trace_jsonl``). False means the sink could not confirm — a dead
        writer thread (unwritable path) or timeout — i.e. the trace file
        is missing or incomplete. Driver/reader code only — never part of
        the dispatch path."""
        return self.tracer.flush(timeout=timeout)

    def close(self) -> None:
        """Release the trace sink (writer thread + file handle) after
        draining it. An engine without ``trace_jsonl`` has nothing to
        release; an engine WITH one should be closed when retired —
        each sink is one daemon thread and one open append handle."""
        self.tracer.close()

    @property
    def n_executables(self) -> int:
        return len(self._cache)

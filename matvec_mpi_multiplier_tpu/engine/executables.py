"""Persistent AOT executable cache: compile once, dispatch forever.

``jax.jit`` alone re-traces on every new input shape and holds its
executables in a global cache keyed by function identity — opaque to a
serving loop that needs to *know* (and prove, in the serve bench) that the
steady state never compiles. Here each program is lowered and compiled
ahead of time (``jit(fn).lower(*shapes).compile()`` — the GSPMD "compile
the sharded program once" discipline, PAPERS.md) and held under an explicit
key (strategy × kernel × combine × bucket × dtype), with compile and hit
counters the bench reports as first-class metrics.

Buffer donation: the RHS block argument is donated (``donate_argnums``) so
XLA may reuse its HBM for the output — every request allocates a fresh
padded RHS, so after dispatch its buffer is garbage by construction, and
without donation a b-wide fp32 stream at serving rate churns
``2 · b · (k + m)`` bytes of allocator traffic per request. Backends that
cannot donate (CPU today) silently ignore it — the engine stays correct,
just without the reuse.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax


class ExecKey(NamedTuple):
    """Identity of one AOT executable in the cache."""

    op: str        # "matvec" | "gemm"
    strategy: str
    kernel: str
    combine: str | None
    bucket: int    # RHS columns (1 for the matvec path)
    dtype: str


@dataclasses.dataclass
class ExecStats:
    """Counters the serve bench reports: a flat ``compiles`` across a warm
    request stream is the zero-recompilation acceptance criterion."""

    compiles: int = 0
    hits: int = 0

    def snapshot(self) -> "ExecStats":
        return ExecStats(self.compiles, self.hits)


class ExecutableCache:
    """AOT-compiled executables keyed by :class:`ExecKey`.

    ``get(key, builder)`` returns the cached executable or compiles it via
    ``builder()`` — which must return ``(fn, arg_structs, donate_argnums)``
    where ``arg_structs`` are ``jax.ShapeDtypeStruct``s carrying the input
    ``NamedSharding``s. The compiled executable accepts only arrays placed
    with exactly those shardings — the engine's dispatch contract.
    """

    def __init__(self) -> None:
        self._executables: dict[ExecKey, Any] = {}
        self.stats = ExecStats()

    def get(
        self,
        key: ExecKey,
        builder: Callable[[], tuple[Callable, tuple, tuple[int, ...]]],
    ):
        exe = self._executables.get(key)
        if exe is not None:
            self.stats.hits += 1
            return exe
        fn, arg_structs, donate = builder()
        exe = (
            jax.jit(fn, donate_argnums=donate)
            .lower(*arg_structs)
            .compile()
        )
        self._executables[key] = exe
        self.stats.compiles += 1
        return exe

    def __len__(self) -> int:
        return len(self._executables)

    def __contains__(self, key: ExecKey) -> bool:
        return key in self._executables

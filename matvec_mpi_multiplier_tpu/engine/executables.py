"""Persistent AOT executable cache: compile once, dispatch forever.

``jax.jit`` alone re-traces on every new input shape and holds its
executables in a global cache keyed by function identity — opaque to a
serving loop that needs to *know* (and prove, in the serve bench) that the
steady state never compiles. Here each program is lowered and compiled
ahead of time (``jit(fn).lower(*shapes).compile()`` — the GSPMD "compile
the sharded program once" discipline, PAPERS.md) and held under an explicit
key (strategy × kernel × combine × bucket × dtype), with compile and hit
counters the bench reports as first-class metrics.

Executables are a pure function of shapes, shardings and config — never
of ``A``'s values — so one cache may be SHARED across engines with equal
``MatvecEngine.exec_signature()``: the multi-tenant registry
(``registry.py``) hands N same-shaped tenants one cache and compiles
each ExecKey once for the fleet. Concurrent misses on the same key may
both compile (a benign race — identical programs; last write wins), but
a compiled entry is never invalidated, so tenants can never observe
divergent executables for one key.

Buffer donation: the RHS block argument is donated (``donate_argnums``) so
XLA may reuse its HBM for the output — every request allocates a fresh
padded RHS, so after dispatch its buffer is garbage by construction, and
without donation a b-wide fp32 stream at serving rate churns
``2 · b · (k + m)`` bytes of allocator traffic per request. Backends that
cannot donate (CPU today) silently ignore it — the engine stays correct,
just without the reuse.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Callable, NamedTuple

import jax

from ..obs.registry import Counter

# The dispatch path's donation spec: the RHS (arg index 1) is donated on
# every engine dispatch so XLA may reuse its HBM for the output (module
# docstring). ONE constant, shared with the staticcheck memory audit
# (staticcheck/hlo.py) — the donation the audit verifies is by
# construction the donation the engine sets.
DONATE_ARGNUMS: tuple[int, ...] = (1,)


def lower_artifact(builder: Callable[[], tuple[Callable, tuple, tuple[int, ...]]]):
    """The ONE compiled-artifact lowering recipe: ``builder()`` returns
    ``(fn, arg_structs, donate_argnums)`` and the artifact is
    ``jit(fn, donate_argnums).lower(*arg_structs)``. Shared between
    :meth:`ExecutableCache.get` (which compiles and fingerprints it) and
    the staticcheck memory audit (``staticcheck/hlo.py``), so the
    donation/aliasing and peak-liveness checks inspect byte-for-byte the
    lowering the engine dispatches — the two passes cannot disagree
    about which executable they audited."""
    fn, arg_structs, donate = builder()
    return jax.jit(fn, donate_argnums=donate).lower(*arg_structs)


class ExecKey(NamedTuple):
    """Identity of one AOT executable in the cache."""

    # "matvec" | "gemm" | a served solver op ("cg", "gmres", "power",
    # "lanczos", "chebyshev" — solvers/ops.py::SOLVER_OPS). Solver keys
    # reuse the bucket field for their static shape parameter (GMRES
    # restart, Lanczos steps); dynamic knobs (rtol, maxiter, interval)
    # are operands and never mint new keys.
    op: str
    strategy: str
    kernel: str
    combine: str | None
    bucket: int    # RHS columns (1 for the matvec path)
    dtype: str
    # Resident-A storage format (ops/quantize.py): "native" for the plain
    # array path, "int8"/"int8c"/"fp8" for quantized residency. A field
    # with a default so every pre-quantization construction site (and
    # pickled/pinned key literal) keeps meaning what it meant.
    storage: str = "native"

    def label(self) -> str:
        """Canonical ``op:strategy:kernel:combine:bucket:dtype[:storage]``
        string — the identity fault-injection patterns match against
        (``resilience/faults.py``) and ``engine.health()`` reports under.
        A None combine reads as ``default`` so patterns can target it;
        the storage suffix appears only for NON-native storage, so every
        existing pattern and pinned label keeps matching the configs it
        always matched (and ``*:int8`` targets quantized configs)."""
        combine = self.combine if self.combine is not None else "default"
        base = (
            f"{self.op}:{self.strategy}:{self.kernel}:{combine}:"
            f"{self.bucket}:{self.dtype}"
        )
        return base if self.storage == "native" else f"{base}:{self.storage}"


@dataclasses.dataclass
class ExecStats:
    """Counters the serve bench reports: a flat ``compiles`` across a warm
    request stream is the zero-recompilation acceptance criterion. A
    point-in-time VIEW of the cache's registry counters (``stats``
    property below) — the counters themselves are the source of truth."""

    compiles: int = 0
    hits: int = 0

    def snapshot(self) -> "ExecStats":
        return ExecStats(self.compiles, self.hits)


class ExecutableCache:
    """AOT-compiled executables keyed by :class:`ExecKey`.

    ``get(key, builder)`` returns the cached executable or compiles it via
    ``builder()`` — which must return ``(fn, arg_structs, donate_argnums)``
    where ``arg_structs`` are ``jax.ShapeDtypeStruct``s carrying the input
    ``NamedSharding``s. The compiled executable accepts only arrays placed
    with exactly those shardings — the engine's dispatch contract.

    Counting goes through obs counters (atomic — the thread-safety
    contract ``EngineStats`` documents): pass the engine's registry
    counters to share one source of truth with its metrics snapshot, or
    let the cache own private ones (standalone use). ``stats`` stays the
    familiar :class:`ExecStats` face, now a snapshot of those counters.
    """

    def __init__(
        self,
        compile_counter: Counter | None = None,
        hit_counter: Counter | None = None,
    ) -> None:
        self._executables: dict[ExecKey, Any] = {}
        self._fingerprints: dict[ExecKey, str] = {}
        self._compiles = compile_counter or Counter("compiles")
        self._hits = hit_counter or Counter("hits")

    @property
    def stats(self) -> ExecStats:
        return ExecStats(
            compiles=self._compiles.value, hits=self._hits.value
        )

    def get(
        self,
        key: ExecKey,
        builder: Callable[[], tuple[Callable, tuple, tuple[int, ...]]],
    ):
        exe = self._executables.get(key)
        if exe is not None:
            self._hits.inc()
            return exe
        lowered = lower_artifact(builder)
        # Fingerprint the lowering: the same ExecKey must always map to
        # the same program text, or the AOT cache would silently recompile
        # (or serve divergent programs) across restarts. The staticcheck
        # HLO auditor applies the same determinism gate to its own
        # strategy lowerings (staticcheck/hlo.py::run_hlo_audit — a
        # different lowering recipe, so its hashes are not comparable to
        # these); recording the hash here makes any one cache's identity
        # checkable across engines built from the same config. Hashed now,
        # stored only once compile() succeeds — a failed compile must not
        # leave a fingerprint for a key with no executable.
        fingerprint = hashlib.sha256(lowered.as_text().encode()).hexdigest()
        exe = lowered.compile()
        self._executables[key] = exe
        self._fingerprints[key] = fingerprint
        self._compiles.inc()
        return exe

    def fingerprint(self, key: ExecKey) -> str | None:
        """sha256 of the lowered program compiled for ``key`` (None before
        its first compile)."""
        return self._fingerprints.get(key)

    def keys(self) -> list[ExecKey]:
        """The ExecKeys compiled so far (insertion order) — the live half
        of the compile-surface story: the static keyspace audit
        (``staticcheck/keyspace.py``) enumerates what MAY compile, this
        lists what DID, and the cross-check test pins the first as a
        superset of the second."""
        return list(self._executables)

    def __len__(self) -> int:
        return len(self._executables)

    def __contains__(self, key: ExecKey) -> bool:
        return key in self._executables

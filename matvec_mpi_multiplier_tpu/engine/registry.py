"""Multi-tenant resident-matrix registry: many tenants' ``A`` matrices
against one fixed HBM budget.

The engine (``core.py``) holds exactly one resident ``A``; the ROADMAP
north star is a service holding THOUSANDS of tenants' matrices on a
device whose memory does not grow with the tenant count. The registry is
the layer that makes that honest — device memory is the binding
constraint at scale (GSPMD, arxiv 2105.04663; the TPU distributed-linalg
paper, arxiv 2112.09017), so the robustness question is not whether HBM
runs out but whether the service survives it gracefully, keeps tenants
isolated, and recovers without a restart. Five mechanisms:

* **HBM accountant** — every resident payload is charged to its tenant:
  the quantized pytree's bytes under quantized storage, AND the
  degradation ladder's lazily placed native safe tier (which used to
  allocate outside any accounting — a degraded tenant's footprint is
  payload + fallback, and the accountant sees both). Charges flow
  through the engine's ``residency_listener``, so the ledger follows
  ACTUAL placements, not intentions.
* **cost-aware LRU eviction with async swap** — admitting a non-resident
  tenant under a full budget evicts the resident tenant with the lowest
  ``last_used + cost_weight · (restore_bytes / mean_payload_bytes)``
  score: plain LRU for homogeneous tenants, a swap-cost bonus for
  tenants that are expensive to bring back (the GreedyDual-Size idea).
  Eviction is a pure reference drop (in-flight dispatches hold their own
  references — refcounted residency), so it is safe under the registry
  lock and safe against racing dispatches by construction; the swap-IN
  is an enqueue-only ``device_put`` issued OUTSIDE the lock, overlapped
  under other tenants' in-flight dispatches exactly like the staged
  transfers in ``parallel/ring.py`` overlap under the next stage's
  compute. An evicted tenant re-admits transparently on its next submit
  with bitwise-identical results (same host bytes, same executable).
* **warm-pinning** — :meth:`MatrixRegistry.pin` makes a hot tenant
  ineligible for eviction (and admits it immediately); :meth:`unpin`
  returns it to the eviction pool.
* **per-tenant quotas / admission control** — a tenant at its
  ``max_in_flight`` quota gets a FAILED future carrying a typed
  :class:`~..utils.errors.TenantQuotaError` before any dispatch: its
  burst fails ITS requests and exerts no eviction or degradation
  pressure on neighbors. Breakers, degradation ladders and the
  integrity gate are per-engine and therefore per-tenant already; fault
  patterns become tenant-addressable through the engine's
  ``label_prefix`` (``--fault-spec 'dispatch:device_error:key=
  tenant-7/*'`` targets exactly one tenant).
* **shared executables** — compiled programs depend on shapes and
  config, never on ``A``'s values, so tenants with equal
  ``exec_signature`` share one AOT :class:`~.executables.ExecutableCache`
  (N tenants, one compile per ExecKey).

Lock discipline (enforced by the ``device-transfer-under-registry-lock``
staticcheck rule, marker ``registry-ok:``): the registry mutex guards
bookkeeping only — never a ``device_put``, a dispatch, or a
``block_until_ready``. Victim release under the lock is legal (reference
drops only); placements and dispatches happen after it is released. The
mutex is reentrant because the engine's residency listener (which takes
it) fires inside victim release.

Budget semantics are SOFT at the edges, deliberately: when every
resident tenant is pinned or mid-submit, the admission proceeds anyway
and ``registry_budget_overshoots_total`` counts the breach — a full
budget must degrade to a measured overshoot, never to a refused or
deadlocked request. (Hard per-tenant admission is what quotas are for.)

Observability: per-tenant resident bytes, hit/evict/pin counters and
quota rejections live in the shared metrics registry under
``tenant_*{tenant="..."}`` names (the obs ``tenants`` panel renders
them; ``python -m matvec_mpi_multiplier_tpu.obs metrics``), and
:meth:`MatrixRegistry.health` mirrors them as one dict next to each
tenant engine's breaker/degradation state. Benchmarked by
``bench/serve.py --tenants/--zipf-a/--hbm-budget`` (the committed
capture lives in ``data/multitenant_demo/``); usage doctrine in
docs/MULTITENANT.md.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import threading
import time
from typing import Callable, Sequence

from ..obs.registry import MetricsRegistry, label
from ..obs.timeline import bound_request_id, get_hub
from ..utils.errors import ConfigError, TenantQuotaError
from .core import MatvecEngine, MatvecFuture
from .executables import ExecutableCache

# Eviction-score weight of restore cost relative to one recency step:
# a tenant twice the mean payload size gets one extra serial of
# protection per cost_weight unit. 1.0 keeps homogeneous fleets exactly
# LRU while still breaking recency ties toward the cheaper restore.
DEFAULT_COST_WEIGHT = 1.0

# Time constant of the per-tenant arrival-rate EWMA feeding demand-aware
# eviction (and exported as tenant_rate_req_per_s{tenant=...} gauges):
# long enough to remember a Zipf-hot tenant across a few of its gaps,
# short enough that a tenant going cold stops being protected within
# seconds.
DEFAULT_RATE_TAU_S = 5.0

# Tenant ids become fault-label prefixes (``<tenant>/op:strategy:...``),
# metric label values and CSV cells — the grammar forbids the separators
# those surfaces key on.
_TENANT_ID_FORBIDDEN = set(':/,"{}* \t\n')


def _validate_tenant_id(tenant_id: str) -> str:
    if not isinstance(tenant_id, str) or not tenant_id:
        raise ConfigError(
            f"tenant id must be a non-empty string, got {tenant_id!r}"
        )
    bad = _TENANT_ID_FORBIDDEN.intersection(tenant_id)
    if bad:
        raise ConfigError(
            f"tenant id {tenant_id!r} contains reserved characters "
            f"{sorted(bad)} (ids become fault-label prefixes, metric "
            "labels and CSV cells)"
        )
    return tenant_id


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Admission limits for one tenant.

    max_in_flight : most outstanding (not yet materialized) futures the
        tenant may hold; the next submit past it fails with
        :class:`TenantQuotaError` BEFORE dispatch. None = unlimited.
    max_resident_bytes : ceiling on the tenant's registered payload
        bytes, checked at :meth:`MatrixRegistry.register` — an A too big
        for the tenant's reservation is refused up front, not admitted
        and then thrashed. None = unlimited.
    """

    max_in_flight: int | None = None
    max_resident_bytes: int | None = None

    def __post_init__(self):
        if self.max_in_flight is not None and self.max_in_flight < 1:
            raise ConfigError(
                f"max_in_flight must be >= 1, got {self.max_in_flight}"
            )
        if (
            self.max_resident_bytes is not None
            and self.max_resident_bytes <= 0
        ):
            raise ConfigError(
                "max_resident_bytes must be positive, got "
                f"{self.max_resident_bytes}"
            )


class HbmAccountant:
    """The per-tenant HBM ledger. A plain object mutated only under the
    registry lock (no lock of its own). Entries are RECONCILED to each
    engine's actual current footprint rather than delta-applied: the
    residency listener fires outside the engine's residency bookkeeping
    lock, so a swap-in's notification can arrive AFTER the eviction that
    undid it — replaying deltas in that order would leak a phantom
    charge forever, while reconciling to the engine's present state
    converges to the truth regardless of arrival order. ``budget=None``
    means unlimited (accounting still runs — the tenants panel reports
    real bytes either way)."""

    def __init__(self, budget: int | None):
        if budget is not None and budget <= 0:
            raise ConfigError(f"hbm_budget must be positive, got {budget}")
        self.budget = int(budget) if budget is not None else None
        self.charged: dict[str, int] = {}
        self.overshoots = 0

    @property
    def total(self) -> int:
        return sum(self.charged.values())

    def headroom(self, needed: int) -> bool:
        """True when ``needed`` more bytes fit under the budget."""
        return self.budget is None or self.total + needed <= self.budget

    def reconcile(self, tenant_id: str, n: int) -> bool:
        """Set the tenant's ledger entry to its ACTUAL current footprint
        ``n``; True when this grew the entry past the budget (counted as
        an overshoot)."""
        prev = self.charged.get(tenant_id, 0)
        if n > 0:
            self.charged[tenant_id] = int(n)
        else:
            self.charged.pop(tenant_id, None)
        breached = (
            self.budget is not None and n > prev
            and self.total > self.budget
        )
        if breached:
            self.overshoots += 1
        return breached


class _Tenant:
    """Registry-internal per-tenant record (mutated under the registry
    lock; the engine itself is touched outside it)."""

    __slots__ = (
        "tenant_id", "engine", "quota", "pinned", "last_used", "active",
        "outstanding", "charged_bytes", "requests", "hits", "evictions",
        "evictions_caused", "quota_rejections", "swap_ins", "payload_sha",
        "rate", "resharding", "reshards", "g_resident_bytes", "g_pinned",
        "g_strategy", "c_requests", "c_hits", "c_evictions",
        "c_evictions_caused", "c_quota_rejections",
    )

    def __init__(self, tenant_id: str, engine: MatvecEngine,
                 quota: TenantQuota | None):
        self.tenant_id = tenant_id
        self.engine = engine
        self.quota = quota
        self.pinned = False
        self.last_used = 0
        self.active = 0          # submits between admission and dispatch
        self.outstanding: list[MatvecFuture] = []
        self.charged_bytes = 0   # actual placed bytes (payload + fallback)
        self.requests = 0
        self.hits = 0
        self.evictions = 0
        self.evictions_caused = 0
        self.quota_rejections = 0
        self.swap_ins = 0
        self.payload_sha = ""    # host-A content hash, lazy (coalesce groups)
        self.rate = None         # per-tenant arrival RateEstimator
        self.resharding = False  # one online migration at a time per tenant
        self.reshards = 0        # completed strategy migrations
        self.g_strategy = None   # current tenant_strategy{...} info gauge

    def sweep(self) -> None:
        """Drop consumed futures from the outstanding window (the quota
        denominator): a future is outstanding until the caller
        materializes it — un-materialized results are exactly the
        buffers still holding HBM, which is what the quota bounds. A
        pre-dispatch failure (deadline) retires on its raising
        ``result()`` too; the ``exception()`` probe covers a caller that
        polls instead. Never blocks."""
        self.outstanding = [
            f for f in self.outstanding
            if not f.retired and f.exception() is None
        ]


class TenantHandle:
    """The caller's face for one registered tenant: submit against its
    resident ``A``, pin/unpin it, read its stats. A thin delegate — the
    registry owns all state, so handles are freely copyable and remain
    valid until :meth:`MatrixRegistry.unregister`."""

    def __init__(self, registry: "MatrixRegistry", tenant_id: str):
        self._registry = registry
        self.tenant_id = tenant_id

    def submit(self, x, **kwargs) -> MatvecFuture:
        return self._registry.submit(self.tenant_id, x, **kwargs)

    def __call__(self, x):
        """Synchronous convenience: ``submit(x).result()``."""
        return self.submit(x).result()

    def pin(self) -> None:
        self._registry.pin(self.tenant_id)

    def unpin(self) -> None:
        self._registry.unpin(self.tenant_id)

    def reshard(self, strategy, *, warm_widths=None) -> dict | None:
        """Migrate this tenant's resident ``A`` to another strategy
        on-device (:meth:`MatrixRegistry.reshard`)."""
        return self._registry.reshard(
            self.tenant_id, strategy, warm_widths=warm_widths
        )

    @property
    def engine(self) -> MatvecEngine:
        return self._registry._entry(self.tenant_id).engine

    def stats(self) -> dict:
        return self._registry.tenant_stats(self.tenant_id)


# Engine parameters the registry owns — a caller supplying them would
# break the residency/accounting/identity contracts register() wires up.
_RESERVED_ENGINE_KWARGS = frozenset({
    "metrics", "retain_host", "defer_placement", "label_prefix",
    "exec_cache", "residency_listener", "fault_plan", "resilience",
    "integrity_gate",
})


class MatrixRegistry:
    """Per-tenant ``A`` registration, HBM accounting, cost-aware LRU
    eviction with async swap, warm-pinning and quota admission — the
    module docstring has the doctrine, docs/MULTITENANT.md the usage.

    Parameters
    ----------
    mesh : device mesh every tenant engine shares (default: all devices).
    hbm_budget : resident-payload byte budget across all tenants (None =
        unlimited; accounting still runs).
    cost_weight : eviction-score weight of restore cost vs recency
        (:data:`DEFAULT_COST_WEIGHT`; 0 = pure LRU).
    demand_weight : eviction-score weight of PREDICTED DEMAND — each
        tenant's EWMA arrival rate (its :class:`~..obs.registry.
        RateEstimator`, exported as ``tenant_rate_req_per_s{tenant=...}``)
        times its restore-cost ratio. One sustained request/s of demand
        on a mean-size payload buys ``demand_weight`` recency serials of
        protection: a hot tenant that is expensive to bring back stops
        being evicted just because its last hit is a few serials old.
        0 (the default) keeps the PR 9 recency+cost score exactly — the
        LRU-floor gates stay byte-for-byte; the global scheduler
        (``global_scheduler.py``; docs/SCHEDULING.md) turns it on.
    rate_tau_s / rate_clock : the demand estimators' EWMA time constant
        and injectable clock (tests drive a fake clock).
    eviction_listener : ``callable(victim_id, caused_by_id, score,
        restore_bytes)`` invoked after each eviction's reference drop,
        under the registry lock — bookkeeping only by the lock
        discipline (the global scheduler records the decision with its
        predicted restore cost).
    metrics : shared obs registry for the whole fleet (default: a fresh
        one). Tenant engines count into it too, so ``engine_*`` counters
        read as fleet aggregates; per-tenant truth lives under the
        ``tenant_*{tenant="..."}`` names.
    resilience / fault_plan / integrity_gate : forwarded to every tenant
        engine (one plan, per-tenant targeting via ``tenant-X/*`` key
        patterns; breakers and ladders are per-tenant by construction).
    **engine_defaults : forwarded to every tenant's
        :class:`~.core.MatvecEngine` (strategy, kernel, combine, stages,
        dtype_storage, max_bucket, promote, donate, ...); per-tenant
        overrides go to :meth:`register`.
    """

    def __init__(
        self,
        mesh=None,
        *,
        hbm_budget: int | None = None,
        cost_weight: float = DEFAULT_COST_WEIGHT,
        demand_weight: float = 0.0,
        rate_tau_s: float = DEFAULT_RATE_TAU_S,
        rate_clock: Callable[[], float] = time.monotonic,
        eviction_listener: (
            Callable[[str, str, float, int], None] | None
        ) = None,
        metrics: MetricsRegistry | None = None,
        resilience=None,
        fault_plan=None,
        integrity_gate: bool = False,
        **engine_defaults,
    ):
        if mesh is None:
            from ..parallel.mesh import make_mesh
            import jax

            mesh = make_mesh(len(jax.devices()))
        self.mesh = mesh
        if cost_weight < 0:
            raise ConfigError(f"cost_weight must be >= 0, got {cost_weight}")
        self.cost_weight = float(cost_weight)
        if demand_weight < 0:
            raise ConfigError(
                f"demand_weight must be >= 0, got {demand_weight}"
            )
        self.demand_weight = float(demand_weight)
        self.rate_tau_s = float(rate_tau_s)
        self._rate_clock = rate_clock
        self.eviction_listener = eviction_listener
        bad = _RESERVED_ENGINE_KWARGS.intersection(engine_defaults)
        if bad:
            raise ConfigError(
                f"engine defaults {sorted(bad)} are registry-owned "
                "(the registry wires residency, accounting and identity "
                "itself)"
            )
        self._engine_defaults = dict(engine_defaults)
        self._resilience = resilience
        self._fault_plan = fault_plan
        self._integrity_gate = bool(integrity_gate)
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.accountant = HbmAccountant(hbm_budget)
        # Reentrant: victim release under the lock fires the engine's
        # residency listener, which re-enters for the ledger update.
        self._lock = threading.RLock()
        self._tenants: dict[str, _Tenant] = {}
        self._exec_caches: dict[tuple, ExecutableCache] = {}
        self._serial = itertools.count(1)
        self._closed = False
        self._timeline = get_hub()

        self._g_budget = self.metrics.gauge(
            "registry_hbm_budget_bytes",
            "resident-payload HBM budget (0 = unlimited)",
        )
        self._g_budget.set(hbm_budget or 0)
        self._g_charged = self.metrics.gauge(
            "registry_hbm_charged_bytes",
            "resident bytes currently charged across all tenants",
        )
        self._g_tenants = self.metrics.gauge(
            "registry_tenants", "registered tenants"
        )
        self._g_resident_tenants = self.metrics.gauge(
            "registry_tenants_resident",
            "tenants whose payload A is device-resident",
        )
        self._c_requests = self.metrics.counter(
            "registry_requests_total", "registry submit() calls"
        )
        self._c_hits = self.metrics.counter(
            "registry_hits_total",
            "submits that found the tenant's A already resident",
        )
        self._c_swap_ins = self.metrics.counter(
            "registry_swap_ins_total",
            "payload placements (admissions and re-admissions)",
        )
        self._c_evictions = self.metrics.counter(
            "registry_evictions_total",
            "tenants evicted to make HBM headroom",
        )
        self._c_quota_rejections = self.metrics.counter(
            "registry_quota_rejections_total",
            "submits refused by a tenant's max_in_flight quota",
        )
        self._c_overshoots = self.metrics.counter(
            "registry_budget_overshoots_total",
            "charges that breached the budget (every resident tenant "
            "pinned or mid-submit — soft-budget admissions)",
        )
        self._c_pins = self.metrics.counter(
            "registry_pins_total", "pin() calls"
        )
        self._c_native_fallbacks = self.metrics.counter(
            "registry_native_fallback_charges_total",
            "degradation-ladder native safe-tier placements charged to "
            "their tenant (the footprint a degraded dispatch adds)",
        )
        self._c_prefetches = self.metrics.counter(
            "registry_prefetches_total",
            "demand-driven prefetch() admissions (swap-ins enqueued to "
            "overlap under another tenant's dispatch — the global "
            "scheduler's interleaving)",
        )
        # Reshard counters are created on the FIRST migration (the
        # pay-for-what-you-use doctrine: a fleet that never reshards
        # carries no reshard vocabulary in its snapshot).
        self._c_reshards = None
        self._c_reshard_bytes = None

    # ---- registration ----

    # cardinality-ok: bounded per-tenant series — stale-ok: anticipatory; the exemption must survive a refactor that moves registration into a per-tenant loop
    # (register() validates ids, unregister removes demand, and label()
    # escapes the values — the one sanctioned dynamic-name site.)

    def _tenant_gauge(self, tenant_id: str, what: str, help_: str):
        return self.metrics.gauge(
            label(f"tenant_{what}", tenant=tenant_id), help_
        )

    def _tenant_counter(self, tenant_id: str, what: str, help_: str):
        return self.metrics.counter(
            label(f"tenant_{what}", tenant=tenant_id), help_
        )

    def _strategy_gauge(self, tenant_id: str, strategy: str):
        return self.metrics.gauge(
            label("tenant_strategy", tenant=tenant_id, strategy=strategy),
            "tenant's current partitioning strategy (info metric; the "
            "active strategy label reads 1)",
        )

    def register(
        self,
        tenant_id: str,
        a,
        *,
        quota: TenantQuota | None = None,
        pinned: bool = False,
        **engine_overrides,
    ) -> TenantHandle:
        """Register one tenant's ``A``. Construction is host-side only
        (quantization included) — no HBM is spent until the tenant's
        first submit (or :meth:`pin`) admits it, so registering a
        thousand tenants costs host memory, not device memory. Returns
        the tenant's :class:`TenantHandle`.

        ``quota.max_resident_bytes`` is checked here against the
        engine's actual payload footprint; a payload over quota is
        refused before it can ever thrash the budget."""
        _validate_tenant_id(tenant_id)
        bad = _RESERVED_ENGINE_KWARGS.intersection(engine_overrides)
        if bad:
            raise ConfigError(
                f"engine overrides {sorted(bad)} are registry-owned"
            )
        with self._lock:
            if self._closed:
                raise ConfigError("registry is closed")
            if tenant_id in self._tenants:
                raise ConfigError(
                    f"tenant {tenant_id!r} is already registered "
                    "(unregister it first to replace its A)"
                )
        kwargs = dict(self._engine_defaults)
        kwargs.update(engine_overrides)
        engine = MatvecEngine(
            a, self.mesh,
            metrics=self.metrics,
            retain_host=True,
            defer_placement=True,
            label_prefix=f"{tenant_id}/",
            resilience=self._resilience,
            fault_plan=self._fault_plan,
            integrity_gate=self._integrity_gate,
            residency_listener=(
                lambda delta, reason, _tid=tenant_id:
                self._on_residency(_tid, delta, reason)
            ),
            **kwargs,
        )
        if (
            quota is not None
            and quota.max_resident_bytes is not None
            and engine.resident_bytes > quota.max_resident_bytes
        ):
            raise TenantQuotaError(
                f"tenant {tenant_id!r} payload is {engine.resident_bytes} "
                f"bytes, over its max_resident_bytes="
                f"{quota.max_resident_bytes} quota"
            )
        entry = _Tenant(tenant_id, engine, quota)
        # Per-tenant arrival-rate EWMA: the predicted-demand signal
        # (demand-aware eviction) and a snapshot gauge.
        entry.rate = self.metrics.rate_estimator(
            label("tenant_rate_req_per_s", tenant=tenant_id),
            "EWMA arrival rate of this tenant's offered requests "
            "(admission-rejected demand included)",
            tau_s=self.rate_tau_s, clock=self._rate_clock,
        )
        entry.g_resident_bytes = self._tenant_gauge(
            tenant_id, "resident_bytes",
            "device-resident bytes charged to this tenant",
        )
        entry.g_pinned = self._tenant_gauge(
            tenant_id, "pinned", "1 while warm-pinned (eviction-exempt)"
        )
        entry.c_requests = self._tenant_counter(
            tenant_id, "requests_total", "registry submits for this tenant"
        )
        entry.c_hits = self._tenant_counter(
            tenant_id, "hits_total", "submits that found A resident"
        )
        entry.c_evictions = self._tenant_counter(
            tenant_id, "evictions_total", "times this tenant was evicted"
        )
        entry.c_evictions_caused = self._tenant_counter(
            tenant_id, "evictions_caused_total",
            "neighbor evictions this tenant's admissions forced",
        )
        entry.c_quota_rejections = self._tenant_counter(
            tenant_id, "quota_rejections_total",
            "submits refused by this tenant's quota",
        )
        # Info gauge, Prometheus-style: the label set carries the fact
        # (the obs `tenants` panel's strategy column); a reshard flips
        # the old label to 0 and the new one to 1.
        entry.g_strategy = self._strategy_gauge(
            tenant_id, engine.strategy.name
        )
        entry.g_strategy.set(1)
        with self._lock:
            if self._closed:
                raise ConfigError("registry is closed")
            if tenant_id in self._tenants:  # lost a racing register()
                raise ConfigError(
                    f"tenant {tenant_id!r} is already registered"
                )
            # Shared AOT executables: first engine of a signature donates
            # its (empty) cache; later ones adopt it. Zero compiles have
            # happened yet, so adoption is a pure pointer swap.
            sig = engine.exec_signature()
            cache = self._exec_caches.get(sig)
            if cache is None:
                self._exec_caches[sig] = engine._cache
            else:
                engine._cache = cache
            self._tenants[tenant_id] = entry
            self._g_tenants.set(len(self._tenants))
        if pinned:
            self.pin(tenant_id)
        return TenantHandle(self, tenant_id)

    def unregister(self, tenant_id: str) -> None:
        """Remove a tenant: release its residency (reference drop —
        in-flight work completes unaffected), clear its ledger, close
        its engine."""
        with self._lock:
            entry = self._entry(tenant_id)
            entry.engine.release_residency()  # callback-ok: listener clears the ledger — reentrant by design (release fires _on_residency, which re-takes this RLock; module docstring)
            del self._tenants[tenant_id]
            self._g_tenants.set(len(self._tenants))
            self._g_resident_tenants.set(self._resident_count_locked())
        entry.engine.close()

    # ---- accounting (the engine residency listener lands here) ----

    def _on_residency(self, tenant_id: str, delta: int, reason: str) -> None:
        """Ledger update for one ACTUAL residency change — placement,
        release, or the degradation ladder's native safe tier. Runs
        under the registry lock (reentrantly when a victim releases
        inside an admission). The event's sign drives the COUNTERS; the
        BYTE ledger reconciles to the engine's current footprint instead
        of applying the delta, because listeners fire outside the
        engine's residency lock and can arrive out of order (a dispatch-
        path self-heal's notification racing the eviction that undid
        it) — reconciliation converges to the truth either way."""
        with self._lock:
            entry = self._tenants.get(tenant_id)
            if entry is None:
                return  # raced an unregister; nothing left to charge
            if delta > 0:
                if reason == "resident":
                    entry.swap_ins += 1
                    self._c_swap_ins.inc()
                elif reason == "native_fallback":
                    self._c_native_fallbacks.inc()
            actual = entry.engine.device_resident_bytes
            if self.accountant.reconcile(tenant_id, actual):
                self._c_overshoots.inc()
            entry.charged_bytes = actual
            entry.g_resident_bytes.set(actual)
            self._g_charged.set(self.accountant.total)
            self._g_resident_tenants.set(self._resident_count_locked())

    def _resident_count_locked(self) -> int:
        return sum(1 for e in self._tenants.values() if e.engine.resident)

    # ---- eviction (bookkeeping under the lock; transfers never) ----

    def _mean_payload_locked(self) -> float:
        if not self._tenants:
            return 1.0
        total = sum(e.engine.resident_bytes for e in self._tenants.values())
        return max(1.0, total / len(self._tenants))

    def _victim_score_locked(self, e: _Tenant, mean: float,
                             now: float) -> float:
        """One tenant's eviction score (lowest evicts): recency, plus
        the restore-cost ratio (PR 9), plus — when ``demand_weight`` is
        on — the predicted-demand term: the tenant's EWMA arrival rate
        weighed by that same restore ratio. A tenant being asked for
        right now and expensive to bring back outranks a merely
        recently-used one; a cold estimator (rate 0) reduces the score
        to exactly the PR 9 form."""
        restore_ratio = e.charged_bytes / mean
        score = e.last_used + self.cost_weight * restore_ratio
        if self.demand_weight:
            score += (
                self.demand_weight
                * e.rate.rate_per_s(now=now)
                * restore_ratio
            )
        return score

    def _pick_victim_locked(self, exclude: _Tenant) -> _Tenant | None:
        """Demand-aware cost-aware LRU: evict the eligible resident
        tenant with the lowest :meth:`_victim_score_locked`. Pinned
        tenants and tenants mid-submit (``active > 0`` — the window
        between admission and the dispatch capturing its device
        reference) are never eligible; in-flight FUTURES need no
        protection (refcounted residency keeps their buffers alive)."""
        mean = self._mean_payload_locked()
        now = self._rate_clock() if self.demand_weight else 0.0
        best: _Tenant | None = None
        best_score = None
        for e in self._tenants.values():
            if (
                e is exclude or e.pinned or e.active > 0
                or not e.engine.resident
            ):
                continue
            score = self._victim_score_locked(e, mean, now)
            if best_score is None or score < best_score:
                best, best_score = e, score
        return best

    def _evict_for_locked(self, entry: _Tenant) -> None:
        """Make budget headroom for ``entry``'s payload: evict lowest-
        score victims until it fits or no victim remains (then the
        admission proceeds as a counted overshoot — see the module
        docstring's soft-budget doctrine). Release is a reference drop,
        legal under the lock; the freed bytes enter the ledger through
        the victim's residency listener before the next victim is
        scored. The optional ``eviction_listener`` fires per victim
        under the lock (bookkeeping only — the global scheduler's
        decision trace)."""
        needed = entry.engine.resident_bytes
        mean = self._mean_payload_locked()
        now = self._rate_clock() if self.demand_weight else 0.0
        while not self.accountant.headroom(needed):
            victim = self._pick_victim_locked(entry)
            if victim is None:
                break
            score = self._victim_score_locked(victim, mean, now)
            victim.engine.release_residency()  # callback-ok: the victim's residency listener re-enters this RLock to update the ledger BEFORE the next victim is scored — the reentrancy the lock is an RLock for (module docstring)
            victim.evictions += 1
            victim.c_evictions.inc()
            self._c_evictions.inc()
            entry.evictions_caused += 1
            entry.c_evictions_caused.inc()
            # Timeline: a swap-out is a background consequence of the
            # admission that needed headroom — cause_id, never
            # request_id. Bookkeeping-only (deque appends), legal under
            # the lock like the listener below.
            self._timeline.emit(
                "swap_out", cause_id=bound_request_id(),
                tenant=victim.tenant_id, caused_by=entry.tenant_id,
                score=score,
            )
            if self.eviction_listener is not None:
                self.eviction_listener(  # callback-ok: bookkeeping-only contract, documented at the parameter — the global scheduler's _on_eviction appends to its ring and queues a sink record, never takes the registry lock
                    victim.tenant_id, entry.tenant_id, score,
                    victim.engine.resident_bytes,
                )

    # ---- the serving face ----

    def _entry(self, tenant_id: str) -> _Tenant:
        entry = self._tenants.get(tenant_id)  # unguarded-ok: GIL-atomic dict.get; serving callers hold the lock, and the lock-free faces (TenantHandle.engine) tolerate racing an unregister — they get the entry or a ConfigError, never a torn dict
        if entry is None:
            raise ConfigError(f"unknown tenant {tenant_id!r}")
        return entry

    def submit(self, tenant_id: str, x, **kwargs) -> MatvecFuture:
        """Dispatch one request against ``tenant_id``'s resident ``A``
        (``MatvecEngine.submit`` semantics — ``deadline_ms``,
        ``integrity`` pass through). Admission happens here: quota gate
        first (a refused request fails its future with
        :class:`TenantQuotaError` BEFORE any dispatch or eviction),
        then residency — a hit dispatches immediately; a miss evicts by
        score under the lock and swaps the payload in outside it
        (enqueue-only, overlapped under other tenants' in-flight
        dispatches)."""
        with self._lock:
            if self._closed:
                raise ConfigError("registry is closed")
            entry = self._entry(tenant_id)
            entry.requests += 1
            entry.c_requests.inc()
            self._c_requests.inc()
            entry.rate.observe()  # the demand signal eviction weighs
            quota = entry.quota
            if quota is not None and quota.max_in_flight is not None:
                entry.sweep()
                # entry.active counts submits past this gate whose
                # futures are not yet appended (appending happens under
                # the same lock hold that decrements active, so the two
                # never both miss a concurrent submit) — without it, N
                # threads racing this check could overrun the quota N-1
                # deep. A concurrent pin() holds active too: transient,
                # conservative.
                if (
                    len(entry.outstanding) + entry.active
                    >= quota.max_in_flight
                ):
                    entry.quota_rejections += 1
                    entry.c_quota_rejections.inc()
                    self._c_quota_rejections.inc()
                    return MatvecFuture.failed(TenantQuotaError(
                        f"tenant {tenant_id!r} has "
                        f"{len(entry.outstanding)} requests in flight, "
                        f"at its max_in_flight={quota.max_in_flight} "
                        "quota; re-submit after materializing results"
                    ))
            entry.last_used = next(self._serial)
            hit = entry.engine.resident
            if hit:
                entry.hits += 1
                entry.c_hits.inc()
                self._c_hits.inc()
            else:
                self._evict_for_locked(entry)
            entry.active += 1
        fut = None
        try:
            if not hit:
                # The async swap-in: device_put is enqueue-only, so this
                # overlaps under whatever other tenants have in flight.
                # (emit auto-adopts the bound request id, so the miss
                # shows up inside the requesting timeline.)
                self._timeline.emit(
                    "swap_in", tenant=tenant_id,
                    restore_bytes=entry.engine.resident_bytes,
                )
                entry.engine.ensure_resident()
            fut = entry.engine.submit(x, **kwargs)
        finally:
            with self._lock:
                # One lock hold for both: the quota gate reads
                # outstanding + active, so the future must be appended
                # before active drops or a racing submit sees neither.
                entry.active -= 1
                if fut is not None and (
                    entry.quota is not None
                    and entry.quota.max_in_flight is not None
                ):
                    entry.outstanding.append(fut)
        return fut

    def __call__(self, tenant_id: str, x):
        """Synchronous convenience: ``submit(tenant_id, x).result()``."""
        return self.submit(tenant_id, x).result()

    # ---- pinning ----

    def pin(self, tenant_id: str) -> None:
        """Warm-pin: admit the tenant now (evicting by score if needed)
        and exempt it from eviction until :meth:`unpin`."""
        with self._lock:
            entry = self._entry(tenant_id)
            entry.pinned = True
            entry.g_pinned.set(1)
            entry.last_used = next(self._serial)
            self._c_pins.inc()
            if not entry.engine.resident:
                self._evict_for_locked(entry)
            entry.active += 1
        try:
            entry.engine.ensure_resident()
        finally:
            with self._lock:
                entry.active -= 1

    def unpin(self, tenant_id: str) -> None:
        with self._lock:
            entry = self._entry(tenant_id)
            entry.pinned = False
            entry.g_pinned.set(0)

    # ---- the global scheduler's hooks (docs/SCHEDULING.md) ----

    def observe_demand(self, tenant_id: str, n: int = 1) -> None:
        """Tick a tenant's demand estimator WITHOUT a submit — the
        global scheduler calls this for admission-rejected requests, so
        a tenant being refused under load still reads as hot demand to
        the eviction score (its residency is exactly what would let its
        next request be admitted)."""
        with self._lock:
            self._entry(tenant_id).rate.observe(n)

    def demand_rate(self, tenant_id: str) -> float:
        """The tenant's EWMA offered-request rate (req/s, idle-decayed)."""
        with self._lock:
            entry = self._entry(tenant_id)
        return entry.rate.rate_per_s()

    def coalesce_group(self, tenant_id: str) -> tuple:
        """The tenant's cross-tenant coalescing identity: its engine's
        exec signature plus the sha256 of its normalized host payload.
        Tenants in one group run the SAME compiled programs over the
        SAME ``A`` bytes, so their requests may share one column-stacked
        flush with bitwise-identical per-column results (the PR 6
        exactness doctrine — which column of the batch a request rides
        never changes its output). The hash is computed LAZILY on first
        use (this method is the only consumer) and cached — a registry
        that never coalesces never pays an O(payload) hashing pass at
        registration; the host payload is immutable for the tenant's
        lifetime, so a racing duplicate computation is idempotent."""
        with self._lock:
            entry = self._entry(tenant_id)
            sha = entry.payload_sha
        if not sha:
            sha = hashlib.sha256(entry.engine._a_host.tobytes()).hexdigest()
            with self._lock:
                entry.payload_sha = sha
        return (entry.engine.exec_signature(), sha)

    def prefetch(self, tenant_id: str, *, protect: str | None = None)\
            -> bool:
        """Demand-driven swap-in: admit the tenant's payload NOW (evict
        by score if needed) without pinning it — the global scheduler
        enqueues this ahead of a predicted-long dispatch so the
        ``device_put`` restore overlaps under that dispatch's compute
        instead of stalling the tenant's next request. Returns True when
        this call placed the payload (False: already resident). The
        prefetch counts as an anticipated USE (recency bumped) so the
        next admission does not immediately re-evict it, and ``protect``
        shields one tenant — the one whose dispatch the overlap hides —
        from being chosen as the victim. The transfer itself happens
        outside the lock, enqueue-only — the same discipline as
        :meth:`pin` and the submit path."""
        with self._lock:
            entry = self._entry(tenant_id)
            if entry.engine.resident:
                return False
            guard = (
                self._tenants.get(protect)
                if protect is not None else None
            )
            if guard is not None:
                guard.active += 1  # victim-ineligible for this pick only
            try:
                self._evict_for_locked(entry)
            finally:
                if guard is not None:
                    guard.active -= 1
            entry.last_used = next(self._serial)
            entry.active += 1
        try:
            placed = entry.engine.ensure_resident()
        finally:
            with self._lock:
                entry.active -= 1
        if placed:
            self._c_prefetches.inc()
            self._timeline.emit(
                "prefetch", cause_id=bound_request_id(),
                tenant=tenant_id, protect=protect,
            )
        return placed

    def reshard(
        self, tenant_id: str, strategy, *, warm_widths=None
    ) -> dict | None:
        """Migrate one tenant's resident ``A`` to another strategy
        ON-DEVICE (``MatvecEngine.reshard``; docs/RESHARDING.md) and
        re-home its executable cache under the new exec signature — the
        same first-donates/later-adopts idiom as :meth:`register`, so
        same-shaped tenants already serving in the destination layout
        hand this one their compiled programs (often making the
        migration compile-free). The migration itself runs OUTSIDE the
        registry lock (collectives are enqueue-only, and in-flight
        dispatches keep serving the old layout); eviction stays legal
        throughout — an eviction landing mid-migration aborts the array
        swap cleanly at the engine commit, so the HBM ledger never
        carries a double footprint (the residency listener reconciles as
        usual). Returns the engine's migration summary, or None when the
        tenant is already mid-reshard or already in the destination
        layout. ``warm_widths`` compiles the destination executable set
        AFTER the cache re-home — the one-time new-layout compile."""
        with self._lock:
            if self._closed:
                raise ConfigError("registry is closed")
            entry = self._entry(tenant_id)
            engine = entry.engine
            dst_name = (
                strategy if isinstance(strategy, str) else strategy.name
            )
            if entry.resharding or engine.strategy.name == dst_name:
                return None
            entry.resharding = True
        try:
            # The engine migration (collective build + enqueue +
            # commit) never runs under the registry lock.
            result = engine.reshard(strategy)
        finally:
            with self._lock:
                entry.resharding = False
        with self._lock:
            # Re-home the exec cache under the NEW signature before any
            # destination-layout compile, so warmup lands in the shared
            # cache (or adopts a sibling's compiled programs wholesale).
            sig = engine.exec_signature()
            cache = self._exec_caches.get(sig)
            if cache is None:
                self._exec_caches[sig] = engine._cache
            else:
                engine._cache = cache
            entry.reshards += 1
            if entry.g_strategy is not None:
                entry.g_strategy.set(0)
            entry.g_strategy = self._strategy_gauge(
                tenant_id, engine.strategy.name
            )
            entry.g_strategy.set(1)
            if self._c_reshards is None:
                self._c_reshards = self.metrics.counter(
                    "registry_reshards_total",
                    "completed online strategy migrations (config-only "
                    "and aborted-array swaps included)",
                )
                self._c_reshard_bytes = self.metrics.counter(
                    "reshard_bytes_total",
                    "payload bytes redistributed by reshard collective "
                    "programs (host-fallback and aborted swaps move 0)",
                )
            self._c_reshards.inc()
            self._c_reshard_bytes.inc(int(result.get("bytes_moved", 0)))
        self._timeline.emit(
            "reshard_apply", cause_id=bound_request_id(),
            tenant=tenant_id, dst=engine.strategy.name,
            bytes_moved=int(result.get("bytes_moved", 0)),
        )
        if warm_widths is not None:
            engine.warmup(widths=warm_widths)
        return result

    # ---- warmup, stats, health ----

    def warmup(self, widths: Sequence[int] | None = None) -> int:
        """Pre-compile the executable set ONCE per distinct exec
        signature (shared caches make that the whole fleet's warmup).
        Needs no residency — AOT compilation runs on shape structs.
        Returns fresh compiles."""
        with self._lock:
            engines: dict[tuple, MatvecEngine] = {}
            for e in self._tenants.values():
                engines.setdefault(e.engine.exec_signature(), e.engine)
            todo = list(engines.values())
        return sum(engine.warmup(widths) for engine in todo)

    def tenant_ids(self) -> list[str]:
        with self._lock:
            return list(self._tenants)

    def __len__(self) -> int:
        with self._lock:
            return len(self._tenants)

    def tenant_stats(self, tenant_id: str) -> dict:
        with self._lock:
            e = self._entry(tenant_id)
            return {
                "tenant": tenant_id,
                "strategy": e.engine.strategy.name,
                "resident": e.engine.resident,
                "resharding": e.resharding,
                "resident_bytes": e.charged_bytes,
                "payload_bytes": e.engine.resident_bytes,
                "pinned": e.pinned,
                "requests": e.requests,
                "hits": e.hits,
                "swap_ins": e.swap_ins,
                "reshards": e.reshards,
                "evictions": e.evictions,
                "evictions_caused": e.evictions_caused,
                "quota_rejections": e.quota_rejections,
            }

    def health(self) -> dict:
        """Fleet snapshot: the HBM ledger plus one entry per tenant —
        the registry-side counters next to the tenant engine's
        resilience summary (breakers not closed, degraded configs). The
        obs ``tenants`` panel renders the same numbers from the metrics
        snapshot."""
        with self._lock:
            entries = list(self._tenants.values())
            hbm = {
                "budget_bytes": self.accountant.budget,
                "charged_bytes": self.accountant.total,
                "overshoots": self.accountant.overshoots,
                "per_tenant": dict(self.accountant.charged),
            }
            stats = [self.tenant_stats(e.tenant_id) for e in entries]
        tenants = {}
        for e, stat in zip(entries, stats):
            eh = e.engine.health()
            stat["breakers_open"] = sum(
                1 for snap in eh["breakers"].values()
                if snap["state"] != "closed"
            )
            stat["degraded"] = eh["degraded"]
            stat["native_fallback_resident"] = (
                eh["storage"]["native_fallback_resident"]
            )
            tenants[e.tenant_id] = stat
        return {"hbm": hbm, "tenants": tenants}

    # ---- lifecycle ----

    def close(self) -> None:
        """Retire the fleet: release every residency (reference drops;
        in-flight device work completes on its own), close every tenant
        engine (idempotent and exception-safe even with failed in-flight
        futures — ``MatvecEngine.close`` doctrine). A second close is a
        no-op; submits after close raise."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries = list(self._tenants.values())
            for e in entries:
                e.engine.release_residency()  # callback-ok: same reentrant ledger-clearing release as unregister (RLock; module docstring) — engines are closed after the lock is dropped
            self._tenants.clear()
            self._g_tenants.set(0)
            self._g_resident_tenants.set(0)
        for e in entries:
            e.engine.close()

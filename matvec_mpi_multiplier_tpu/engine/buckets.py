"""Bucket ladder: shape-canonicalization for the request stream.

A serving workload presents right-hand-side blocks of arbitrary width; a
compiled XLA executable serves exactly one shape. Left alone, a mixed-width
stream would compile one program per distinct width — unbounded compile
churn in the hot path (the GSPMD lesson, PAPERS.md: compile the sharded
program once, reuse it across the request stream). The ladder quantizes
widths to powers of two, so at most ``log2(max_bucket) + 1`` executables
ever exist per (strategy, kernel, combine, dtype) and every request after
warmup hits a cached one.

Padding is host-side (the request is a host array on its way to the device
anyway) and the pad columns are zeros; the matching unpad is a slice of the
result columns at materialization time (``MatvecFuture.result``). Zero
columns cannot perturb the real ones — each output element is a dot product
over its own column only — so padded results are bitwise-identical to what
the same executable computes with any other pad content.
"""

from __future__ import annotations

import numpy as np

from ..utils.errors import ConfigError

# Widest batch one executable serves (and the widest bucket the ladder
# offers). Wider requests are split into max-bucket chunks — bounded VMEM
# footprint per dispatch, and the chunks all hit the same hot executable.
DEFAULT_MAX_BUCKET = 128


def bucket_ladder(max_bucket: int = DEFAULT_MAX_BUCKET) -> tuple[int, ...]:
    """The power-of-two bucket widths up to ``max_bucket`` inclusive
    (``max_bucket`` itself is appended when it is not a power of two)."""
    if max_bucket < 1:
        raise ConfigError(f"max_bucket must be >= 1, got {max_bucket}")
    ladder = []
    b = 1
    while b <= max_bucket:
        ladder.append(b)
        b *= 2
    if ladder[-1] != max_bucket:
        ladder.append(max_bucket)
    return tuple(ladder)


def bucket_for(width: int, max_bucket: int = DEFAULT_MAX_BUCKET) -> int:
    """The bucket a request of ``width`` columns is padded to: the smallest
    ladder entry >= width. Callers split requests wider than ``max_bucket``
    into chunks first (``split_widths``)."""
    if width < 1:
        raise ConfigError(f"request width must be >= 1, got {width}")
    if width > max_bucket:
        raise ConfigError(
            f"request width {width} exceeds max_bucket {max_bucket}; "
            "split it first (split_widths)"
        )
    for b in bucket_ladder(max_bucket):
        if b >= width:
            return b
    raise AssertionError("unreachable: ladder ends at max_bucket")


def split_widths(width: int, max_bucket: int = DEFAULT_MAX_BUCKET) -> list[int]:
    """Chunk widths for a request of ``width`` columns: full ``max_bucket``
    chunks plus the remainder (which then pads to its own bucket)."""
    if width < 1:
        raise ConfigError(f"request width must be >= 1, got {width}")
    chunks = [max_bucket] * (width // max_bucket)
    if width % max_bucket:
        chunks.append(width % max_bucket)
    return chunks


def pad_columns(block: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad a host (k, b) block to (k, bucket) columns (no-op copy-free
    when already at bucket width)."""
    k, b = block.shape
    if b == bucket:
        return block
    if b > bucket:
        raise ConfigError(f"block width {b} exceeds bucket {bucket}")
    padded = np.zeros((k, bucket), dtype=block.dtype)
    padded[:, :b] = block
    return padded

"""Serving engine: batched multi-RHS dispatch against a resident sharded A.

The serving-shape subsystem (ROADMAP north star): where ``bench/`` measures
one matvec at a time, this package serves a *stream* of right-hand sides —
shape-bucketed, AOT-compiled, buffer-donating, GEMV→GEMM-promoting,
(``scheduler.py``) continuously batched — an arrival-window scheduler
coalesces concurrent requests into one column-stacked multi-RHS dispatch —
and fault-tolerant (``resilience/``): retry + per-ExecKey circuit
breakers behind a degradation ladder, coalesced-batch bisection, and an
optional result-integrity gate — and multi-tenant (``registry.py``): a
matrix registry holds many tenants' ``A`` matrices against one HBM
budget with cost-aware LRU eviction, async swap, warm-pinning and
per-tenant quotas. See ``core.py`` for the engine architecture,
``buckets.py`` for the shape ladder, ``executables.py`` for the AOT
cache, ``scheduler.py`` for coalescing, ``registry.py`` for tenancy,
``docs/SERVING.md`` / ``docs/RESILIENCE.md`` / ``docs/MULTITENANT.md``
for usage. Benchmarked by ``bench/serve.py`` (``--op serve``; chaos mode
via ``--fault-spec``; multi-tenant trace mode via ``--tenants``).
"""

from .buckets import (
    DEFAULT_MAX_BUCKET,
    bucket_for,
    bucket_ladder,
    pad_columns,
    split_widths,
)
from .core import DEFAULT_PROMOTE_B, EngineStats, MatvecEngine, MatvecFuture
from .executables import ExecKey, ExecStats, ExecutableCache
from .global_scheduler import GlobalScheduler
from .registry import (
    HbmAccountant,
    MatrixRegistry,
    TenantHandle,
    TenantQuota,
)
from .scheduler import (
    DEFAULT_MAX_WINDOW_MS,
    QOS_TIERS,
    ArrivalWindowScheduler,
    CoalescedFuture,
    SchedulerStats,
)

__all__ = [
    "MatvecEngine",
    "MatvecFuture",
    "EngineStats",
    "GlobalScheduler",
    "MatrixRegistry",
    "TenantHandle",
    "TenantQuota",
    "HbmAccountant",
    "ArrivalWindowScheduler",
    "CoalescedFuture",
    "SchedulerStats",
    "QOS_TIERS",
    "DEFAULT_MAX_WINDOW_MS",
    "ExecutableCache",
    "ExecKey",
    "ExecStats",
    "DEFAULT_MAX_BUCKET",
    "DEFAULT_PROMOTE_B",
    "bucket_ladder",
    "bucket_for",
    "split_widths",
    "pad_columns",
]

"""Process-local metrics registry: counters, gauges, latency histograms.

Design constraints, in order:

* **atomic under threads** — the engine's submit path and a caller's
  materialize/stats threads update and read the same counters concurrently
  (the race ``EngineStats`` used to carry as bare ``int += 1`` attributes);
  every metric guards its state with one small mutex, so a snapshot never
  reads a half-applied update;
* **no I/O** — this module only mutates memory. Exporting a snapshot to
  disk is driver code (``bench/serve.py``, the obs CLI) or the sink thread
  (``sink.py``); the I/O lint (``tests/test_lint.py``) enforces it;
* **exact percentiles over a bounded window** — the histogram keeps fixed
  cumulative buckets (the Prometheus export shape) AND a bounded window of
  raw observations; ``percentile`` computes over the window with
  ``np.percentile``, so for runs shorter than the window (every committed
  demo) the summary is bit-identical to what ``np.percentile`` over the
  full sample would report — the property the serve bench's p50/p99
  unification test pins.

Plus two derived metrics: :class:`RateEstimator`, the windowed EWMA
arrival-rate (req/s) the batching scheduler (``engine/scheduler.py``)
sizes its coalescing window from, and :class:`EwmaGauge`, the
time-decayed windowed average of an observation stream (the engine's
escalation rate ε, the cost model's divergence) — a lifetime ratio
never forgets, so a config that misbehaved an hour ago would poison
re-tuning forever; the EWMA tracks *recent* traffic with time constant
``tau_s``. Both export as gauges in snapshots — no new wire type — and
take an injectable clock so their dynamics are unit-testable without
sleeping.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Iterable

import numpy as np

# Default bucket upper bounds, in milliseconds: tuned to dispatch/serve
# latencies (tens of microseconds through seconds). The terminal +Inf
# bucket is implicit — ``observe`` always lands somewhere.
DEFAULT_BUCKETS_MS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
)

# Raw observations retained for exact percentiles. Beyond this, percentile
# reports over the most recent WINDOW observations (documented, bounded
# memory); bucket counts remain exact forever.
DEFAULT_WINDOW = 8192


class Counter:
    """Monotone counter. ``inc`` is atomic (one mutex), ``value`` reads
    under the same mutex — a snapshot never sees a torn update."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket latency histogram with exact windowed percentiles.

    ``observe(v)`` updates the cumulative bucket counts (Prometheus
    semantics: bucket ``le`` counts observations ``<= le``), the running
    sum/count, and a bounded deque of raw observations. ``percentile(q)``
    is ``np.percentile`` over that window — exact (not bucket-interpolated)
    whenever fewer than ``window`` values were observed, which covers every
    in-process serve run the bench reports on.
    """

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: Iterable[float] = DEFAULT_BUCKETS_MS,
        window: int = DEFAULT_WINDOW,
    ):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {name!r} needs at least one bucket")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._window: deque[float] = deque(maxlen=window)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            i = np.searchsorted(self.buckets, v, side="left")
            self._counts[i] += 1
            self._window.append(v)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """``np.percentile`` over the retained window (NaN when empty) —
        the single percentile implementation the serve bench and the
        engine's latency summaries share."""
        with self._lock:
            if not self._window:
                return float("nan")
            return float(np.percentile(np.asarray(self._window), q))

    def summary(self) -> dict:
        with self._lock:
            window = np.asarray(self._window) if self._window else None
            counts = list(self._counts)
            total, s = self._count, self._sum
        if window is None:
            p50 = p95 = p99 = float("nan")
        else:
            p50, p95, p99 = (
                float(np.percentile(window, q)) for q in (50, 95, 99)
            )
        cumulative = []
        running = 0
        for le, c in zip(self.buckets, counts):
            running += c
            cumulative.append([le, running])
        cumulative.append(["+Inf", running + counts[-1]])
        return {
            "count": total,
            "sum": s,
            "p50": p50,
            "p95": p95,
            "p99": p99,
            "buckets": cumulative,
        }


class RateEstimator:
    """Windowed EWMA arrival-rate estimator: events in, req/s out.

    ``observe()`` records one (or ``n`` simultaneous) arrivals;
    ``rate_per_s()`` reports an exponentially-weighted moving average of
    the instantaneous arrival rate with time constant ``tau_s`` — the
    effective averaging window. Two properties the consumer (the
    batching scheduler's adaptive coalescing window) depends on:

    * **burst-safe** — arrivals sharing one clock reading accumulate and
      enter the average as ``count / gap`` at the next distinct
      timestamp, so a thread stampede reads as a high rate, not a
      division by zero;
    * **idle decay** — ``rate_per_s`` discounts the stored average by
      the time since the last arrival (``exp(-idle/tau)``), so a stream
      that stops reads as a falling rate instead of freezing at its
      last burst (the scheduler must shrink its window when traffic
      drains, not keep serving yesterday's estimate).

    The clock is injectable (``time.monotonic`` by default) so the
    dynamics are testable without real sleeps. Exported by the registry
    snapshot as a plain gauge value — sampled at snapshot time.
    """

    def __init__(
        self,
        name: str,
        help: str = "",
        tau_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if tau_s <= 0:
            raise ValueError(f"rate estimator {name!r} needs tau_s > 0")
        self.name = name
        self.help = help
        self.tau_s = float(tau_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._rate = 0.0
        self._last: float | None = None
        self._burst = 0  # arrivals at the last timestamp, not yet averaged
        self._count = 0

    def observe(self, n: int = 1, now: float | None = None) -> None:
        if now is None:
            now = self._clock()
        with self._lock:
            self._count += n
            if self._last is None:
                self._last = now
                self._burst = n
                return
            dt = now - self._last
            if dt <= 0:  # same (or regressed) clock reading: accumulate
                self._burst += n
                return
            inst = self._burst / dt
            w = math.exp(-dt / self.tau_s)
            self._rate = w * self._rate + (1.0 - w) * inst
            self._last = now
            self._burst = n

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def rate_per_s(self, now: float | None = None) -> float:
        """The EWMA arrival rate, discounted for idle time since the last
        arrival (0.0 before any event)."""
        if now is None:
            now = self._clock()
        with self._lock:
            if self._last is None:
                return 0.0
            idle = max(0.0, now - self._last)
            return self._rate * math.exp(-idle / self.tau_s)


class EwmaGauge:
    """Time-decayed windowed average of an observation stream.

    ``observe(x)`` folds one observation into a pair of decayed
    accumulators (weighted sum and weight), each discounted by
    ``exp(-dt/tau_s)`` since the previous observation; ``value`` is
    their ratio — an exponentially-weighted average in which an
    observation ``age`` seconds old carries weight ``exp(-age/tau_s)``.
    Three properties the consumers (the ``engine_escalation_rate`` ε the
    cost model re-adopts at tuning time, the cost-model divergence
    gauge) depend on:

    * **recent, not lifetime** — after ~5·tau of contrary evidence the
      old regime is <1% of the estimate, where a lifetime ratio would
      still be dragging half its history;
    * **burst-safe** — observations sharing one clock reading all enter
      with full weight (the accumulators add; no division by dt);
    * **idle-stable** — silence decays numerator and denominator
      equally, so the value *holds* over a quiet period instead of
      drifting toward zero (no traffic is "no new evidence", not
      "the rate fell").

    Exported by the registry snapshot as a plain gauge value.
    """

    def __init__(
        self,
        name: str,
        help: str = "",
        tau_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if tau_s <= 0:
            raise ValueError(f"ewma gauge {name!r} needs tau_s > 0")
        self.name = name
        self.help = help
        self.tau_s = float(tau_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._num = 0.0
        self._den = 0.0
        self._last: float | None = None
        self._count = 0

    def observe(self, x: float, now: float | None = None) -> None:
        if now is None:
            now = self._clock()
        with self._lock:
            if self._last is not None:
                w = math.exp(-max(0.0, now - self._last) / self.tau_s)
                self._num *= w
                self._den *= w
            self._num += float(x)
            self._den += 1.0
            self._last = now
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def value(self) -> float:
        """The decayed average (0.0 before any observation)."""
        with self._lock:
            if self._den <= 0.0:
                return 0.0
            return self._num / self._den


def label(name: str, **labels: object) -> str:
    """Build a labeled metric name — ``name{k="v", ...}`` — with the
    label values escaped per the Prometheus text exposition rules
    (backslash, double-quote, and newline). The registry stores labeled
    metrics under their full labeled name (one string, no label
    indexing), so escaping must happen at construction; every f-string
    that used to build these names by hand goes through here.

    Label *sources* must still be bounded (tenant ids capped by the
    registry's capacity, declared SLO names): the staticcheck
    ``metric-label-cardinality`` rule flags per-request/loop
    construction from unbounded sources."""
    if not labels:
        return name
    # Keyword order is kept and the separator is a bare comma — the
    # exact grammar the hand-built f-strings used, so names (and the
    # committed metrics.json captures keyed on them) are unchanged.
    parts = ",".join(
        f'{k}="{escape_label_value(str(v))}"' for k, v in labels.items()
    )
    return f"{name}{{{parts}}}"


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: ``\\`` → ``\\\\``, ``"`` →
    ``\\"``, newline → ``\\n``."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class MetricsRegistry:
    """Named metrics, get-or-create. One registry per engine (isolated
    counters per serving instance) plus a process default
    (:func:`get_registry`) for subsystem-level events (the tuner's
    per-candidate measurements)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._rates: dict[str, RateEstimator] = {}
        self._ewmas: dict[str, EwmaGauge] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name, help)
            return c

    def gauge(self, name: str, help: str = "") -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, help)
            return g

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Iterable[float] = DEFAULT_BUCKETS_MS,
        window: int = DEFAULT_WINDOW,
    ) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(
                    name, help, buckets=buckets, window=window
                )
            return h

    def rate_estimator(
        self,
        name: str,
        help: str = "",
        tau_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> RateEstimator:
        with self._lock:
            r = self._rates.get(name)
            if r is None:
                r = self._rates[name] = RateEstimator(
                    name, help, tau_s=tau_s, clock=clock
                )
            return r

    def ewma_gauge(
        self,
        name: str,
        help: str = "",
        tau_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> EwmaGauge:
        with self._lock:
            e = self._ewmas.get(name)
            if e is None:
                e = self._ewmas[name] = EwmaGauge(
                    name, help, tau_s=tau_s, clock=clock
                )
            return e

    def snapshot(self) -> dict:
        """JSON-able view of every metric — the ``--metrics-out`` payload
        and the obs CLI's input. Values are read metric-by-metric under
        each metric's own lock (atomic per metric; the registry makes no
        cross-metric consistency claim). Rate estimators and EWMA gauges
        export as gauges, sampled at snapshot time."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
            rates = dict(self._rates)
            ewmas = dict(self._ewmas)
        gauge_values = {n: g.value for n, g in gauges.items()}
        gauge_values.update({n: r.rate_per_s() for n, r in rates.items()})
        gauge_values.update({n: e.value for n, e in ewmas.items()})
        return {
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": dict(sorted(gauge_values.items())),
            "histograms": {
                n: h.summary() for n, h in sorted(histograms.items())
            },
        }

    def to_prometheus(self) -> str:
        """Prometheus text exposition of the registry (counters, gauges,
        histograms with cumulative ``le`` buckets)."""
        return prometheus_text(self.snapshot())


def prometheus_text(snapshot: dict) -> str:
    """Prometheus text exposition of a :meth:`MetricsRegistry.snapshot`
    dict — the ONE serializer, shared by live registries and the obs CLI
    (which renders snapshots read back from ``--metrics-out`` files)."""
    lines: list[str] = []
    for name, value in snapshot.get("counters", {}).items():
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {value}")
    for name, value in snapshot.get("gauges", {}).items():
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_fmt(value)}")
    for name, summ in snapshot.get("histograms", {}).items():
        lines.append(f"# TYPE {name} histogram")
        for le, cum in summ.get("buckets", []):
            le_s = "+Inf" if le == "+Inf" else _fmt(le)
            lines.append(f'{name}_bucket{{le="{le_s}"}} {cum}')
        lines.append(f"{name}_sum {_fmt(summ.get('sum', 0))}")
        lines.append(f"{name}_count {summ.get('count', 0)}")
    return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    if isinstance(v, float) and (math.isinf(v) or math.isnan(v)):
        return str(v)
    return repr(float(v)) if isinstance(v, float) else str(v)


# ---- process default registry (subsystem-level events, e.g. the tuner) ----

_default: MetricsRegistry | None = None
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-local default registry. Engine instances carry their own
    (isolated per serving instance); subsystem-level emitters that have no
    instance to hang metrics on — the tuner's per-candidate measurement
    events — land here."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default


def reset_registry() -> None:
    """Drop the process default registry (tests; mirrors
    ``tuning.reset_cache``)."""
    global _default
    with _default_lock:
        _default = None

"""Named device-trace annotations: make Perfetto captures read by phase.

A device trace of an overlap schedule without names is a wall of fused
ops; GSPMD-style collective schedules (PAPERS.md) are only debuggable when
each pipeline stage carries its name into the capture. ``named_span``
wraps a trace-time region in BOTH

* ``jax.named_scope`` — pushes the name onto JAX's name stack, so it lands
  in the lowered program's op metadata (visible in the compiled HLO and in
  the device rows of a Perfetto capture); and
* ``jax.profiler.TraceAnnotation`` — a host TraceMe, so the same name
  shows on the host timeline while the region traces.

Both are *trace-time* constructs: they cost nothing per dispatch (the
traced program is compiled once and replayed), and toggling the enable
flag only affects programs traced afterwards — already-compiled
executables keep whatever names they were traced with.

Enablement: off by default (byte-identical lowered programs to the
un-annotated build); ``--annotate`` on the serve/sweep CLIs,
``MATVEC_ANNOTATE=1`` in the environment, or :func:`set_annotations` turn
it on. Tests scope it with the :func:`annotations` context manager.

Lives in ``obs`` (imports jax only) so ``parallel/ring.py`` and the
strategy bodies can use it without touching ``bench`` — which imports
``models`` and would cycle. ``bench.profiling`` re-exports the public
face.
"""

from __future__ import annotations

import contextlib
import os

import jax

_override: bool | None = None  # None -> consult the environment


def annotations_enabled() -> bool:
    """Whether :func:`named_span` annotates (checked at trace time)."""
    if _override is not None:
        return _override
    return os.environ.get("MATVEC_ANNOTATE", "0") == "1"


def set_annotations(enabled: bool | None) -> None:
    """Force annotations on/off (None restores the environment default).
    Only programs traced after the change are affected."""
    global _override
    _override = enabled


@contextlib.contextmanager
def annotations(enabled: bool):
    """Scoped :func:`set_annotations` — the test/capture-script form."""
    global _override
    saved = _override
    _override = enabled
    try:
        yield
    finally:
        _override = saved


@contextlib.contextmanager
def named_span(name: str):
    """Annotate the enclosed trace-time region with ``name`` (no-op when
    annotations are disabled). Nests: inner spans extend the name stack
    (``colwise/combine/overlap`` containing ``stage0/compute``)."""
    if not annotations_enabled():
        yield
        return
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield

"""Correlated event timeline: one causally-ordered stream for the stack.

Every serving subsystem already tells its own story — request span trees
(obs/tracing.py), scheduler decision rings (engine/global_scheduler.py),
resilience counters — but in disjoint streams with no shared key, so
"which decision caused this slow request" cannot be answered from the
artifacts. This module is the shared key plus the shared stream:

* **Correlation IDs.** :func:`next_request_id` hands out process-unique
  request ids; :func:`bind_request` binds one to the current thread so
  every event emitted anywhere below the binding (engine dispatch,
  retries, ladder downgrades, breaker transitions fired from inside the
  dispatch) carries it without any call-site plumbing. The engine's
  tracer adopts a bound id for its trace records too, so the span tree
  and the event stream share the key.

* **The hub.** :class:`TimelineHub` is a bounded in-memory ring plus an
  optional JSONL sink plus zero-or-more in-process subscribers (the
  flight recorder). Emission is hot-path-safe by construction: one dict
  build, one GIL-atomic ``deque.append``, one ``SimpleQueue.put`` when a
  sink is attached — no locks, no file handles, no blocking calls
  (obs/sink.py owns all file I/O, same doctrine as request traces).

* **The contract.** Every event carries ``request_id`` (the request it
  belongs to) or ``cause_id`` (the request that *triggered* a background
  action — an eviction forced by another tenant's admission, a breaker
  opened by a failing dispatch). Batch events additionally carry
  ``members`` (the coalesced request ids), which is how a member's
  timeline finds the batch it rode in. ``python -m ...obs timeline``
  reconstructs one request's causal story from these three fields.

Event vocabulary (open — subsystems may add kinds, the renderer is
vocabulary-agnostic): ``submit``, ``bypass``, ``coalesce``, ``retry``,
``degrade``, ``breaker_open``, ``breaker_close``, ``escalate``,
``deadline_failed``, ``dispatch_failed``, ``integrity_refused``,
``solver_diverged``, ``batch_failure``, ``isolated_failure``,
``bisect``, ``admit``, ``reject``, ``interleave``, ``evict``,
``prefetch``, ``reshard``, ``flush``.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable

__all__ = [
    "FAILURE_KINDS",
    "TimelineHub",
    "bind_request",
    "bound_request_id",
    "get_hub",
    "next_request_id",
    "related_events",
    "reset_hub",
]

# The typed-failure kinds: the flight recorder auto-dumps on these, and
# the SLO demo's "one failed request" is found by them.
FAILURE_KINDS = frozenset({
    "breaker_open",
    "solver_diverged",
    "batch_failure",
    "isolated_failure",
    "integrity_refused",
    "deadline_failed",
    "dispatch_failed",
})

# Process-unique request ids: ONE counter for every layer. Schedulers
# allocate at admission; the engine allocates for direct (unscheduled)
# submits; a bare RequestTracer outside an engine falls back to its own
# local numbering, but nothing it emits reaches the hub.
_request_ids = itertools.count(1)

_tls = threading.local()


def next_request_id() -> int:
    """A process-unique correlation id (``itertools.count`` — GIL-atomic,
    safe from any thread)."""
    return next(_request_ids)


def bound_request_id() -> int | None:
    """The request id bound to the current thread, or None."""
    return getattr(_tls, "rid", None)


@contextlib.contextmanager
def bind_request(request_id: int | None):
    """Bind ``request_id`` to the current thread for the duration of the
    block. Everything emitted below the binding — nested dispatches,
    retries, breaker callbacks fired synchronously from inside the
    dispatch — picks the id up via :func:`bound_request_id` without any
    argument threading. Bindings nest (the previous binding is restored
    on exit); binding ``None`` is a no-op passthrough."""
    prev = getattr(_tls, "rid", None)
    _tls.rid = request_id if request_id is not None else prev
    try:
        yield request_id
    finally:
        _tls.rid = prev


class TimelineHub:
    """The unified event stream: bounded ring + optional JSONL sink +
    in-process subscribers.

    ``emit`` is called from dispatch hot paths and from under subsystem
    bookkeeping locks (the global scheduler's eviction listener), so it
    must stay bookkeeping-only: no locks of its own, no I/O, no
    callbacks that could re-enter subsystem locks. Subscribers share
    that contract (the flight recorder's subscriber is one
    ``deque.append`` plus one ``SimpleQueue.put``)."""

    def __init__(
        self,
        capacity: int = 4096,
        *,
        sink=None,
        clock: Callable[[], float] = time.time,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._events: deque[dict] = deque(maxlen=capacity)
        self._sink = sink
        self._clock = clock
        # Copy-on-write subscriber tuple: emit iterates a snapshot, so
        # subscribing never races an in-flight emission.
        self._subscribers: tuple[Callable[[dict], None], ...] = ()
        self._count = itertools.count()
        self._emitted = 0

    def subscribe(self, fn: Callable[[dict], None]) -> None:
        self._subscribers = self._subscribers + (fn,)

    def emit(
        self,
        kind: str,
        *,
        request_id: int | None = None,
        cause_id: int | None = None,
        **fields: Any,
    ) -> dict:
        """Append one event. ``request_id`` defaults to the thread's
        bound id (:func:`bind_request`); background actions pass
        ``cause_id`` instead. Returns the event dict (callers may not
        mutate it after emission — the ring and sink share it)."""
        if request_id is None and cause_id is None:
            request_id = bound_request_id()
        event: dict[str, Any] = {
            "seq": next(self._count),
            "t_s": self._clock(),
            "kind": kind,
        }
        if request_id is not None:
            event["request_id"] = request_id
        if cause_id is not None:
            event["cause_id"] = cause_id
        event.update(fields)
        self._events.append(event)
        self._emitted += 1
        sink = self._sink
        if sink is not None:
            sink.put(event)
        for fn in self._subscribers:
            fn(event)
        return event

    def events(self) -> list[dict]:
        """A snapshot of the ring, oldest first."""
        return list(self._events)

    @property
    def emitted(self) -> int:
        """Total events emitted (the ring bounds memory, not this)."""
        return self._emitted

    def flush(self, timeout: float = 5.0) -> bool:
        """Confirm the sink drained (True when there is no sink)."""
        return self._sink.flush(timeout=timeout) if self._sink else True

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()


def related_events(
    events: Iterable[dict], request_id: int
) -> list[dict]:
    """The causal slice for one request: events carrying the id as
    ``request_id`` or ``cause_id``, batch events listing it in
    ``members``, and — one hop out — events whose ``request_id`` is a
    batch the request was coalesced into (so a member's timeline shows
    the batch's retries/downgrades/failures too)."""
    events = list(events)
    keys = {request_id}
    for ev in events:
        if request_id in ev.get("members", ()):
            if ev.get("request_id") is not None:
                keys.add(ev["request_id"])
            if ev.get("cause_id") is not None:
                keys.add(ev["cause_id"])
    out = []
    for ev in events:
        if (
            ev.get("request_id") in keys
            or ev.get("cause_id") in keys
            or request_id in ev.get("members", ())
        ):
            out.append(ev)
    out.sort(key=lambda ev: (ev.get("t_s", 0.0), ev.get("seq", 0)))
    return out


# ------------------------------------------------------- process default
#
# Same shape as obs.registry.get_registry(): one always-on hub per
# process so subsystems correlate without plumbing, resettable for tests
# and for arming a sink at capture time.

_default_hub: TimelineHub | None = None
_default_lock = threading.Lock()


def get_hub() -> TimelineHub:
    global _default_hub
    with _default_lock:
        if _default_hub is None:
            _default_hub = TimelineHub()
        return _default_hub


def reset_hub(
    capacity: int = 4096, *, sink=None, clock: Callable[[], float] = time.time
) -> TimelineHub:
    """Replace the process hub (tests; capture CLIs arming a sink).
    Closes the previous hub's sink."""
    global _default_hub
    with _default_lock:
        old = _default_hub
        _default_hub = TimelineHub(capacity, sink=sink, clock=clock)
        hub = _default_hub
    if old is not None:
        old.close()  # after release: close joins the sink writer thread
    return hub

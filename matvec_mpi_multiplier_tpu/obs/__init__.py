"""Unified telemetry: metrics registry, request-lifecycle tracing, named
device-trace annotations.

The reference's only instrumentation was the barrier/Wtime protocol and an
append-only CSV (SURVEY.md §5.1/C8) — numbers about the *whole* run, with
no way to see where inside one request the time went. This package adds the
three observability layers a serving system is debugged with:

* **metrics registry** (``registry.py``) — process-local counters, gauges
  and fixed-bucket latency histograms (p50/p95/p99 summaries), exportable
  as a JSON snapshot or Prometheus text. ``EngineStats`` is a view over
  these counters — one source of truth for every count the serve bench
  reports.
* **request-lifecycle tracer** (``tracing.py``) — one structured span tree
  per engine request (submit → backpressure gate → bucket/pad → exec-cache
  lookup → dispatch → materialize) into an in-memory ring buffer, with an
  optional JSONL sink (``sink.py``). The hot path never blocks on I/O:
  recording is a ``deque.append``/``SimpleQueue.put`` (GIL-atomic, no
  locks, no file handles) and all file writes happen on the sink thread —
  the engine's sync-free dispatch lint extends to an I/O lint over this
  package (``tests/test_lint.py``, ``scripts/tier1.sh``).
* **named device-trace annotations** (``annotations.py``) — trace-time
  ``jax.named_scope`` + ``jax.profiler.TraceAnnotation`` spans around each
  strategy's local GEMV, each combine schedule, and each overlap stage
  (``stage{i}/compute`` / ``stage{i}/combine``), so Perfetto captures show
  the staged pipeline structure by name (the GSPMD/``arXiv:2112.09017``
  debugging discipline, PAPERS.md).

``python -m matvec_mpi_multiplier_tpu.obs`` pretty-prints a metrics
snapshot or summarizes a JSONL trace (per-phase breakdown, top-k slowest
requests). Capture recipe: ``docs/OBSERVABILITY.md``.

Dependency-free by design (stdlib + numpy + jax only): the telemetry layer
must be importable everywhere the engine is.
"""

from .annotations import (
    annotations,
    annotations_enabled,
    named_span,
    set_annotations,
)
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    RateEstimator,
    get_registry,
    prometheus_text,
    reset_registry,
)
from .sink import JsonlSink
from .tracing import RequestTracer, Span

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "RateEstimator",
    "get_registry",
    "prometheus_text",
    "reset_registry",
    "RequestTracer",
    "Span",
    "JsonlSink",
    "named_span",
    "annotations",
    "annotations_enabled",
    "set_annotations",
]

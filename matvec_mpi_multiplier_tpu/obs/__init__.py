"""Unified telemetry: metrics registry, request-lifecycle tracing, named
device-trace annotations.

The reference's only instrumentation was the barrier/Wtime protocol and an
append-only CSV (SURVEY.md §5.1/C8) — numbers about the *whole* run, with
no way to see where inside one request the time went. This package adds the
three observability layers a serving system is debugged with:

* **metrics registry** (``registry.py``) — process-local counters, gauges
  and fixed-bucket latency histograms (p50/p95/p99 summaries), exportable
  as a JSON snapshot or Prometheus text. ``EngineStats`` is a view over
  these counters — one source of truth for every count the serve bench
  reports.
* **request-lifecycle tracer** (``tracing.py``) — one structured span tree
  per engine request (submit → backpressure gate → bucket/pad → exec-cache
  lookup → dispatch → materialize) into an in-memory ring buffer, with an
  optional JSONL sink (``sink.py``). The hot path never blocks on I/O:
  recording is a ``deque.append``/``SimpleQueue.put`` (GIL-atomic, no
  locks, no file handles) and all file writes happen on the sink thread —
  the engine's sync-free dispatch lint extends to an I/O lint over this
  package (``tests/test_lint.py``, ``scripts/tier1.sh``).
* **named device-trace annotations** (``annotations.py``) — trace-time
  ``jax.named_scope`` + ``jax.profiler.TraceAnnotation`` spans around each
  strategy's local GEMV, each combine schedule, and each overlap stage
  (``stage{i}/compute`` / ``stage{i}/combine``), so Perfetto captures show
  the staged pipeline structure by name (the GSPMD/``arXiv:2112.09017``
  debugging discipline, PAPERS.md).

Three control-plane layers ride on those (docs/OBSERVABILITY.md):

* **correlated event timeline** (``timeline.py``) — one causally-ordered
  event stream across engine, schedulers, registry, and resilience, with
  ``request_id``/``cause_id`` correlation threaded via a thread-local
  binding (``bind_request``) so every JSONL line answers "which request
  caused this";
* **SLO burn-rate engine** (``slo.py``) — declarative targets evaluated
  from the registry with multi-window (5m/1h + 1h/6h) burn-rate
  alerting, exported as ``slo_*`` gauges and ``engine.health()["slo"]``;
* **flight recorder** (``flight.py``) — always-on bounded black box
  (last N events + metric snapshots) auto-dumping a post-mortem bundle
  on typed failures.

``python -m matvec_mpi_multiplier_tpu.obs`` pretty-prints a metrics
snapshot (``--watch`` refreshes), summarizes a JSONL trace (per-phase
breakdown, top-k slowest requests), reconstructs one request's causal
story (``timeline``), renders an SLO evaluation (``slo``), and renders a
flight-recorder bundle (``dump``). Capture recipe:
``docs/OBSERVABILITY.md``.

Dependency-free by design (stdlib + numpy + jax only): the telemetry layer
must be importable everywhere the engine is.
"""

from .annotations import (
    annotations,
    annotations_enabled,
    named_span,
    set_annotations,
)
from .flight import FlightRecorder
from .registry import (
    Counter,
    EwmaGauge,
    Gauge,
    Histogram,
    MetricsRegistry,
    RateEstimator,
    get_registry,
    label,
    prometheus_text,
    reset_registry,
)
from .sink import JsonlSink
from .slo import DEFAULT_TARGETS, ENGINE_TARGETS, SloMonitor, SloTarget
from .timeline import (
    FAILURE_KINDS,
    TimelineHub,
    bind_request,
    bound_request_id,
    get_hub,
    next_request_id,
    related_events,
    reset_hub,
)
from .tracing import RequestTracer, Span

__all__ = [
    "Counter",
    "EwmaGauge",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "RateEstimator",
    "get_registry",
    "label",
    "prometheus_text",
    "reset_registry",
    "RequestTracer",
    "Span",
    "JsonlSink",
    "FAILURE_KINDS",
    "TimelineHub",
    "bind_request",
    "bound_request_id",
    "get_hub",
    "next_request_id",
    "related_events",
    "reset_hub",
    "DEFAULT_TARGETS",
    "ENGINE_TARGETS",
    "SloMonitor",
    "SloTarget",
    "FlightRecorder",
    "named_span",
    "annotations",
    "annotations_enabled",
    "set_annotations",
]

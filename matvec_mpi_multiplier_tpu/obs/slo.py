"""Declarative SLO targets with multi-window burn-rate alerting.

An SLO is a promise over a window ("99.9% of offered requests succeed"),
and the operational question is never "what is the instantaneous error
rate" but "how fast is the error *budget* burning". This module
evaluates declared targets from an existing
:class:`~.registry.MetricsRegistry` — no new instrumentation, the
counters and gauges the stack already maintains ARE the SLIs — using
the standard SRE multi-window, multi-burn-rate recipe:

* **burn rate** = (window error fraction) / (budget fraction). Burn 1.0
  consumes exactly the budget over the SLO period; burn 14.4 over 5
  minutes consumes a 30-day 99.9% budget in ~2 hours.
* **page** when the fast pair breaches: burn > 14.4 on BOTH the 5 m and
  1 h windows (the long window filters blips, the short window resets
  the alert promptly once the incident ends);
* **ticket** when the slow pair breaches: burn > 6 on BOTH 1 h and 6 h.

Two target kinds cover the declared SLOs:

* ``availability`` — ratio of summed *bad* counters to summed *total*
  counters, windowed by cumulative-sample deltas;
* ``threshold`` — a gauge or histogram percentile compared to a bound
  (e2e p99, escalation rate, cost-model divergence); its window error
  fraction is the fraction of samples in the window observed in breach,
  so the same burn algebra applies with a declared time-in-breach
  budget.

The monitor is sampling-based over an injectable clock: ``sample()``
records one cumulative observation, ``evaluate()`` answers from the
retained samples and exports ``slo_*`` gauges back into the registry
(bounded cardinality — the declared target names). Nothing here touches
the dispatch hot path: sampling/evaluation run from ``health()``, the
serve bench, the flight recorder's snapshot thread, or a CLI.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable

from .registry import MetricsRegistry

__all__ = [
    "ALERT_POLICIES",
    "DEFAULT_TARGETS",
    "ENGINE_TARGETS",
    "SloMonitor",
    "SloTarget",
    "WINDOWS_S",
]

# The evaluation windows, by display name. 5m/1h is the fast (paging)
# pair, 1h/6h the slow (ticket) pair.
WINDOWS_S = {"5m": 300.0, "1h": 3600.0, "6h": 21600.0}

# (severity, short window, long window, burn threshold): an alert fires
# when burn exceeds the threshold on BOTH windows of its pair.
ALERT_POLICIES = (
    ("page", "5m", "1h", 14.4),
    ("ticket", "1h", "6h", 6.0),
)


@dataclasses.dataclass(frozen=True)
class SloTarget:
    """One declared objective, evaluated from registry names.

    ``availability`` kind: ``objective`` is the success-ratio promise
    (0.999), ``total``/``bad`` name the counters to sum for the
    denominator/numerator, and the budget fraction is ``1 -
    objective``. ``threshold`` kind: ``source`` names a gauge (or a
    histogram, with ``percentile``) compared against ``objective`` as
    an upper bound, and ``budget`` is the allowed fraction of time in
    breach."""

    name: str
    kind: str                       # "availability" | "threshold"
    objective: float
    total: tuple[str, ...] = ()     # availability: offered-request counters
    bad: tuple[str, ...] = ()       # availability: failed-request counters
    source: str | None = None       # threshold: gauge or histogram name
    percentile: int | None = None   # threshold: histogram percentile (50/95/99)
    budget: float | None = None     # threshold: allowed breach-time fraction
    description: str = ""

    def __post_init__(self):
        if self.kind not in ("availability", "threshold"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if self.kind == "availability":
            if not (0.0 < self.objective < 1.0):
                raise ValueError(
                    f"availability objective must be in (0, 1), got "
                    f"{self.objective}"
                )
            if not self.total or not self.bad:
                raise ValueError(
                    f"availability SLO {self.name!r} needs total and bad "
                    "counter names"
                )
        else:
            if self.source is None:
                raise ValueError(
                    f"threshold SLO {self.name!r} needs a source metric"
                )

    @property
    def budget_fraction(self) -> float:
        if self.kind == "availability":
            return 1.0 - self.objective
        return self.budget if self.budget is not None else 0.05


# The serve-capture targets (the chaos/demo vocabulary: the steady-phase
# offered/failed counters are the availability SLI by the same doctrine
# as the obs `resilience` panel).
DEFAULT_TARGETS = (
    SloTarget(
        name="availability", kind="availability", objective=0.999,
        total=("serve_requests_total",),
        bad=("serve_failed_requests_total",),
        description="steady-phase requests that materialized",
    ),
    SloTarget(
        name="e2e_p99_ms", kind="threshold", objective=50.0,
        source="serve_e2e_latency_ms", percentile=99, budget=0.05,
        description="steady-phase e2e p99 under the declared bound",
    ),
    SloTarget(
        name="escalation_rate", kind="threshold", objective=0.05,
        source="engine_escalation_rate", budget=0.05,
        description="speculative-tier escalation EWMA under the "
                    "acceptance bound",
    ),
    SloTarget(
        name="cost_model_divergence", kind="threshold", objective=1.0,
        source="tuning_cost_model_divergence", budget=0.05,
        description="cost-model |log10(predicted/measured)| EWMA under "
                    "one decade",
    ),
)

# The engine-local targets (``engine.health()["slo"]``): same promises
# against the engine's own failure counters — no serve bench required.
# Engine-local targets carry an engine_ prefix: an engine's registry is
# often the serve bench's registry too, and the exported slo_<name>_*
# gauges share that one namespace — same-named targets in two monitors
# would overwrite each other's verdicts.
ENGINE_TARGETS = (
    SloTarget(
        name="engine_availability", kind="availability", objective=0.999,
        total=("engine_requests_total",),
        bad=(
            "engine_dispatch_failures_total",
            "engine_integrity_failures_total",
            "engine_deadline_failures_total",
        ),
        description="submitted requests that dispatched and materialized",
    ),
    SloTarget(
        name="engine_escalation_rate", kind="threshold", objective=0.05,
        source="engine_escalation_rate", budget=0.05,
        description="speculative-tier escalation EWMA under the "
                    "acceptance bound",
    ),
)


class SloMonitor:
    """Sample-and-evaluate burn-rate engine over one registry.

    ``sample()`` reads the registry once and retains (t, cumulative
    counters, instantaneous values); ``evaluate()`` computes per-window
    error fractions and burn rates from the retained ring, fires the
    multi-window alert policies, and exports ``slo_<name>_burn_<w>`` /
    ``slo_<name>_alert`` gauges (0 ok / 1 ticket / 2 page / -1 no
    data). The clock is injectable so hours of burn history are
    testable (and demo-capturable) in milliseconds."""

    def __init__(
        self,
        registry: MetricsRegistry,
        targets: tuple[SloTarget, ...] = DEFAULT_TARGETS,
        *,
        clock: Callable[[], float] = time.time,
        capacity: int = 4096,
    ):
        names = [t.name for t in targets]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO target names: {names}")
        self.registry = registry
        self.targets = tuple(targets)
        self._clock = clock
        self._samples: deque[dict] = deque(maxlen=capacity)
        # Gauge handles up front: declared target names x fixed windows
        # is bounded by construction, and evaluate() then touches no
        # registry locks beyond the per-gauge sets.
        self._g_burn = {
            (t.name, w): registry.gauge(  # cardinality-ok: label source is the declared SLO target list x the three fixed windows — bounded at construction, nothing per-request
                f"slo_{t.name}_burn_{w}",
                f"error-budget burn rate of {t.name} over {w}",
            )
            for t in self.targets for w in WINDOWS_S
        }
        self._g_alert = {
            t.name: registry.gauge(  # cardinality-ok: one gauge per declared SLO target — bounded at construction
                f"slo_{t.name}_alert",
                f"alert state of {t.name}: 0 ok, 1 ticket, 2 page, "
                "-1 no data",
            )
            for t in self.targets
        }

    # ------------------------------------------------------------ sampling

    def sample(self, now: float | None = None) -> dict:
        """Record one observation of every target's SLI sources."""
        if now is None:
            now = self._clock()
        snap = self.registry.snapshot()
        counters = snap.get("counters", {})
        gauges = snap.get("gauges", {})
        hists = snap.get("histograms", {})
        record: dict = {"t": now, "counters": {}, "values": {}}
        for t in self.targets:
            if t.kind == "availability":
                for name in t.total + t.bad:
                    record["counters"][name] = counters.get(name, 0)
            else:
                record["values"][t.name] = self._read_value(
                    t, gauges, hists
                )
        self._samples.append(record)
        return record

    @staticmethod
    def _read_value(t: SloTarget, gauges: dict, hists: dict):
        if t.source in gauges:
            return gauges[t.source]
        summ = hists.get(t.source)
        if summ is not None:
            q = t.percentile if t.percentile is not None else 99
            v = summ.get(f"p{q}")
            # An empty histogram reports NaN percentiles: no evidence.
            if v is not None and v == v:
                return v
        return None

    # ---------------------------------------------------------- evaluation

    def _window_samples(self, now: float, window_s: float) -> list[dict]:
        return [s for s in self._samples if s["t"] > now - window_s]

    def _baseline(self, now: float, window_s: float) -> dict | None:
        """The cumulative-counter baseline for a window: the newest
        sample at or before the window start, else the oldest retained
        sample (a partial window reads as the traffic it saw)."""
        base = None
        for s in self._samples:
            if s["t"] <= now - window_s:
                base = s
            else:
                break
        if base is None and self._samples:
            base = self._samples[0]
        return base

    def _window_error(
        self, t: SloTarget, now: float, window_s: float
    ) -> float | None:
        """The error fraction of one target over one window, or None
        when the window holds no evidence."""
        if t.kind == "availability":
            base = self._baseline(now, window_s)
            if base is None or not self._samples:
                return None
            cur = self._samples[-1]["counters"]
            ref = base["counters"]
            total = sum(
                cur.get(n, 0) - ref.get(n, 0) for n in t.total
            )
            if total <= 0:
                return None
            bad = sum(cur.get(n, 0) - ref.get(n, 0) for n in t.bad)
            return min(1.0, max(0.0, bad / total))
        window = self._window_samples(now, window_s)
        flags = [
            float(s["values"][t.name] > t.objective)
            for s in window
            if s["values"].get(t.name) is not None
        ]
        if not flags:
            return None
        return sum(flags) / len(flags)

    def evaluate(self, now: float | None = None) -> dict:
        """Burn rates, alert states, and gauge export — the
        ``engine.health()["slo"]`` block, the ``obs slo`` panel's JSON,
        and the demo capture's ``slo.json``."""
        if now is None:
            now = self._clock()
        targets: dict[str, dict] = {}
        fired: list[dict] = []
        for t in self.targets:
            budget = t.budget_fraction
            errors: dict[str, float | None] = {}
            burn: dict[str, float | None] = {}
            for w, span in WINDOWS_S.items():
                err = self._window_error(t, now, span)
                errors[w] = err
                burn[w] = None if err is None else err / budget
            alerts = []
            for severity, short, long_, threshold in ALERT_POLICIES:
                bs, bl = burn[short], burn[long_]
                if bs is not None and bl is not None and (
                    bs > threshold and bl > threshold
                ):
                    alerts.append({
                        "slo": t.name,
                        "severity": severity,
                        "short": short,
                        "long": long_,
                        "burn_short": bs,
                        "burn_long": bl,
                        "threshold": threshold,
                    })
            if all(b is None for b in burn.values()):
                status, level = "no_data", -1.0
            elif any(a["severity"] == "page" for a in alerts):
                status, level = "page", 2.0
            elif alerts:
                status, level = "ticket", 1.0
            else:
                status, level = "ok", 0.0
            current = None
            if t.kind == "threshold" and self._samples:
                current = self._samples[-1]["values"].get(t.name)
            targets[t.name] = {
                "kind": t.kind,
                "objective": t.objective,
                "budget": budget,
                "description": t.description,
                "value": current,
                "errors": errors,
                "burn": burn,
                "status": status,
                "alerts": alerts,
            }
            fired.extend(alerts)
            for w in WINDOWS_S:
                self._g_burn[(t.name, w)].set(
                    burn[w] if burn[w] is not None else 0.0
                )
            self._g_alert[t.name].set(level)
        return {"t_s": now, "targets": targets, "alerts": fired}

"""JSONL trace sink: the one place obs does blocking file I/O.

The tracer's hot-path contract (``tracing.py``) is that emitting a record
never blocks on the filesystem; this module is the other half of that
contract — a daemon thread draining a ``SimpleQueue`` into an append-mode
JSONL file. The I/O lint (``tests/test_lint.py``, ``scripts/tier1.sh``)
forbids ``open``/``json.dump``/``.write`` everywhere else on the engine
dispatch path and exempts exactly this module.

``flush()`` uses an in-band marker (an ``Event`` queued behind every
pending record) so a caller can deterministically wait for the file to be
complete — the serve bench flushes before reporting the trace path, and
tests flush before reading the file back.
"""

from __future__ import annotations

import json
import os
import queue
import threading
from pathlib import Path

_CLOSE = object()


class JsonlSink:
    """Background JSONL writer. ``put`` is the hot-path face: one
    ``SimpleQueue.put`` (no lock acquisition in CPython), nothing else."""

    def __init__(self, path: str | os.PathLike):
        self.path = Path(path)
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="obs-jsonl-sink"
        )
        self._thread.start()

    def put(self, record: dict) -> None:
        self._q.put(record)

    def _run(self) -> None:
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            f = open(self.path, "a")
        except OSError:
            # Unwritable destination: exit cleanly — the thread's death is
            # the signal (flush() returns False; callers surface it). A
            # noisy daemon-thread traceback would land mid-serve-output.
            return
        with f:
            while True:
                item = self._q.get()
                if item is _CLOSE:
                    return
                if isinstance(item, threading.Event):
                    f.flush()
                    item.set()
                    continue
                f.write(json.dumps(item) + "\n")

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait until every record queued before this call is on disk.
        Returns False on timeout (dead sink thread)."""
        if not self._thread.is_alive():
            return False
        marker = threading.Event()
        self._q.put(marker)
        return marker.wait(timeout)

    def close(self, timeout: float = 5.0) -> None:
        self._q.put(_CLOSE)
        self._thread.join(timeout)


def dump_json(path: str | os.PathLike, payload: dict) -> Path:
    """Synchronous JSON dump for the flight recorder's writer thread and
    the CLIs — kept here so the I/O lint's 'all blocking file I/O lives
    in obs/sink.py' contract stays literally true (flight.py itself
    never opens a file)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return path
